"""Cost-model self-calibration: per-worker online estimators that feed
MEASURED prefill throughput, queue wait, and handoff bandwidth back into
``decide_kv_route`` in place of the four static priors (round 20,
ROADMAP item 3 — "the cost model still prices migration off four static
guesses").

Three sources, all already on the wire:

- **Flight traces** (``server/flight_recorder.py``): a worker's ``done``
  wire carries the batcher's ``enqueued`` → ``admitted`` → ``first_token``
  events. admitted−enqueued is the request's real queue wait; the
  ``admitted`` event's ``tokens`` attr over first_token−admitted is the
  real prefill tok/s. Ingest dedups per (trace, worker) — the flight ring
  re-delivers wires, the estimator must not double-count.
- **Worker kv_migrate wire counters** (``engines/llm.py``): cumulative
  per-tier ``pull_bytes_<tier>`` / ``pull_ms_<tier>`` ride the heartbeat;
  deltas of the pair give measured pull bandwidth per (worker, tier).
  Delta-anchored exactly like the PD metrics: a counter that went
  BACKWARD means the worker restarted — re-anchor, never emit a negative.

Estimators are EMA + outlier clamp: once warm (>= min_samples), a sample
further than ``calibrate_clamp``x from the running value is clamped
before blending, so one GC pause or one cold-cache pull cannot poison
the estimate. Each also tracks ``err_ema`` — the EMA of the relative
error between the value it WOULD have predicted and the sample that
arrived — which is the published ``predicted_vs_measured`` number the
bench asserts falls round-over-round.

Everything here is advisory and read-locked behind
``RoutingConfig.calibrate``: ingestion always runs (the /admin/routing
snapshot shows what calibration WOULD use), but no placement decision
reads a learned value while the flag is off — byte-identical routing is
the A/B contract.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from .prefix_routing import MIGRATE_TIER_COST, RoutingConfig


class Estimator:
    """One EMA-with-clamp online estimator (value + sample count +
    relative-error EMA). Not thread-safe on its own — owners lock."""

    __slots__ = ("alpha", "clamp", "min_samples", "value", "n", "err_ema")

    def __init__(self, *, alpha: float = 0.3, clamp: float = 5.0,
                 min_samples: int = 3) -> None:
        self.alpha = min(1.0, max(0.0, alpha))
        self.clamp = max(1.0, clamp)
        self.min_samples = max(1, min_samples)
        self.value = 0.0
        self.n = 0
        self.err_ema: Optional[float] = None

    def observe(self, sample: float) -> None:
        if not (sample == sample) or sample in (float("inf"),
                                                float("-inf")):
            return  # NaN/inf: a degenerate measurement never lands
        if self.n == 0:
            self.value = float(sample)
            self.n = 1
            return
        # predicted-vs-measured BEFORE this sample updates the value —
        # the convergence signal the bench publishes
        err = abs(sample - self.value) / max(abs(sample), abs(self.value),
                                             1e-9)
        self.err_ema = (err if self.err_ema is None
                        else self.err_ema + self.alpha * (err - self.err_ema))
        s = float(sample)
        if self.n >= self.min_samples and self.value > 0.0:
            lo, hi = self.value / self.clamp, self.value * self.clamp
            s = min(max(s, lo), hi)
        self.value += self.alpha * (s - self.value)
        self.n += 1

    @property
    def warm(self) -> bool:
        return self.n >= self.min_samples

    def get(self) -> Optional[float]:
        """The calibrated value, or None below min_samples (caller keeps
        the static prior — never steer off one lucky measurement)."""
        return self.value if self.warm else None

    def snapshot(self) -> Dict[str, Any]:
        return {
            "value": round(self.value, 6),
            "samples": self.n,
            "warm": self.warm,
            "err_ema": (None if self.err_ema is None
                        else round(self.err_ema, 6)),
        }


class CostCalibration:
    """Per-worker estimator bank + the delta anchors for the cumulative
    wire counters. Thread-safe (heartbeats and discovery race)."""

    # bound per-process growth under worker-id churn
    _MAX_WORKERS = 512
    # (trace_id, worker_id) dedup ring: flight wires re-deliver
    _MAX_SEEN = 4096

    def __init__(self, cfg: RoutingConfig) -> None:
        self.cfg = cfg
        self._lock = threading.Lock()
        # worker_id -> estimator
        self._prefill: Dict[str, Estimator] = {}
        self._queue: Dict[str, Estimator] = {}
        # (worker_id, tier) -> estimator (bytes/s)
        self._bw: Dict[Tuple[str, str], Estimator] = {}
        # (worker_id, tier) -> (prev_bytes, prev_ms) cumulative anchors
        self._bw_prev: Dict[Tuple[str, str], Tuple[float, float]] = {}
        self._seen: set = set()
        self._seen_ring: Deque[Tuple[str, str]] = deque()

    def _estimator(self) -> Estimator:
        return Estimator(alpha=self.cfg.calibrate_alpha,
                         clamp=self.cfg.calibrate_clamp,
                         min_samples=self.cfg.calibrate_min_samples)

    def _get(self, table: Dict, key) -> Estimator:
        est = table.get(key)
        if est is None:
            if len(table) >= self._MAX_WORKERS:
                # arbitrary-but-bounded eviction; churned ids re-learn
                table.pop(next(iter(table)))
            est = table[key] = self._estimator()
        return est

    # -- ingest: flight traces ----------------------------------------------

    def ingest_trace(self, worker_id: str, trace_id: str,
                     events: Sequence[Tuple[str, float, Dict[str, Any]]]
                     ) -> bool:
        """Feed one worker's completed flight wire. Extracts queue wait
        (admitted − enqueued) and prefill tok/s (admitted ``tokens`` attr
        over first_token − admitted). Idempotent per (trace, worker).
        Returns True when a sample landed (tests use it)."""
        key = (str(trace_id), str(worker_id))
        with self._lock:
            if key in self._seen:
                return False
            self._seen.add(key)
            self._seen_ring.append(key)
            while len(self._seen_ring) > self._MAX_SEEN:
                self._seen.discard(self._seen_ring.popleft())
            enq = adm = ftk = None
            tokens = 0
            for name, ts, attrs in events:
                if name == "batcher.enqueued" and enq is None:
                    enq = ts
                elif name == "batcher.admitted" and adm is None:
                    adm = ts
                    try:
                        tokens = int((attrs or {}).get("tokens") or 0)
                    except (TypeError, ValueError):
                        tokens = 0
                elif name == "batcher.first_token" and ftk is None:
                    ftk = ts
            landed = False
            if enq is not None and adm is not None and adm >= enq:
                self._get(self._queue, worker_id).observe(adm - enq)
                landed = True
            if (adm is not None and ftk is not None and ftk > adm
                    and tokens > 0):
                self._get(self._prefill, worker_id).observe(
                    tokens / (ftk - adm))
                landed = True
            return landed

    # -- ingest: kv_migrate wire counters -----------------------------------

    def ingest_kv_migrate(self, worker_id: str,
                          stats: Dict[str, Any]) -> None:
        """Feed one heartbeat's cumulative kv_migrate engine stats. The
        puller reports per-tier ``pull_bytes_<tier>`` / ``pull_ms_<tier>``;
        a matched positive delta pair gives one bandwidth sample for
        (worker, tier). Counter regression (restart) re-anchors."""
        if not isinstance(stats, dict):
            return
        with self._lock:
            for tier in MIGRATE_TIER_COST:
                try:
                    cur_b = float(stats.get(f"pull_bytes_{tier}") or 0)
                    cur_ms = float(stats.get(f"pull_ms_{tier}") or 0)
                except (TypeError, ValueError):
                    continue
                if cur_b <= 0 and cur_ms <= 0:
                    continue
                key = (worker_id, tier)
                prev_b, prev_ms = self._bw_prev.get(key, (0.0, 0.0))
                db, dms = cur_b - prev_b, cur_ms - prev_ms
                self._bw_prev[key] = (cur_b, cur_ms)
                if db <= 0 or dms <= 0:
                    continue   # regression = restart re-anchor, or no pull
                self._get(self._bw, key).observe(db / (dms / 1000.0))

    # -- decide-time reads (None → caller keeps the prior) -------------------

    def prefill_tps(self, worker_id: str) -> Optional[float]:
        if not self.cfg.calibrate:
            return None
        with self._lock:
            est = self._prefill.get(worker_id)
            return est.get() if est is not None else None

    def queue_wait_s(self, worker_id: str) -> Optional[float]:
        if not self.cfg.calibrate:
            return None
        with self._lock:
            est = self._queue.get(worker_id)
            return est.get() if est is not None else None

    def bandwidth(self, worker_id: Optional[str],
                  tier: str) -> Optional[float]:
        """Measured pull bandwidth for (source worker, tier). The tier
        cost multiplier stays applied by ``decide_kv_route`` — the
        estimator already folds it in per-tier, so we divide it back out
        to return the cfg-equivalent base bandwidth."""
        if not self.cfg.calibrate or worker_id is None:
            return None
        with self._lock:
            est = self._bw.get((worker_id, tier))
            if est is None or not est.warm:
                return None
            # decide_kv_route divides by bw then multiplies by tier cost;
            # our samples measured the tier-inclusive effective rate
            return est.value * MIGRATE_TIER_COST.get(tier, 1.0)

    # -- admin surface -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Live values + predicted_vs_measured error for /admin/routing."""
        with self._lock:
            workers: Dict[str, Dict[str, Any]] = {}
            for wid, est in self._prefill.items():
                workers.setdefault(wid, {})["prefill_tokens_per_s"] = \
                    est.snapshot()
            for wid, est in self._queue.items():
                workers.setdefault(wid, {})["queue_wait_s"] = est.snapshot()
            for (wid, tier), est in self._bw.items():
                workers.setdefault(wid, {}).setdefault(
                    "bandwidth_bytes_per_s", {})[tier] = est.snapshot()
            errs = [est.err_ema
                    for table in (self._prefill, self._queue)
                    for est in table.values() if est.err_ema is not None]
            errs += [e.err_ema for e in self._bw.values()
                     if e.err_ema is not None]
            return {
                "active": bool(self.cfg.calibrate),
                "workers": workers,
                "predicted_vs_measured": (
                    round(sum(errs) / len(errs), 6) if errs else None),
            }

    def reset(self) -> None:
        """Freeze back to priors: drop every learned value AND the delta
        anchors (the next cumulative reading re-anchors cleanly). The
        admin PUT ``calibrate_reset`` action — the A/B switch's hard
        half."""
        with self._lock:
            self._prefill.clear()
            self._queue.clear()
            self._bw.clear()
            self._bw_prev.clear()
            self._seen.clear()
            self._seen_ring.clear()


class MigrateHintTracker:
    """Counts the migrate/replicate pulls the plane has recently steered
    at each worker, so ``decide_kv_route`` can price a target that is
    already mid-budget (satellite fix: without this, every request in a
    burst races to the same 'idle' exporter). Entries expire after
    ``migrate_hint_window_s`` — a pull is presumed resolved by then
    (done, fallen back, or abandoned); the worker's own budget/backoff
    remains the hard limit either way."""

    _MAX_WORKERS = 512

    def __init__(self, cfg: RoutingConfig) -> None:
        self.cfg = cfg
        self._lock = threading.Lock()
        self._hints: Dict[str, Deque[float]] = {}

    def note(self, worker_id: str, now: Optional[float] = None) -> None:
        """The plane just handed out a hint whose PULLER is worker_id."""
        now = time.time() if now is None else now
        with self._lock:
            dq = self._hints.get(worker_id)
            if dq is None:
                if len(self._hints) >= self._MAX_WORKERS:
                    self._hints.pop(next(iter(self._hints)))
                dq = self._hints[worker_id] = deque()
            dq.append(now)

    def inflight(self, worker_id: str,
                 now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        cutoff = now - max(0.1, self.cfg.migrate_hint_window_s)
        with self._lock:
            dq = self._hints.get(worker_id)
            if not dq:
                return 0
            while dq and dq[0] < cutoff:
                dq.popleft()
            if not dq:
                del self._hints[worker_id]
                return 0
            return len(dq)
