"""Control-plane orchestration of prefill/decode-disaggregated jobs.

This is the wiring the reference never built: its ``pd_scheduler.py`` is a
standalone service no API route consults (SURVEY C30 "standalone; not wired
into C24/C25"), and its KV migration is a 50 ms sleep
(``server/app/services/pd_scheduler.py:462-472``). Here a job submitted with
``params.pd_disaggregated`` flows end to end through the REAL pieces:

1. **Placement** — role-tagged registered workers (store ``role`` column,
   ``WorkerRole`` in C1) are mirrored into :class:`PrefillDecodeScheduler`
   capabilities (topology-derived TFLOPs/bandwidth) and the request is
   placed on a prefill worker and a decode worker at submission.
2. **Prefill stage** — a child job pinned to the prefill worker
   (``params.target_worker``; the store's claim loop enforces the pin). The
   worker's LLM engine prefills, samples the first token (TTFT), exports the
   sequence's KV pages (``runtime/kv_handoff.py``), and POSTs the serialized
   handoff DIRECTLY to the decode worker's data plane (``/kv/transfer``,
   the HTTP twin of grpc TransferKVCache) — KV bytes never pass through the
   control plane.
3. **Decode stage** — a second child pinned to the decode worker, which
   resumes the adopted slot and streams the rest of the generation
   (bit-exact greedy continuation — the kv_handoff invariant).
4. **Merge** — the parent job completes with the full token stream plus
   end-to-end TTFT and real migration bytes/ms in the result.

Parent jobs are created RUNNING (never claimable); children carry
``pd_parent`` and the flow advances in the ``complete_job`` hook.
"""

from __future__ import annotations

import asyncio
import random
import time
import uuid
from typing import Any, Dict, Optional

from ..utils.data_structures import TpuTopology, WorkerRole
from .pd_scheduler import PDRequest, PrefillDecodeScheduler, WorkerCapability
from .store import Store


class PDFlowError(RuntimeError):
    pass


class PDFlowService:
    """Drives pd-disaggregated jobs through prefill → handoff → decode,
    with a re-prefill fallback: a failed stage (prefill worker died
    mid-transfer, decode worker died after adoption, handoff lost or
    corrupted) re-places the WHOLE flow — prompt prefilled again on a
    surviving worker, failed workers excluded — up to ``max_reprefills``
    times, WITHOUT burning the parent job's own retry budget (stage
    children carry their own ``retry_count``; the flow's attempt counter
    is independent of both)."""

    # re-prefill budget per flow: attempts 0..max_reprefills (the prompt
    # is recomputed from scratch each time, so this bounds wasted FLOPs,
    # not correctness — greedy outputs are identical on any attempt)
    MAX_REPREFILLS = 3
    # jittered exponential backoff BETWEEN attempts
    # (``U(0.5, 1.5)·base·2^(attempt-1)``): a handoff-partition window
    # lasting a couple of seconds must not eat the whole budget in its
    # first 200 ms — attempts spread past the outage instead. 0 disables
    # (immediate, synchronous re-placement — deterministic tests).
    REPREFILL_BACKOFF_S = 0.5

    def __init__(self, store: Store,
                 scheduler: Optional[PrefillDecodeScheduler] = None,
                 metrics: Optional[Any] = None,
                 max_reprefills: int = MAX_REPREFILLS,
                 reprefill_backoff_s: float = REPREFILL_BACKOFF_S) -> None:
        self.store = store
        self.scheduler = scheduler or PrefillDecodeScheduler()
        self.metrics = metrics
        self.max_reprefills = max_reprefills
        self.reprefill_backoff_s = reprefill_backoff_s
        # predictive rebalance (round 20): optional
        # ``server.autoscaler.PredictiveRebalancer`` ticked on every
        # placement sync — None (default) keeps the reactive-only build
        self.rebalancer: Optional[Any] = None
        # request_id → PDRequest (placement state released on completion)
        self._live: Dict[str, PDRequest] = {}
        # in-flight delayed re-placement tasks (strong refs)
        self._bg: set = set()
        self.stats = {"submitted": 0, "completed": 0, "failed": 0,
                      "reprefills": 0, "stale_stage_results": 0}

    # ---------------------------------------------------------------- sync

    async def _sync_workers(self) -> None:
        """Mirror role-tagged, live workers into scheduler capabilities."""
        rows = await self.store.list_workers(status=("idle", "online", "busy"))
        seen = set()
        for w in rows:
            if "llm" not in (w.get("supported_types") or []):
                continue
            topo = TpuTopology.from_dict(w["topology"]) if w.get("topology") \
                else TpuTopology()
            role = WorkerRole(w.get("role") or "hybrid")
            cap = WorkerCapability.from_topology(w["id"], topo, role=role)
            # refresh IN PLACE for live workers (register_worker would
            # zero active_prefill/active_decode for live placements,
            # unbinding the batch caps) — and a predictive preflip must
            # survive the refresh, so the scheduler owns the merge
            self.scheduler.refresh_worker(cap)
            seen.add(w["id"])
        for wid in [w.cap.worker_id for w in
                    self.scheduler._workers.values()]:
            if wid not in seen:
                self.scheduler.remove_worker(wid)
        if self.rebalancer is not None:
            # predictive rebalance rides the placement sync: the
            # projection is re-read against fresh capabilities, preflips
            # restore once it recovers. Advisory — a rebalancer failure
            # never blocks a placement.
            try:
                self.rebalancer.tick()
            except Exception:  # noqa: BLE001
                pass
        if self.metrics is not None:
            # pd_fleet_balance{role}: free capacity per side, refreshed on
            # every placement pass — a side pinned at 0 while the other
            # has headroom is the brownout rebalance absorbs
            self.metrics.record_pd_fleet_balance(
                self.scheduler.capacity_by_role()
            )

    # -------------------------------------------------------------- submit

    async def on_parent_terminal(self, parent_id: str) -> None:
        """A parent went terminal outside the normal child-completion path
        (cancellation, sweep timeout, permanent child failure): release the
        placement state and cancel any still-queued stage children — a
        pinned child of a dead container would otherwise sit QUEUED forever
        (nothing else may claim it) and pin scheduler capacity."""
        self._finish(parent_id, ok=False)
        await self._cancel_queued_children(parent_id)

    @staticmethod
    def _child_id(parent_id: str, stage: str, attempt: int) -> str:
        """Deterministic stage-child id per re-prefill attempt — attempt 0
        keeps the legacy un-suffixed id (restart compatibility), retries
        append ``-rN`` so a stale attempt's children never collide with
        the live attempt's."""
        base = f"{parent_id}-{stage}"
        return base if attempt <= 0 else f"{base}-r{attempt}"

    async def _cancel_queued_children(self, parent_id: str) -> None:
        for stage in ("prefill", "decode"):
            for attempt in range(self.max_reprefills + 1):
                child_id = self._child_id(parent_id, stage, attempt)
                child = await self.store.get_job(child_id)
                if child is not None and child["status"] == "queued":
                    # conditional transition: a pinned worker may claim/
                    # finish the child between the read and this write,
                    # and a terminal status must never be clobbered back
                    # to CANCELLED
                    await self.store.try_transition_job(
                        child_id, "queued", status="cancelled",
                        completed_at=time.time(),
                    )

    async def on_job_permanently_failed(self, job: Dict[str, Any]) -> None:
        """TaskGuarantee hook: the sweeps failed ``job`` for good (retries
        exhausted, container timeout, pinned worker gone). PD containers
        release placement and cancel orphaned children; PD stage children
        enter the RE-PREFILL fallback (the flow re-places the whole
        generation on surviving workers) and only fail their container
        when the re-prefill budget is spent — a stranded parent holds a
        scheduler placement and keeps its sync waiters hanging the full
        window."""
        params = job.get("params") or {}
        # child check FIRST: stage children inherit the container's params
        # (pd_disaggregated included) and would otherwise match the
        # container branch and silently orphan their parent
        if self.is_pd_child(job):
            parent_id = params["pd_parent"]
            parent = await self.store.get_job(parent_id)
            if parent is not None and parent["status"] not in (
                "completed", "failed", "cancelled"
            ):
                await self._stage_failed(parent_id, params["pd_stage"], job)
            return
        if params.get("pd_disaggregated"):
            await self.on_parent_terminal(job["id"])

    async def _prune_live(self) -> None:
        """Drop placements whose parent went terminal without passing
        through on_child_complete (e.g. swept by the stale-job timeout) so
        worker active-counters cannot leak."""
        for pid in list(self._live.keys()):
            job = await self.store.get_job(pid)
            if job is None or job["status"] in (
                "completed", "failed", "cancelled"
            ):
                self._finish(pid, ok=False)

    async def submit(self, parent: Dict[str, Any]) -> None:
        """Place a pd job and enqueue its prefill child. Parent is already
        stored with status=running (unclaimable container)."""
        await self._prune_live()
        await self._sync_workers()
        params = parent.get("params") or {}
        prompt = params.get("prompt_token_ids") or params.get("prompt") or []
        # token lists count exactly; raw text estimates ~4 chars/token so the
        # scheduler's prefill scoring isn't skewed 4-5x by character counts
        n_prompt = len(prompt) if isinstance(prompt, list) \
            else max(1, len(prompt) // 4)
        req = PDRequest(
            request_id=parent["id"],
            prompt_tokens=n_prompt,
            max_new_tokens=int(params.get("max_tokens") or 256),
            model_name=params.get("model") or "llama3-8b",
        )
        await self._place_and_enqueue(parent, req)
        self._live[parent["id"]] = req
        self.stats["submitted"] += 1

    async def _place_and_enqueue(self, parent: Dict[str, Any],
                                 req: PDRequest) -> None:
        """Place ``req`` on a prefill + decode pair and enqueue this
        attempt's pinned prefill child. Raises :class:`PDFlowError` (with
        placement fully released) when no capable pair exists."""
        params = parent.get("params") or {}
        pw = self.scheduler.place_prefill(req)
        if pw is None:
            raise PDFlowError("no prefill-capable worker available")
        # decode placed up front so the prefill worker knows where to push
        # KV; kv_holder is the prefill worker once prefill lands
        req.kv_holder = pw
        dw = self.scheduler.place_decode(req)
        if dw is None:
            self.scheduler.release(req)
            req.prefill_worker = None
            raise PDFlowError("no decode-capable worker available")
        decode_row = await self.store.get_worker(dw)
        decode_url = (decode_row or {}).get("data_plane_url")
        if dw != pw and not decode_url:
            self.scheduler.release(req)
            req.prefill_worker = req.decode_worker = None
            raise PDFlowError(
                f"decode worker {dw} advertises no data_plane_url for the "
                "KV handoff"
            )
        # fresh key per attempt: a stale attempt's adopted KV (if its push
        # landed after all) can never be claimed by the live attempt's
        # decode stage — it ages out via the worker's pd-slot TTL
        req.kv_cache_key = f"pd-{parent['id']}-{uuid.uuid4().hex[:8]}"
        child_params = {
            **params,
            "pd_stage": "prefill",
            "pd_parent": parent["id"],
            "pd_attempt": req.attempt,
            "target_worker": pw,
            "decode_worker": dw,
            "decode_url": decode_url,
            "kv_cache_key": req.kv_cache_key,
        }
        await self.store.create_job({
            "id": self._child_id(parent["id"], "prefill", req.attempt),
            "type": parent["type"],
            "params": child_params,
            "priority": int(parent.get("priority") or 0) + 5,
            "timeout_seconds": parent.get("timeout_seconds") or 300.0,
        })

    # ------------------------------------------------------------ advance

    def is_pd_child(self, job: Dict[str, Any]) -> bool:
        p = job.get("params") or {}
        return bool(p.get("pd_parent") and p.get("pd_stage"))

    async def on_child_complete(self, child: Dict[str, Any]) -> None:
        """Advance the flow when a pinned stage job finishes."""
        params = child.get("params") or {}
        parent_id = params["pd_parent"]
        stage = params["pd_stage"]
        parent = await self.store.get_job(parent_id)
        if parent is None:
            return
        if parent["status"] in ("completed", "failed", "cancelled"):
            # late child of a terminal (e.g. cancelled) parent: release any
            # placement state, never overwrite the terminal status
            self._finish(parent_id, ok=False)
            return
        req = self._live.get(parent_id)
        if req is not None and \
                int(params.get("pd_attempt") or 0) != req.attempt:
            # a STALE attempt's child finished late (its worker revived
            # after the flow re-prefilled elsewhere): the live attempt
            # owns the flow — ignore. KV the stale prefill pushed ages
            # out via the decode worker's pd-slot TTL (fresh key per
            # attempt, so the live decode stage can never claim it).
            self.stats["stale_stage_results"] += 1
            return
        if child["status"] != "completed":
            await self._stage_failed(parent_id, stage, child)
            return
        result = child.get("result") or {}
        if stage == "prefill":
            # decode needs only the sampling config + flow keys — NOT the
            # prompt (its KV already moved) or prefill-only routing. A
            # multi-MB prompt stored a third time would also hit the claim
            # path's params parse.
            decode_params = {
                k: v for k, v in params.items()
                if k not in ("pd_stage", "target_worker", "prompt",
                             "prompt_token_ids", "messages", "decode_url")
            }
            decode_params.update({
                "pd_stage": "decode",
                "target_worker": params["decode_worker"],
                "kv_cache_key": params["kv_cache_key"],
                # carried so the final merge needs no extra store round-trip
                "pd_prefill_result": {
                    "first_token": result.get("first_token"),
                    "ttft_ms": result.get("ttft_ms"),
                    "migration_bytes": result.get("migration_bytes"),
                    "migration_ms": result.get("migration_ms"),
                    "prefill_worker": child.get("worker_id"),
                },
            })
            await self.store.create_job({
                "id": self._child_id(
                    parent_id, "decode", int(params.get("pd_attempt") or 0)
                ),
                "type": parent["type"],
                "params": decode_params,
                "priority": int(parent.get("priority") or 0) + 5,
                "timeout_seconds": parent.get("timeout_seconds") or 300.0,
            })
            return
        # stage == "decode": merge and complete the parent
        pre = params.get("pd_prefill_result") or {}
        merged = {
            **result,
            "pd_disaggregated": True,
            "prefill_worker": pre.get("prefill_worker"),
            "decode_worker": child.get("worker_id"),
            "ttft_ms": pre.get("ttft_ms", result.get("ttft_ms")),
            "migration_bytes": pre.get("migration_bytes"),
            "migration_ms": pre.get("migration_ms"),
        }
        now = time.time()
        # conditional: a cancel racing this merge between the status check
        # above and here must keep its terminal state (terminal is terminal)
        won = await self.store.try_transition_job(
            parent_id, "running",
            status="completed", result=merged, completed_at=now,
            actual_duration_ms=(
                (now - float(parent["started_at"])) * 1000.0
                if parent.get("started_at") else None
            ),
        )
        self._finish(parent_id, ok=won)

    @staticmethod
    def _failure_reason(stage: str, error: str) -> str:
        """Counted re-prefill reason (``pd_reprefill_total{reason}``)."""
        low = (error or "").lower()
        if "no adopted kv" in low or "reclaimed" in low:
            return "kv_holder_lost"
        if "push" in low or "handoff" in low or "kv/transfer" in low:
            return "handoff_failed"
        return f"{stage}_failed"

    async def _stage_failed(self, parent_id: str, stage: str,
                            child: Dict[str, Any]) -> None:
        """A stage child went terminal without completing (worker died
        mid-transfer, handoff lost/corrupted, adopted KV gone, pinned
        worker swept): RE-PREFILL — release the placement, exclude the
        failed workers, and re-run the whole flow on survivors. The
        parent's own retry budget is untouched; the flow's attempt
        counter bounds the fallback. Out of budget (or flow state lost to
        a plane restart) → fail the parent as before."""
        params = child.get("params") or {}
        error = child.get("error") or f"{stage} stage failed"
        req = self._live.get(parent_id)
        if req is not None and \
                int(params.get("pd_attempt") or 0) != req.attempt:
            # stale attempt failing late: the live attempt owns the flow
            self.stats["stale_stage_results"] += 1
            return
        if req is None or req.attempt >= self.max_reprefills:
            await self._fail(parent_id, stage, error)
            return
        parent = await self.store.get_job(parent_id)
        if parent is None or parent["status"] in (
            "completed", "failed", "cancelled"
        ):
            self._finish(parent_id, ok=False)
            return
        # release the failed placement; exclude the stage's pinned worker
        # (and, for a prefill/handoff failure, the push target — a dead
        # RECEIVER fails the sender's child). Exclusions are advisory:
        # the scheduler retries over everyone before giving up.
        self.scheduler.release(req)
        excluded = {params.get("target_worker")}
        if stage == "prefill":
            excluded.add(params.get("decode_worker"))
        req.excluded_workers |= {w for w in excluded if w}
        req.prefill_worker = req.decode_worker = None
        req.kv_holder = None
        req.needs_migration = False
        req.attempt += 1
        self.stats["reprefills"] += 1
        if self.metrics is not None:
            self.metrics.record_pd_reprefill(
                self._failure_reason(stage, error)
            )
        # a still-queued sibling of the failed attempt (e.g. its decode
        # child) must not run against KV that no longer exists
        await self._cancel_queued_children(parent_id)
        if self.reprefill_backoff_s <= 0 or req.attempt == 1:
            # FIRST fallback places immediately: a one-off failure (worker
            # died, KV lost) recovers with no added latency, and a flow
            # whose re-placement cannot succeed at all (fleet dark) fails
            # promptly in the same pass — the round-10 contract
            await self._replace_now(parent_id, req, stage, error)
            return
        # repeat failures back off with jitter before the next attempt: a
        # handoff outage lasting a couple of seconds must not consume the
        # whole budget before it heals
        delay = (self.reprefill_backoff_s * (2 ** (req.attempt - 2))
                 * (0.5 + random.random()))
        task = asyncio.ensure_future(
            self._replace_later(parent_id, req, req.attempt, stage,
                                error, delay)
        )
        self._bg.add(task)
        task.add_done_callback(self._bg.discard)

    async def _replace_now(self, parent_id: str, req: PDRequest,
                           stage: str, error: str) -> None:
        await self._sync_workers()
        parent = await self.store.get_job(parent_id)
        if parent is None or parent["status"] in (
            "completed", "failed", "cancelled"
        ):
            self._finish(parent_id, ok=False)
            return
        try:
            await self._place_and_enqueue(parent, req)
        except PDFlowError as exc:
            await self._fail(
                parent_id, stage,
                f"{error}; re-prefill placement failed: {exc}",
            )

    async def _replace_later(self, parent_id: str, req: PDRequest,
                             attempt: int, stage: str, error: str,
                             delay: float) -> None:
        try:
            await asyncio.sleep(delay)
            # the flow may have gone terminal (cancel, timeout) or been
            # superseded while we slept — only the still-live attempt we
            # scheduled for may place
            if self._live.get(parent_id) is not req or \
                    req.attempt != attempt:
                return
            await self._replace_now(parent_id, req, stage, error)
        except Exception:  # noqa: BLE001 — a failed re-place must not
            # leak an unobserved task exception; the parent either fails
            # via _replace_now or the sweeps time it out
            pass

    async def _fail(self, parent_id: str, stage: str, error: str) -> None:
        # conditional: a cancel or completion racing this failure between
        # the caller's status check and here keeps its terminal state —
        # placement is released either way, but only the transition winner
        # cancels queued children (the racing path owns its own cleanup)
        won = await self.store.try_transition_job(
            parent_id, "running",
            status="failed",
            error=f"pd {stage} stage: {error}", completed_at=time.time(),
        )
        self._finish(parent_id, ok=False)
        if won:
            await self._cancel_queued_children(parent_id)

    def _finish(self, parent_id: str, ok: bool) -> None:
        req = self._live.pop(parent_id, None)
        if req is not None:
            # stats count each flow once — a late child arriving after the
            # parent went terminal finds _live already drained and is a no-op
            self.scheduler.release(req)
            self.stats["completed" if ok else "failed"] += 1

    def get_stats(self) -> Dict[str, Any]:
        return {**self.stats, "live": len(self._live),
                "scheduler": self.scheduler.get_stats()}
