"""Privacy / compliance: anonymization, field encryption, retention cleanup.

Behavioral parity with the reference's ``server/app/services/privacy.py``:
- ``Anonymizer``: IP truncation (:94), PII scrubbing in free text (:184),
  stable pseudonyms (:162).
- Fernet field encryption with a PBKDF2-derived key (:194-271) —
  ``cryptography`` is available in this image; gated import keeps the module
  usable without it (encryption methods then raise).
- Retention cleanup of old jobs/usage (:273-395).
- Privacy audit + compliance report (:397-530).
- Enterprise privacy orchestration (store/retrieve/export/delete, :532-812).
"""

from __future__ import annotations

import base64
import hashlib
import json
import re
import time
from typing import Any, Dict, List, Optional

from .store import Store

def _load_crypto():
    """Lazy ``cryptography`` import: the module (and every privacy feature
    that doesn't encrypt) must work on images without the optional dep —
    only constructing a :class:`FieldEncryptor` requires it, and the error
    then names the missing capability instead of an ImportError at import
    time (which used to take the whole server module down with it)."""
    try:
        from cryptography.fernet import Fernet
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.kdf.pbkdf2 import PBKDF2HMAC
    except Exception as exc:  # pragma: no cover - present in full images
        raise RuntimeError(
            "field encryption requires the optional 'cryptography' package "
            f"(pip install cryptography): {exc}"
        ) from exc
    return Fernet, hashes, PBKDF2HMAC


def crypto_available() -> bool:
    try:
        _load_crypto()
        return True
    except RuntimeError:
        return False

_EMAIL_RE = re.compile(r"[\w.+-]+@[\w-]+\.[\w.-]+")
_PHONE_RE = re.compile(r"\+?\d[\d\s().-]{7,}\d")
_IPV4_RE = re.compile(r"\b(?:\d{1,3}\.){3}\d{1,3}\b")
_SSN_RE = re.compile(r"\b\d{3}-\d{2}-\d{4}\b")


class Anonymizer:
    """Stateless PII reduction utilities."""

    def __init__(self, pseudonym_salt: str = "") -> None:
        self._salt = pseudonym_salt

    @staticmethod
    def truncate_ip(ip: Optional[str]) -> Optional[str]:
        """Zero the host octet / trailing groups (reference privacy.py:94)."""
        if not ip:
            return ip
        if ":" in ip:  # ipv6: keep first 3 groups
            groups = ip.split(":")
            return ":".join(groups[:3]) + "::"
        parts = ip.split(".")
        if len(parts) == 4:
            return ".".join(parts[:3]) + ".0"
        return ip

    def pseudonym(self, identity: str) -> str:
        """Stable non-reversible pseudonym (reference :162)."""
        h = hashlib.sha256(f"{self._salt}{identity}".encode()).hexdigest()
        return f"anon-{h[:12]}"

    @staticmethod
    def scrub_text(text: str) -> str:
        """Mask emails / phones / IPs / SSNs in free text (reference :184)."""
        text = _EMAIL_RE.sub("[EMAIL]", text)
        text = _SSN_RE.sub("[SSN]", text)
        text = _IPV4_RE.sub("[IP]", text)
        text = _PHONE_RE.sub("[PHONE]", text)
        return text

    def anonymize_record(self, record: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(record)
        if out.get("client_ip"):
            out["client_ip"] = self.truncate_ip(out["client_ip"])
        for key in ("prompt", "text", "error"):
            if isinstance(out.get(key), str):
                out[key] = self.scrub_text(out[key])
        return out


class FieldEncryptor:
    """Fernet encryption of individual fields, key derived via PBKDF2."""

    def __init__(self, passphrase: str, salt: bytes = b"dgi-tpu-privacy") -> None:
        Fernet, hashes, PBKDF2HMAC = _load_crypto()
        kdf = PBKDF2HMAC(
            algorithm=hashes.SHA256(), length=32, salt=salt, iterations=100_000
        )
        key = base64.urlsafe_b64encode(kdf.derive(passphrase.encode()))
        self._fernet = Fernet(key)

    def encrypt_field(self, value: Any) -> str:
        raw = json.dumps(value).encode()
        return self._fernet.encrypt(raw).decode()

    def decrypt_field(self, token: str) -> Any:
        raw = self._fernet.decrypt(token.encode())
        return json.loads(raw.decode())

    def encrypt_fields(self, record: Dict[str, Any],
                       fields: List[str]) -> Dict[str, Any]:
        out = dict(record)
        for f in fields:
            if f in out and out[f] is not None:
                out[f] = self.encrypt_field(out[f])
        return out

    def decrypt_fields(self, record: Dict[str, Any],
                       fields: List[str]) -> Dict[str, Any]:
        out = dict(record)
        for f in fields:
            if isinstance(out.get(f), str):
                try:
                    out[f] = self.decrypt_field(out[f])
                except Exception:  # noqa: BLE001 — leave non-encrypted values
                    pass
        return out


class RetentionPolicy:
    """Deletes terminal jobs and usage records older than per-enterprise
    retention windows (reference privacy.py:273-395)."""

    def __init__(self, store: Store, default_days: int = 30) -> None:
        self._store = store
        self._default_days = default_days

    async def _retention_days(self, enterprise_id: Optional[str]) -> int:
        if enterprise_id:
            ent = await self._store.get("enterprises", enterprise_id)
            if ent and ent.get("retention_days") is not None:
                return int(ent["retention_days"])
        return self._default_days

    async def cleanup(self, now: Optional[float] = None) -> Dict[str, int]:
        now = time.time() if now is None else now
        cutoff = now - self._default_days * 86400.0
        before_jobs = await self._store.query(
            "SELECT COUNT(*) AS n FROM jobs WHERE completed_at IS NOT NULL "
            "AND completed_at < ?",
            (cutoff,),
        )
        await self._store.execute(
            "DELETE FROM jobs WHERE completed_at IS NOT NULL AND completed_at < ?",
            (cutoff,),
        )
        before_usage = await self._store.query(
            "SELECT COUNT(*) AS n FROM usage_records WHERE created_at < ?",
            (cutoff,),
        )
        await self._store.execute(
            "DELETE FROM usage_records WHERE created_at < ?", (cutoff,)
        )
        return {
            "jobs_deleted": int(before_jobs[0]["n"]),
            "usage_deleted": int(before_usage[0]["n"]),
        }


class EnterprisePrivacyService:
    """Per-enterprise privacy orchestration: anonymize-on-store, encrypted
    fields, export, delete (reference privacy.py:532-812)."""

    ENCRYPTED_FIELDS = ["params", "result"]

    def __init__(self, store: Store, passphrase: Optional[str] = None,
                 pseudonym_salt: str = "") -> None:
        self._store = store
        self.anonymizer = Anonymizer(pseudonym_salt)
        self.retention = RetentionPolicy(store)
        self._encryptor = (
            FieldEncryptor(passphrase)
            if (passphrase and crypto_available()) else None
        )

    async def _settings(self, enterprise_id: Optional[str]) -> Dict[str, Any]:
        if enterprise_id:
            ent = await self._store.get("enterprises", enterprise_id)
            if ent:
                return ent
        return {"allow_logging": 1, "anonymize_data": 0, "encrypt_fields": 0}

    async def prepare_job_record(self, job: Dict[str, Any],
                                 enterprise_id: Optional[str] = None
                                 ) -> Optional[Dict[str, Any]]:
        """Apply the enterprise's privacy settings before persisting."""
        s = await self._settings(enterprise_id)
        if not s.get("allow_logging", 1):
            return None
        out = dict(job)
        if s.get("anonymize_data"):
            out = self.anonymizer.anonymize_record(out)
        if s.get("encrypt_fields") and self._encryptor is not None:
            out = self._encryptor.encrypt_fields(out, self.ENCRYPTED_FIELDS)
        return out

    async def export_enterprise_data(self, enterprise_id: str
                                     ) -> Dict[str, Any]:
        usage = await self._store.query(
            "SELECT * FROM usage_records WHERE enterprise_id=?", (enterprise_id,)
        )
        bills = await self._store.query(
            "SELECT * FROM bills WHERE enterprise_id=?", (enterprise_id,)
        )
        ent = await self._store.get("enterprises", enterprise_id)
        return {"enterprise": ent, "usage_records": usage, "bills": bills}

    async def delete_enterprise_data(self, enterprise_id: str) -> Dict[str, int]:
        usage = await self._store.query(
            "SELECT COUNT(*) AS n FROM usage_records WHERE enterprise_id=?",
            (enterprise_id,),
        )
        await self._store.execute(
            "DELETE FROM usage_records WHERE enterprise_id=?", (enterprise_id,)
        )
        await self._store.execute(
            "DELETE FROM bills WHERE enterprise_id=?", (enterprise_id,)
        )
        await self._store.audit("enterprise_data_deleted", actor=enterprise_id)
        return {"usage_deleted": int(usage[0]["n"])}

    async def compliance_report(self) -> Dict[str, Any]:
        """Summary of privacy posture (reference :397-530)."""
        ents = await self._store.query("SELECT * FROM enterprises")
        jobs = await self._store.query("SELECT COUNT(*) AS n FROM jobs")
        usage = await self._store.query("SELECT COUNT(*) AS n FROM usage_records")
        return {
            "generated_at": time.time(),
            "enterprises": len(ents),
            "with_anonymization": sum(1 for e in ents if e.get("anonymize_data")),
            "with_encryption": sum(1 for e in ents if e.get("encrypt_fields")),
            "logging_disabled": sum(1 for e in ents if not e.get("allow_logging", 1)),
            "stored_jobs": int(jobs[0]["n"]),
            "stored_usage_records": int(usage[0]["n"]),
            "encryption_available": crypto_available(),
        }
