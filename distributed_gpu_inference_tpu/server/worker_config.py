"""Server-pushed versioned worker configuration (load control, security,
per-task model configs).

Behavioral parity with the reference's ``server/app/services/worker_config.py``:
- Load-control knobs (:20-47): acceptance_rate, max_concurrent_jobs,
  max_jobs_per_hour, HBM utilization cap, working hours, per-type weights,
  cooldown between jobs.
- Security policy (:50-66) and per-type ``ModelConfig`` incl. quantization
  (:68-82).
- Versioned ``WorkerRemoteConfig`` (:85-107): bump on every update; workers
  learn of changes via the heartbeat ``config_changed`` flag
  (reference ``workers.py:276-289``).
- Server-side ``should_accept_job`` (:195) so admission policy is enforced
  even if a worker is stale.

TPU deltas: memory knob is HBM fraction (not VRAM), model configs carry
mesh-shape hints for pjit layouts.
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, Optional, Tuple

from .store import Store

# shed fractions of max_queue_depth by tenant tier when the fleet config
# doesn't override them (LoadControl.tier_queue_fractions): batch browns
# out first, then free; paid holds the full limit — the shed ORDER the
# round-12 overload ladder guarantees ("paid never shed while free-tier
# capacity exists") falls out of these being strictly ordered.
DEFAULT_TIER_QUEUE_FRACTIONS: Dict[str, float] = {
    "paid": 1.0,
    "free": 0.85,
    "batch": 0.6,
}


@dataclass
class LoadControl:
    acceptance_rate: float = 1.0          # probability of accepting any job
    # since round 6 this is also the worker's SHARED serving-claim cap
    # (batcher-backed engines batch this many concurrent jobs/streams):
    # the fleet default matches the worker-local default
    # (utils.config.LoadControlConfig) — a server pushing 1 would silently
    # disable continuous batching on every worker it manages. Workers
    # whose engines have no batcher still serialize via the exclusive
    # claim regardless of this value.
    max_concurrent_jobs: int = 4
    max_jobs_per_hour: int = 0            # 0 = unlimited
    max_hbm_utilization: float = 0.9      # fraction of per-chip HBM usable
    working_hours: Optional[list] = None  # [start_hour, end_hour] UTC or None
    task_type_weights: Dict[str, float] = field(default_factory=dict)
    cooldown_seconds: float = 0.0
    # end-to-end backpressure: job submissions beyond this queue depth are
    # rejected with 429 + Retry-After instead of growing the queue silently
    # (the SDK's jittered backoff honors the hint). 0 = unlimited.
    max_queue_depth: int = 0
    # tier-aware shed fractions of max_queue_depth (round 12 overload
    # control): a tier sheds once the queue passes fraction * limit, so
    # lower tiers brown out FIRST and paid traffic is never shed while
    # free-tier capacity exists. Missing tiers fall back to
    # DEFAULT_TIER_QUEUE_FRACTIONS; untiered submissions keep the full
    # limit (fraction 1.0 — exactly the pre-round-12 blanket behavior).
    tier_queue_fractions: Dict[str, float] = field(default_factory=dict)


@dataclass
class SecurityPolicy:
    require_signing: bool = True
    token_ttl_hours: float = 168.0
    allowed_ips: Optional[list] = None


@dataclass
class ModelConfig:
    model_id: str = ""
    quantization: Optional[str] = None    # int8 / fp8 (TPU-native AQT-style)
    max_seq_len: int = 4096
    mesh_shape: Optional[Dict[str, int]] = None  # e.g. {"tp": 4, "dp": 2}
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class WorkerRemoteConfig:
    version: int = 1
    load_control: LoadControl = field(default_factory=LoadControl)
    security: SecurityPolicy = field(default_factory=SecurityPolicy)
    model_configs: Dict[str, ModelConfig] = field(default_factory=dict)
    # batcher-serving SLO knobs pushed to live workers (the keys of
    # utils.config.ServingConfig that retune a RUNNING batcher between
    # decode rounds: target_step_ms, max_horizon, min_horizon, multi_step,
    # adaptive, max_wait_ms, queue_limit, default_timeout_s,
    # max_preemptions, spec_max_batch, spec_max_active, ragged).
    # Compile-affecting admission knobs (subwave/interleave) and `mode`
    # are load-time-only worker YAML and silently ignored by the worker if
    # pushed. The round-6 ragged serving path made subwave/interleave/
    # max_horizon degenerate: still accepted (saved SLO configs keep
    # deploying) but deprecation-warned once on ingest — see
    # utils.config.DEPRECATED_SERVING_KEYS. Empty dict = no override (the
    # worker keeps its local config).
    serving: Dict[str, Any] = field(default_factory=dict)
    updated_at: float = field(default_factory=time.time)

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WorkerRemoteConfig":
        from distributed_gpu_inference_tpu.utils.config import (
            warn_deprecated_serving_key,
        )

        lc = LoadControl(**(d.get("load_control") or {}))
        sec = SecurityPolicy(**(d.get("security") or {}))
        mcs = {
            k: ModelConfig(**v) for k, v in (d.get("model_configs") or {}).items()
        }
        for key, val in (d.get("serving") or {}).items():
            if val is not None:
                warn_deprecated_serving_key(key, "remote config push")
        return cls(
            version=int(d.get("version") or 1),
            load_control=lc,
            security=sec,
            model_configs=mcs,
            serving=dict(d.get("serving") or {}),
            updated_at=float(d.get("updated_at") or time.time()),
        )


class WorkerConfigService:
    """Source of truth for per-worker remote config, persisted on worker rows
    (``config_version`` + ``config_override``)."""

    def __init__(self, store: Store,
                 defaults: Optional[WorkerRemoteConfig] = None) -> None:
        self._store = store
        self._defaults = defaults or WorkerRemoteConfig()

    async def get_config(self, worker_id: str) -> WorkerRemoteConfig:
        w = await self._store.get_worker(worker_id)
        if w is None:
            return self._defaults
        override = w.get("config_override")
        if override:
            cfg = WorkerRemoteConfig.from_dict(override)
        else:
            cfg = WorkerRemoteConfig.from_dict(self._defaults.to_dict())
        cfg.version = int(w.get("config_version") or cfg.version or 1)
        return cfg

    async def update_config(self, worker_id: str,
                            updates: Dict[str, Any]) -> WorkerRemoteConfig:
        """Merge updates into the worker's config and bump the version."""
        cfg = await self.get_config(worker_id)
        d = cfg.to_dict()
        for key, val in updates.items():
            if key in ("load_control", "security", "serving") \
                    and isinstance(val, dict):
                d[key] = {**(d.get(key) or {}), **val}
            elif key == "model_configs" and isinstance(val, dict):
                merged = dict(d.get("model_configs") or {})
                for task, mc in val.items():
                    base = dict(merged.get(task) or {})
                    base.update(mc)
                    merged[task] = base
                d["model_configs"] = merged
            else:
                d[key] = val
        d["version"] = cfg.version + 1
        d["updated_at"] = time.time()
        new = WorkerRemoteConfig.from_dict(d)
        await self._store.update_worker(
            worker_id,
            config_version=new.version,
            config_override=new.to_dict(),
        )
        return new

    async def config_changed_since(self, worker_id: str, version: int) -> bool:
        w = await self._store.get_worker(worker_id)
        if w is None:
            return False
        return int(w.get("config_version") or 0) > version

    # -- submission backpressure (same policy object should_accept_job
    # enforces on the claim side; this is the client-facing half) ------------

    @property
    def submit_queue_limit(self) -> int:
        """Fleet-default queue-depth ceiling for job submissions (0 =
        backpressure disabled)."""
        return int(self._defaults.load_control.max_queue_depth or 0)

    def set_submit_queue_limit(self, limit: int) -> None:
        self._defaults.load_control.max_queue_depth = int(limit)

    def should_accept_submission(self, queued: int, active_workers: int,
                                 tier: Optional[str] = None
                                 ) -> Tuple[bool, float]:
        """Queue-depth admission control for POST /jobs. Returns
        ``(accept, retry_after_s)`` — when the fleet-default
        ``LoadControl.max_queue_depth`` is exceeded the submission is
        rejected and the hint estimates the drain time of the overflow
        (queue beyond the limit, spread over live workers), clamped to
        [1, 60] s so a burst never tells every client to come back at the
        same instant far in the future.

        ``tier`` (round 12 overload control) scales the limit by the
        tier's queue fraction: free/batch tiers shed at a fraction of the
        limit paid keeps, so the shed order under saturation is
        batch → free → paid by construction. ``tier=None`` (legacy
        untiered submissions) keeps the full limit — byte-identical to
        the pre-tier behavior."""
        limit = self.submit_queue_limit
        if limit <= 0:
            return True, 0.0
        if tier is not None:
            frac = (self._defaults.load_control.tier_queue_fractions.get(
                tier, DEFAULT_TIER_QUEUE_FRACTIONS.get(tier, 1.0)))
            limit = max(1, int(limit * max(0.0, min(1.0, float(frac)))))
        if queued < limit:
            return True, 0.0
        overflow = queued - limit + 1
        retry_after = min(60.0, max(1.0, overflow / max(1, active_workers)))
        return False, retry_after

    # -- server-side admission (reference worker_config.py:195) --------------

    async def should_accept_job(self, worker_id: str, job_type: str,
                                now: Optional[float] = None,
                                rand: float = 0.0,
                                ignore_job_id: Optional[str] = None) -> bool:
        cfg = await self.get_config(worker_id)
        lc = cfg.load_control
        now = time.time() if now is None else now

        if rand > lc.acceptance_rate:
            return False
        weight = lc.task_type_weights.get(job_type, 1.0)
        if weight <= 0:
            return False
        if lc.working_hours:
            start, end = lc.working_hours
            hour = time.gmtime(now).tm_hour
            in_window = (start <= hour < end) if start <= end else (
                hour >= start or hour < end
            )
            if not in_window:
                return False
        w = await self._store.get_worker(worker_id)
        if w is not None:
            current = w.get("current_job_id")
            if (current and current != ignore_job_id
                    and lc.max_concurrent_jobs <= 1):
                return False
            hbm_cap = lc.max_hbm_utilization * float(w.get("hbm_gb_per_chip") or 0)
            if hbm_cap and float(w.get("hbm_used_gb") or 0) > hbm_cap * max(
                1, int(w.get("num_chips") or 1)
            ):
                return False
        return True
