"""Brownout-driven replica autoscaling.

PR 10 made degradation *measurable* — SLO-in-window, goodput,
time-to-recover ride the brownout bench and ``/metrics``. This module
makes the fleet *act* on those measurements:

- **Scale out on a projected SLO miss.** The controller keeps a sliding
  window of per-request SLO samples (did this request meet its latency
  bound?) and a linear trend over the window. Because a new replica
  takes a measured cold-start time to serve (engine build + compile +
  registration), the decision uses the SLO *projected one cold-start
  ahead*: by the time the replica is useful the window will have moved —
  scaling on the current value alone is always one cold-start late.
- **Measured cold start as lead time.** Every scale-out is timed from
  the decision to the replica's first served request
  (:meth:`note_scale_out_started` / :meth:`note_replica_serving`); the
  EMA feeds the projection AND is published
  (``autoscaler_cold_start_seconds``) so the lead time in the math is
  the lead time on the floor, not a config guess.
- **Scale in on sustained headroom only.** The SLO comfortably above
  target AND measured utilization low for ``headroom_ticks``
  consecutive ticks — a single quiet tick after a burst must not
  shrink the fleet straight back into the next brownout (cooldowns
  bound flapping in both directions).

The controller is deliberately fleet-agnostic: it consumes observations
and emits decisions (``scale_out`` / ``scale_in`` / ``hold``); the
driver that owns real replicas (``testing/harness.py``
:class:`FleetAutoscaler` for :class:`LiveFleet`, a k8s operator in a
real deployment) executes them. Decisions and their inputs land in
``/metrics`` (``autoscaler_decisions_total``,
``autoscaler_target_replicas``, ``autoscaler_slo_in_window``,
``autoscaler_cold_start_seconds``).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Deque, Optional, Tuple
from collections import deque


@dataclass
class AutoscalerConfig:
    # per-request SLO bound the window samples are judged against (the
    # driver may also pre-judge and feed booleans; then this is unused)
    slo_latency_ms: float = 2000.0
    # scale out when the PROJECTED fraction of in-SLO requests drops
    # below this target
    slo_target: float = 0.9
    window_s: float = 10.0
    min_samples: int = 5          # no decisions on statistical noise
    min_replicas: int = 1
    max_replicas: int = 8
    # scale in only after this many consecutive ticks of headroom
    # (SLO >= headroom_slo AND utilization <= headroom_utilization)
    headroom_ticks: int = 3
    headroom_slo: float = 0.98
    headroom_utilization: float = 0.5
    scale_out_cooldown_s: float = 2.0
    scale_in_cooldown_s: float = 10.0
    # cold-start prior before the first measurement; the EMA replaces it
    default_cold_start_s: float = 5.0
    cold_start_ema: float = 0.5   # weight of the newest measurement


class BrownoutAutoscaler:
    """Sliding-window SLO controller. Thread-safe: the traffic driver
    calls :meth:`observe` from request threads while a ticker thread
    calls :meth:`tick`."""

    def __init__(self, cfg: Optional[AutoscalerConfig] = None,
                 metrics: Optional[Any] = None) -> None:
        self.cfg = cfg or AutoscalerConfig()
        self.metrics = metrics
        self._lock = threading.Lock()
        # (ts, in_slo) per completed request
        self._samples: "Deque[Tuple[float, bool]]" = deque()
        self._last_out = -float("inf")
        self._last_in = -float("inf")
        self._headroom_streak = 0
        self._cold_start_s = float(self.cfg.default_cold_start_s)
        self._out_started_at: Optional[float] = None
        self.stats = {"scale_out": 0, "scale_in": 0, "hold": 0,
                      "cold_starts_measured": 0}

    # -- observations ---------------------------------------------------------

    def observe(self, latency_ms: Optional[float] = None,
                in_slo: Optional[bool] = None,
                now: Optional[float] = None) -> None:
        """One completed request: either the raw latency (judged against
        ``slo_latency_ms``) or a pre-judged boolean. Failed/shed requests
        should be fed ``in_slo=False`` — a shed request is an SLO miss
        from the client's chair."""
        now = time.time() if now is None else now
        if in_slo is None:
            in_slo = (latency_ms is not None
                      and latency_ms <= self.cfg.slo_latency_ms)
        with self._lock:
            self._samples.append((now, bool(in_slo)))
            self._trim(now)

    def note_scale_out_started(self, now: Optional[float] = None) -> None:
        """The driver began bringing a replica up (measure from the
        DECISION, not process exec — queue/registration time is part of
        the lead time the projection must cover)."""
        self._out_started_at = time.time() if now is None else now

    def note_replica_serving(self, now: Optional[float] = None) -> None:
        """The scaled-out replica served its first request: fold the
        measured cold start into the EMA lead time."""
        now = time.time() if now is None else now
        if self._out_started_at is None:
            return
        measured = max(0.0, now - self._out_started_at)
        self._out_started_at = None
        a = self.cfg.cold_start_ema
        self._cold_start_s = (1 - a) * self._cold_start_s + a * measured
        self.stats["cold_starts_measured"] += 1

    @property
    def cold_start_s(self) -> float:
        return self._cold_start_s

    # -- window math ----------------------------------------------------------

    def _trim(self, now: float) -> None:
        cutoff = now - self.cfg.window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def slo_in_window(self, now: Optional[float] = None) -> Optional[float]:
        """Fraction of windowed requests inside the SLO bound; None when
        the window is under ``min_samples`` (no decision-grade signal)."""
        now = time.time() if now is None else now
        with self._lock:
            self._trim(now)
            n = len(self._samples)
            if n < self.cfg.min_samples:
                return None
            return sum(1 for _, ok in self._samples if ok) / n

    def projected_slo(self, now: Optional[float] = None) -> Optional[float]:
        """SLO one cold-start ahead: current window value plus the linear
        trend (second window half minus first window half, per second)
        extrapolated over the measured cold-start lead time, clamped to
        [0, 1]. A worsening trend therefore triggers scale-out BEFORE the
        current value crosses the target."""
        now = time.time() if now is None else now
        with self._lock:
            self._trim(now)
            n = len(self._samples)
            if n < self.cfg.min_samples:
                return None
            samples = list(self._samples)
        cur = sum(1 for _, ok in samples if ok) / n
        half = now - self.cfg.window_s / 2.0
        early = [ok for ts, ok in samples if ts < half]
        late = [ok for ts, ok in samples if ts >= half]
        if not early or not late:
            return cur
        e = sum(early) / len(early)
        l_ = sum(late) / len(late)
        slope_per_s = (l_ - e) / max(self.cfg.window_s / 2.0, 1e-6)
        return max(0.0, min(1.0, cur + slope_per_s * self._cold_start_s))

    # -- the decision ---------------------------------------------------------

    def tick(self, replicas: int, utilization: Optional[float] = None,
             now: Optional[float] = None) -> str:
        """One control tick → ``scale_out`` | ``scale_in`` | ``hold``.

        ``replicas`` is the CURRENT serving replica count (the driver's
        truth, incl. chaos kills — decisions and failures must compose);
        ``utilization`` in [0, 1] gates scale-in (None = unknown = never
        scale in on SLO alone)."""
        now = time.time() if now is None else now
        slo = self.slo_in_window(now)
        projected = self.projected_slo(now)
        action = "hold"
        if projected is not None and projected < self.cfg.slo_target \
                and replicas < self.cfg.max_replicas \
                and now - self._last_out >= self.cfg.scale_out_cooldown_s:
            action = "scale_out"
            self._last_out = now
            self._headroom_streak = 0
        else:
            headroom = (
                slo is not None and slo >= self.cfg.headroom_slo
                and utilization is not None
                and utilization <= self.cfg.headroom_utilization
            )
            self._headroom_streak = self._headroom_streak + 1 if headroom \
                else 0
            if self._headroom_streak >= self.cfg.headroom_ticks \
                    and replicas > self.cfg.min_replicas \
                    and now - self._last_in >= self.cfg.scale_in_cooldown_s \
                    and now - self._last_out >= self.cfg.scale_in_cooldown_s:
                # the scale-out cooldown also gates scale-in: shrinking
                # while a cold replica is still warming up would measure
                # its warmup as headroom
                action = "scale_in"
                self._last_in = now
                self._headroom_streak = 0
        self.stats[action] += 1
        target = replicas + (1 if action == "scale_out" else 0) \
            - (1 if action == "scale_in" else 0)
        if self.metrics is not None:
            try:
                self.metrics.record_autoscaler(
                    action, target_replicas=target,
                    # None (window below min_samples — e.g. EVERY request
                    # hanging) must not publish as a perfect 1.0: skip
                    # the gauge and let it hold its last honest value
                    slo_in_window=slo,
                    cold_start_s=self._cold_start_s,
                )
            except Exception:  # noqa: BLE001 — metrics must not gate
                pass
        return action


# ---------------------------------------------------------------------------
# predictive PD rebalance (round 20)
# ---------------------------------------------------------------------------


@dataclass
class PredictiveRebalanceConfig:
    # off by default: tick() is a no-op and the PD pool behaves exactly
    # as the reactive-only build
    enabled: bool = False
    # preflip when the PROJECTED SLO drops below this; None inherits the
    # autoscaler's own slo_target (one knob, one truth)
    slo_target: Optional[float] = None
    # restore preflips only once projected SLO recovers ABOVE
    # target + hysteresis — a value hovering at the target must not flap
    # roles every tick
    hysteresis: float = 0.05
    # preflip only when the starved side's free capacity is below this
    # fraction of the donor side's: a projected miss with BALANCED pools
    # is an under-provisioned fleet (scale out), not a role imbalance
    imbalance_ratio: float = 0.5
    # between consecutive preflips
    cooldown_s: float = 5.0
    # bound how much of the donor side a streak of misses can convert
    max_preflips: int = 1


class PredictiveRebalancer:
    """Couples the brownout autoscaler's projected-SLO signal to PD role
    rebalancing: when the projection says the fleet will miss its target
    one cold-start from now AND one PD side is starved for capacity while
    the other has headroom, flip a donor worker to HYBRID *before* the
    starved queue melts down (the reactive ``role_rebalance`` in
    :class:`~.pd_scheduler.PrefillDecodeScheduler` only fires once a side
    is already dark). The same starved-side signal is returned to the
    scale driver so a scale-out lands a replica of the role the
    projection says will be short.

    Advisory and reversible: a wrong prediction costs one worker serving
    hybrid for a few ticks — roles gate new assignments only, in-flight
    work is untouched, and recovery past target + hysteresis restores
    the configured roles."""

    def __init__(self, autoscaler: BrownoutAutoscaler, pd_scheduler: Any,
                 cfg: Optional[PredictiveRebalanceConfig] = None,
                 metrics: Optional[Any] = None) -> None:
        self.autoscaler = autoscaler
        self.pd = pd_scheduler
        self.cfg = cfg or PredictiveRebalanceConfig()
        self.metrics = metrics
        self._last_flip = -float("inf")
        self.stats = {"ticks": 0, "preflips": 0, "restores": 0,
                      "suggestions": 0}

    def _record(self, action: str) -> None:
        if self.metrics is not None:
            try:
                self.metrics.record_predictive_rebalance(action)
            except Exception:  # noqa: BLE001 — metrics must not gate
                pass

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One control pass. Returns the PD role the NEXT scale-out
        should target (the projected-starved side), or None (no signal /
        disabled / balanced)."""
        if not self.cfg.enabled:
            return None
        now = time.time() if now is None else now
        self.stats["ticks"] += 1
        projected = self.autoscaler.projected_slo(now)
        target = (self.autoscaler.cfg.slo_target
                  if self.cfg.slo_target is None else self.cfg.slo_target)
        if projected is None:
            return None
        if projected >= target + self.cfg.hysteresis:
            if self.pd.restore_preflips():
                self.stats["restores"] += 1
                self._record("restore")
            return None
        if projected >= target:
            return None   # inside the hysteresis band: hold current shape
        cap = self.pd.capacity_by_role()
        pf, dc = int(cap.get("prefill") or 0), int(cap.get("decode") or 0)
        if pf == dc:
            return None   # balanced shortage → plain scale-out territory
        starved = "prefill" if pf < dc else "decode"
        starved_free, donor_free = (pf, dc) if starved == "prefill" \
            else (dc, pf)
        self.stats["suggestions"] += 1
        self._record("scale_out_role")
        if donor_free > 0 and \
                starved_free < self.cfg.imbalance_ratio * donor_free and \
                len(self.pd._preflipped) < max(0, self.cfg.max_preflips) and \
                now - self._last_flip >= self.cfg.cooldown_s:
            if self.pd.preflip_role(starved) is not None:
                self._last_flip = now
                self.stats["preflips"] += 1
                self._record("preflip")
        return starved
