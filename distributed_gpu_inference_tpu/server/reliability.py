"""Worker reliability scoring, online-pattern learning, availability predictors.

Behavioral parity with the reference's ``server/app/services/reliability.py``:
- Event-driven score deltas (:19-26): complete +0.02, fail −0.05,
  unexpected-offline −0.15, graceful-offline −0.02, long-session +0.05,
  fast-response +0.01; score clamped to [0, 1].
- Per-hour-of-day EMA online pattern (:98-108).
- Predictors: ``predict_online_probability`` (:130) and
  ``predict_remaining_online_time`` (:143).

Pure logic over Store rows — hermetically testable on CPU.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from .store import Store

SCORE_DELTAS = {
    "job_completed": +0.02,
    "job_failed": -0.05,
    "unexpected_offline": -0.15,
    "graceful_offline": -0.02,
    "long_session": +0.05,      # session > LONG_SESSION_MINUTES
    "fast_response": +0.01,     # latency < FAST_RESPONSE_MS
}
LONG_SESSION_MINUTES = 60.0
FAST_RESPONSE_MS = 1000.0
PATTERN_EMA_ALPHA = 0.2


def _clamp(x: float, lo: float = 0.0, hi: float = 1.0) -> float:
    return max(lo, min(hi, x))


class ReliabilityService:
    """Maintains reliability stats on worker rows."""

    def __init__(self, store: Store) -> None:
        self._store = store

    # -- event recording ---------------------------------------------------

    async def record_event(self, worker_id: str, event: str,
                           latency_ms: Optional[float] = None,
                           now: Optional[float] = None) -> Optional[float]:
        """Apply a score delta + update aggregate stats; returns new score."""
        w = await self._store.get_worker(worker_id)
        if w is None:
            return None
        now = time.time() if now is None else now
        # NOT `or 0.5`: a worker pinned at the 0.0 rail must stay there —
        # falsy-0.0 coercion would bounce it back to the neutral prior on
        # every subsequent event, erasing the penalty history
        raw = w.get("reliability_score")
        score = 0.5 if raw is None else float(raw)
        fields: Dict[str, Any] = {}

        delta = SCORE_DELTAS.get(event, 0.0)
        score = _clamp(score + delta)

        if event == "job_completed":
            fields["total_jobs"] = int(w.get("total_jobs") or 0) + 1
            fields["completed_jobs"] = int(w.get("completed_jobs") or 0) + 1
            if latency_ms is not None:
                prev = float(w.get("avg_latency_ms") or 0.0)
                n = fields["completed_jobs"]
                fields["avg_latency_ms"] = prev + (latency_ms - prev) / n
                if latency_ms < FAST_RESPONSE_MS:
                    score = _clamp(score + SCORE_DELTAS["fast_response"])
        elif event == "job_failed":
            fields["total_jobs"] = int(w.get("total_jobs") or 0) + 1
            fields["failed_jobs"] = int(w.get("failed_jobs") or 0) + 1
        elif event == "unexpected_offline":
            fields["unexpected_offline_count"] = (
                int(w.get("unexpected_offline_count") or 0) + 1
            )

        total = int(fields.get("total_jobs", w.get("total_jobs") or 0))
        completed = int(fields.get("completed_jobs", w.get("completed_jobs") or 0))
        if total > 0:
            fields["success_rate"] = completed / total
        fields["reliability_score"] = score
        await self._store.update_worker(worker_id, **fields)
        return score

    # -- session tracking (reference reliability.py:110-128) ----------------

    async def start_session(self, worker_id: str,
                            now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        await self._store.update_worker(worker_id, current_session_start=now)

    async def end_session(self, worker_id: str, graceful: bool = True,
                          now: Optional[float] = None) -> Optional[float]:
        """Close a session; returns its length in minutes."""
        w = await self._store.get_worker(worker_id)
        if w is None or not w.get("current_session_start"):
            return None
        now = time.time() if now is None else now
        dur_s = max(0.0, now - float(w["current_session_start"]))
        sessions = int(w.get("total_sessions") or 0) + 1
        prev_avg = float(w.get("avg_session_minutes") or 0.0)
        avg = prev_avg + (dur_s / 60.0 - prev_avg) / sessions
        await self._store.update_worker(
            worker_id,
            current_session_start=None,
            total_sessions=sessions,
            avg_session_minutes=avg,
            total_online_seconds=float(w.get("total_online_seconds") or 0.0) + dur_s,
        )
        if dur_s / 60.0 >= LONG_SESSION_MINUTES:
            await self.record_event(worker_id, "long_session", now=now)
        await self.record_event(
            worker_id,
            "graceful_offline" if graceful else "unexpected_offline",
            now=now,
        )
        return dur_s / 60.0

    # -- online pattern ------------------------------------------------------

    async def update_online_pattern(self, worker_id: str, online: bool,
                                    now: Optional[float] = None) -> None:
        """EMA per hour-of-day of observed online-ness (reference :98-108)."""
        w = await self._store.get_worker(worker_id)
        if w is None:
            return
        now = time.time() if now is None else now
        hour = str(int(time.gmtime(now).tm_hour))
        pattern = dict(w.get("online_pattern") or {})
        prev = float(pattern.get(hour, 0.5))
        pattern[hour] = (
            (1 - PATTERN_EMA_ALPHA) * prev + PATTERN_EMA_ALPHA * (1.0 if online else 0.0)
        )
        await self._store.update_worker(worker_id, online_pattern=pattern)

    # -- predictors ----------------------------------------------------------

    def predict_online_probability(self, worker: Dict[str, Any],
                                   now: Optional[float] = None) -> float:
        """P(online at this hour) from the learned pattern, blended with
        the reliability score (reference :130-141)."""
        now = time.time() if now is None else now
        hour = str(int(time.gmtime(now).tm_hour))
        pattern = worker.get("online_pattern") or {}
        p_hour = float(pattern.get(hour, 0.5))
        raw = worker.get("reliability_score")   # 0.0 is a real score, not
        score = 0.5 if raw is None else float(raw)  # "unknown"
        return _clamp(0.7 * p_hour + 0.3 * score)

    def predict_remaining_online_time(self, worker: Dict[str, Any],
                                      now: Optional[float] = None) -> float:
        """Expected remaining minutes of the current session (reference :143)."""
        now = time.time() if now is None else now
        start = worker.get("current_session_start")
        avg_min = float(worker.get("avg_session_minutes") or 0.0)
        if not start or avg_min <= 0:
            return avg_min
        elapsed_min = max(0.0, (now - float(start)) / 60.0)
        return max(0.0, avg_min - elapsed_min)
