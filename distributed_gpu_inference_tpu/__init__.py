"""distributed_gpu_inference_tpu — a TPU-native distributed inference framework.

A from-scratch re-design of the capabilities of the reference platform
``Baozhi888/distributed-gpu-inference`` (a federated GPU inference platform:
FastAPI control plane + volunteer GPU workers + vLLM/SGLang engines), built
TPU-first on JAX/XLA/Pallas:

- ``models/``    pure-JAX model families (Llama-class decoders, embeddings, vision)
- ``ops/``       Pallas TPU kernels (paged attention, flash prefill) + XLA fallbacks
- ``parallel/``  mesh/sharding (TP/PP/DP/SP) over ICI collectives, ring attention,
                 shard planner
- ``runtime/``   serving engine: paged KV cache, continuous batching, speculative
                 decoding, worker poll loop, engine registry
- ``server/``    control plane: aiohttp REST API, sqlite-backed store, smart
                 scheduler, PD disaggregation scheduler, reliability, security,
                 geo, usage/privacy/admin, observability
- ``distributed/`` cross-host data plane: pipeline sessions, KV transfer, P2P server
- ``sdk/``       Python client SDK
- ``utils/``     substrate: typed data structures, tensor wire framing, config
- ``native/``    C++ components (block allocator, radix prefix index, framing codec)

Subpackages are imported lazily — ``import distributed_gpu_inference_tpu`` does
not pull in jax or aiohttp.
"""

__version__ = "0.1.0"

_SUBPACKAGES = (
    "utils",
    "models",
    "ops",
    "parallel",
    "runtime",
    "server",
    "distributed",
    "sdk",
    "native",
)


def __getattr__(name):
    if name in _SUBPACKAGES:
        import importlib

        mod = importlib.import_module(f"{__name__}.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
