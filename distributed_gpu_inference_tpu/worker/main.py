"""Worker runtime: register → load engines → heartbeat + poll → process jobs.

Behavioral parity with the reference's ``worker/main.py`` (Worker:28):

- ``_register``:83 — verify persisted credentials first, re-register when
  stale, then fetch server-pushed remote config (:151).
- ``_load_engines``:234 — one engine per supported task type from the
  registry; a task type whose engine cannot load is dropped, not fatal.
- ``_heartbeat_loop``:263 — background thread, every ``heartbeat_interval_s``;
  a ``config_changed`` flag in the response triggers a remote-config refetch
  (reference ``main.py:290-301``).
- ``_main_loop``:313 — poll every ``poll_interval_s``; fetch → process →
  complete; load-control gates (acceptance rate, hourly cap, working hours,
  cooldown — server-pushed, ``worker_config.py`` values win over local).
- ``request_shutdown``:444 — graceful drain: stop accepting, finish the
  running job, tell the server ``going-offline`` then ``offline`` (which
  requeues anything still assigned); SIGTERM/SIGINT wired (:410-411).

TPU-first deltas: capability probing reports a :class:`TpuTopology` from
``jax.devices()`` (chip generation, chip count, HBM) instead of nvidia-smi;
engines are the in-repo JAX engines, so "loading" compiles jitted graphs
rather than importing a CUDA backend.
"""

from __future__ import annotations

import logging
import random
import signal
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils.config import WorkerConfig
from ..utils.data_structures import TpuTopology, WorkerState
from .api_client import APIClient, APIError
from .engines import EngineLoadError, create_engine
from .engines.base import JobMigrated
from .machine_id import MachineFingerprint

log = logging.getLogger("tpu_worker")


# per-generation chip facts: HBM GB, per-link ICI GB/s, peak bf16 TFLOP/s
_TPU_GEN = {
    "v4":  (32.0, 300.0, 275.0),
    "v5e": (16.0, 400.0, 197.0),
    "v5p": (95.0, 600.0, 459.0),
    "v6e": (32.0, 900.0, 918.0),
}


def probe_tpu_runtime() -> dict:
    """Environment-level TPU runtime probe — the analogue of the reference
    wizard's nvidia-smi/CUDA-version detection (``cli.py:77-133,298-651``),
    but for libtpu: works BEFORE any jax backend initializes (a probe that
    must first dial the chip cannot diagnose a broken runtime).

    Returns {libtpu, accel_devices, accelerator_type, worker_id, hosts} where
    ``accelerator_type`` is the platform-provided string (e.g.
    ``v5litepod-16``) GKE/GCE export via TPU_ACCELERATOR_TYPE.
    """
    import glob
    import importlib.util
    import os

    libtpu = bool(
        os.environ.get("TPU_LIBRARY_PATH")
        or importlib.util.find_spec("libtpu") is not None
        or glob.glob("/usr/lib/libtpu*")
        or glob.glob("/lib/libtpu*")
    )
    accel = sorted(glob.glob("/dev/accel*")) + sorted(glob.glob("/dev/vfio/*"))
    return {
        "libtpu": libtpu,
        "accel_devices": accel,
        "accelerator_type": os.environ.get("TPU_ACCELERATOR_TYPE")
        or os.environ.get("TPU_TYPE") or "",
        "worker_id": os.environ.get("TPU_WORKER_ID", ""),
        "hosts": (os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
                  if os.environ.get("TPU_WORKER_HOSTNAMES") else []),
    }


def _gen_from_string(s: str) -> str:
    s = s.lower()
    if "v5p" in s or "v5 pod" in s:
        return "v5p"
    if "v5lite" in s or "v5e" in s or "v5" in s:
        return "v5e"
    if "v6" in s:
        return "v6e"
    if "v4" in s:
        return "v4"
    return "v5e"


def probe_topology() -> TpuTopology:
    """Describe local accelerators (the TPU analogue of the reference's
    nvidia-smi probe, ``cli.py:77``): libtpu/env runtime facts first
    (``probe_tpu_runtime``), then jax device enumeration with physical
    mesh-shape discovery from device coords. Falls back to a CPU topology
    when no accelerator is reachable. The result rides in worker
    registration (``Worker.register`` → ``topology``) so schedulers see
    generation, chip count, HBM, and mesh shape (VERDICT r2 next #10)."""
    runtime = probe_tpu_runtime()
    try:
        import jax

        devices = jax.devices()
        kind = devices[0].device_kind.lower()
        is_tpu = any(t in kind for t in ("tpu", "v4", "v5", "v6"))
        if is_tpu:
            chip = _gen_from_string(runtime["accelerator_type"] or kind)
            hbm, ici, tflops = _TPU_GEN[chip]
            # physical mesh from device coords (bounding box of the slice);
            # fall back to a flat axis when coords are unavailable
            try:
                coords = [d.coords for d in devices]
                dims = tuple(
                    max(c[i] for c in coords) - min(c[i] for c in coords) + 1
                    for i in range(len(coords[0]))
                )
                dims = tuple(d for d in dims if d > 1) or (len(devices),)
                if int(np.prod(dims)) != len(devices):
                    dims = (len(devices),)
            except Exception:
                dims = (len(devices),)
            return TpuTopology(
                chip_type=chip, num_chips=len(devices), hbm_gb_per_chip=hbm,
                mesh_shape=dims,
                mesh_axis_names=tuple(f"ici{i}" for i in range(len(dims)))
                if len(dims) > 1 else ("data",),
                ici_bandwidth_gbps=ici, peak_bf16_tflops=tflops,
            )
        return TpuTopology(chip_type="cpu", num_chips=len(devices),
                           hbm_gb_per_chip=4.0, ici_bandwidth_gbps=10.0,
                           dcn_bandwidth_gbps=10.0, peak_bf16_tflops=0.2)
    except Exception:
        # no jax backend — if the runtime probe still smells TPU hardware,
        # report what the environment declares instead of "cpu" (a worker
        # with a broken driver should register as a TPU host needing repair)
        if runtime["libtpu"] and runtime["accelerator_type"]:
            chip = _gen_from_string(runtime["accelerator_type"])
            hbm, ici, tflops = _TPU_GEN[chip]
            import re as _re

            m = _re.search(r"-(\d+)$", runtime["accelerator_type"])
            chips = int(m.group(1)) if m else 1
            return TpuTopology(
                chip_type=chip, num_chips=chips, hbm_gb_per_chip=hbm,
                mesh_shape=(chips,), ici_bandwidth_gbps=ici,
                peak_bf16_tflops=tflops,
            )
        return TpuTopology(chip_type="cpu", num_chips=1, hbm_gb_per_chip=4.0)


class _PDReceiverShim:
    """Stage adapter for a PD KV-receiving DataPlaneServer: only /health and
    /kv/transfer are served; pipeline-session endpoints 404."""

    def __init__(self, llm_engine: Any) -> None:
        self._eng = llm_engine

    def health(self) -> Dict[str, Any]:
        return {**self._eng.health(), "pd_kv_receiver": True}

    def create_session(self, *a: Any, **kw: Any) -> None:
        raise KeyError("not a pipeline stage (PD KV receiver only)")

    def close_session(self, *a: Any, **kw: Any) -> None:
        raise KeyError("not a pipeline stage (PD KV receiver only)")

    def forward(self, *a: Any, **kw: Any) -> None:
        raise KeyError("not a pipeline stage (PD KV receiver only)")


class Worker:
    """The volunteer/fleet worker process (reference ``Worker``, main.py:28)."""

    def __init__(
        self,
        config: WorkerConfig,
        api: Optional[APIClient] = None,
        on_credentials: Optional[Callable[[Dict[str, str]], None]] = None,
        topology: Optional[TpuTopology] = None,
    ) -> None:
        self.config = config
        self.api = api or APIClient(
            # plane cohort: primary + fallbacks become the failover list
            # (a single URL keeps the historical one-plane behavior)
            [config.server.url, *(config.server.fallback_urls or [])],
            worker_id=config.server.worker_id,
            auth_token=config.server.auth_token,
            refresh_token=config.server.refresh_token,
            signing_secret=config.server.signing_secret,
            timeout_s=config.server.request_timeout_s,
        )
        self._on_credentials = on_credentials
        self.topology = topology or probe_topology()
        self.engines: Dict[str, Any] = {}
        self.state = WorkerState.INITIALIZING
        self.current_job_id: Optional[str] = None

        self._shutdown = threading.Event()
        self._drained = threading.Event()
        self._direct: Optional[Any] = None
        # worker-measured round-trip of the PREVIOUS heartbeat (ms) —
        # shipped on the next beat as a control-path latency sample for
        # the plane's gray-failure health scoring
        self._hb_rtt_ms: Optional[float] = None
        # guards IDLE→BUSY transitions so the poll loop and the direct server
        # can never run engine.inference concurrently on the same engines
        self._state_lock = threading.Lock()
        # shared serving claims (batcher-backed engines): count of direct
        # requests / queued jobs currently sharing decode rounds — they
        # coexist with each other up to load_control.max_concurrent_jobs
        # but never with an exclusive claim (PD stages, legacy engines)
        self._serving_jobs = 0
        self._job_pool: Optional[Any] = None
        self._job_pool_width = 16
        self._pool_inflight = 0
        self._active_jobs: set = set()
        # exclusive-needing work (PD stage / non-llm) was fetched while
        # other shared claims were live: back off from polling until this
        # deadline (or until the shared load drains) instead of
        # claim/fetch/releasing the same head-of-queue job every interval
        self._exclusive_defer_until = 0.0
        self._heartbeat_thread: Optional[threading.Thread] = None
        self._hour_window: List[float] = []       # job-start times, rolling hour
        self._last_job_done_at = 0.0
        self._released_once: set = set()          # jobs we declined once
        self._rng = random.Random(0xC0FFEE)
        # per-PROCESS incarnation id: registration sends it so the plane
        # can tell a fast restart (new boot_id on the same fingerprint →
        # the old incarnation's RUNNING jobs requeue immediately) from a
        # credential-blip re-register by the same live process (same
        # boot_id → running work stays put)
        self.boot_id = uuid.uuid4().hex
        # plane cohort: identity of the control-plane replica that answered
        # our last heartbeat (None single-plane / pre-first-beat). A CHANGE
        # means we failed over — the new plane holds no ACKed base for our
        # prefix-summary delta chain, so a full snapshot must be resynced.
        self._last_plane_id: Optional[str] = None
        self.stats: Dict[str, Any] = {
            "jobs_completed": 0, "jobs_failed": 0, "jobs_rejected": 0,
            "jobs_migrated": 0,
            "heartbeats": 0, "config_refetches": 0,
        }

    # -- registration (reference main.py:83-165) -----------------------------

    def register(self) -> None:
        if self.api.worker_id and self.api.auth_token and \
                self.api.verify_credentials():
            log.info("existing credentials valid for %s", self.api.worker_id)
        else:
            info = {
                "name": self.config.name,
                "region": self.config.region,
                "machine_fingerprint": MachineFingerprint().get_or_create(),
                "supported_types": list(self.config.task_types),
                "topology": self.topology.to_dict(),
                "supports_direct": self.config.direct.enabled,
                "direct_url": self.config.direct.public_url,
                "role": self.config.role,
                "data_plane_url": self.config.pd_data_plane_url,
                "boot_id": self.boot_id,
            }
            data = self.api.register(info)
            if self._on_credentials:
                self._on_credentials(
                    {
                        "worker_id": data["worker_id"],
                        "auth_token": data["auth_token"],
                        "refresh_token": data["refresh_token"],
                        "signing_secret": data["signing_secret"],
                    }
                )
            log.info("registered as %s", data["worker_id"])
        self._fetch_remote_config()

    def _fetch_remote_config(self) -> None:
        """Server-pushed load control wins over local values
        (reference main.py:151-165; worker_config.py:85-107)."""
        try:
            remote = self.api.fetch_remote_config()
        except APIError as exc:
            log.warning("remote config fetch failed: %s", exc)
            return
        self.stats["config_refetches"] += 1
        self.config.config_version = int(remote.get("version", 0))
        lc = remote.get("load_control") or {}
        for key in (
            "acceptance_rate", "max_concurrent_jobs", "max_jobs_per_hour",
            "hbm_limit_fraction", "cooldown_seconds",
        ):
            if key in lc and lc[key] is not None:
                setattr(self.config.load_control, key, lc[key])
        if lc.get("working_hours"):
            self.config.load_control.working_hours = tuple(lc["working_hours"])
        if lc.get("job_type_weights"):
            self.config.load_control.job_type_weights = dict(
                lc["job_type_weights"]
            )
        serving = remote.get("serving")
        if isinstance(serving, dict) and serving:
            # server-pushed SLO retune: batcher knobs (target_step_ms,
            # max_horizon, queue limits) apply to LIVE batchers between
            # rounds — no engine reload, no dropped requests
            for eng in self.engines.values():
                apply = getattr(eng, "apply_serving_config", None)
                if apply is None:
                    continue
                try:
                    apply(dict(serving))
                except Exception:  # noqa: BLE001 — a bad push must not kill the worker
                    log.warning("serving config push rejected",
                                exc_info=True)

    # -- engines (reference main.py:234-261) ---------------------------------

    def load_engines(self) -> None:
        loaded: List[str] = []
        for task_type in list(self.config.task_types):
            try:
                cfg = self.config.engine_for(task_type)
                eng = create_engine(task_type, cfg.model_dump())
                eng.load_model()
                self.engines[task_type] = eng
                loaded.append(task_type)
            except (EngineLoadError, KeyError) as exc:
                log.warning("dropping task type %s: %s", task_type, exc)
        self.config.task_types = loaded
        if not loaded:
            raise EngineLoadError("no engine loaded for any task type")

    # -- heartbeat (reference main.py:263-311) -------------------------------

    def _spec_engine_stats(self) -> Optional[Dict[str, Any]]:
        """Speculation-efficiency counters of any engine running the
        integrated speculative decode mode — ride the heartbeat so the
        control plane's ``/metrics`` surfaces accept-rate and tokens-per-
        step per worker. None when nothing speculates (no payload bloat)."""
        out: Dict[str, Any] = {}
        for eng in self.engines.values():
            core = getattr(eng, "engine", None)
            if core is None or \
                    getattr(getattr(core, "cfg", None), "speculative",
                            None) is None:
                continue
            s = core.get_stats()
            for k in ("spec_accepted", "spec_drafted", "spec_slot_steps",
                      "spec_emitted"):
                out[k] = out.get(k, 0) + int(s.get(k, 0) or 0)
        if not out:
            return None
        # rates derived from the SUMMED counters so the gauges always agree
        # with the counter ratios when several engines speculate
        out["spec_accept_rate"] = (
            out["spec_accepted"] / out["spec_drafted"]
            if out.get("spec_drafted") else 0.0
        )
        out["spec_tokens_per_step"] = (
            out["spec_emitted"] / out["spec_slot_steps"]
            if out.get("spec_slot_steps") else 0.0
        )
        return out

    def _pressure_engine_stats(self) -> Optional[Dict[str, Any]]:
        """KV-pressure recovery counters of every loaded paged engine
        (cumulative preemptions / resumes / pressure events) — ride the
        heartbeat so the control plane's ``/metrics`` shows which workers
        run their pools hot. None when no loaded engine exposes the
        counters (payload stays lean for non-LLM workers)."""
        out: Dict[str, int] = {}
        for eng in self.engines.values():
            core = getattr(eng, "engine", None)
            stats = getattr(core, "stats", None)
            if isinstance(stats, dict):
                for k in ("preemptions", "resumes", "kv_pressure_events"):
                    if k in stats:
                        out[k] = out.get(k, 0) + int(stats.get(k, 0) or 0)
            # abandoned streamed-handoff sessions purged by the engine's
            # HandoffReceiver → kv_handoff_sessions_purged_total
            purged = getattr(eng, "handoff_sessions_purged", None)
            if purged:
                out["kv_handoff_sessions_purged"] = (
                    out.get("kv_handoff_sessions_purged", 0) + int(purged)
                )
        return out or None

    def _pd_engine_stats(self) -> Optional[Dict[str, Any]]:
        """PD handoff lifecycle counters of every loaded engine (sender
        outcomes, piece retries, receiver abort/purge reasons) — nested
        under heartbeat ``engine_stats["pd"]`` so the control plane's
        ``/metrics`` surfaces ``pd_handoffs_total{outcome}`` and
        ``pd_handoff_bytes_total`` per worker. None when no engine has
        touched a handoff (payload stays lean off the PD path)."""
        out: Dict[str, int] = {}
        for eng in self.engines.values():
            fn = getattr(eng, "pd_wire_stats", None)
            if fn is None:
                continue
            try:
                s = fn()
            except Exception:  # noqa: BLE001 — never break the heartbeat
                continue
            for k, v in (s or {}).items():
                out[k] = out.get(k, 0) + int(v)
        return out or None

    def _kv_migrate_engine_stats(self) -> Optional[Dict[str, Any]]:
        """Cluster-KV migration counters of every loaded engine (pull
        outcomes, export service, bytes) — nested under heartbeat
        ``engine_stats["kv_migrate"]`` so the control plane's ``/metrics``
        surfaces ``kv_migrations_total{outcome}`` and
        ``kv_migration_bytes_total`` per worker. None when nothing ever
        migrated (payload stays lean)."""
        out: Dict[str, int] = {}
        for eng in self.engines.values():
            fn = getattr(eng, "kv_migrate_wire_stats", None)
            if fn is None:
                continue
            try:
                s = fn()
            except Exception:  # noqa: BLE001 — never break the heartbeat
                continue
            for k, v in (s or {}).items():
                out[k] = out.get(k, 0) + int(v)
        return out or None

    def _kv_spill_engine_stats(self) -> Optional[Dict[str, Any]]:
        """Spill-tier IO health of every loaded engine (put/get errors,
        corrupt-entry quarantines, breaker states/trips, refused corrupt
        checkpoints) — nested under heartbeat ``engine_stats["kv_spill"]``
        so the control plane's ``/metrics`` surfaces
        ``kv_spill_errors_total{tier}``, ``spill_quarantined_total`` and
        ``io_breaker_state{tier}`` per worker. None while every counter is
        zero and all breakers are closed (payload stays lean)."""
        out: Dict[str, int] = {}
        for eng in self.engines.values():
            fn = getattr(eng, "kv_spill_wire_stats", None)
            if fn is None:
                continue
            try:
                s = fn()
            except Exception:  # noqa: BLE001 — never break the heartbeat
                continue
            for k, v in (s or {}).items():
                if k.endswith("_state"):
                    # breaker state is a gauge: report the sickest engine
                    out[k] = max(out.get(k, 0), int(v))
                else:
                    out[k] = out.get(k, 0) + int(v)
        return out or None

    def _batcher_stats(self) -> Optional[Dict[str, Any]]:
        """Live batcher serving stats of every batcher-backed engine
        (occupancy, queue depth, chunked admissions, preemption counters)
        — nested under heartbeat ``engine_stats["batcher"]`` so the control
        plane's ``/metrics`` shows how hot each worker's batch runs. None
        when no engine serves through a batcher (payload stays lean)."""
        out: Dict[str, Any] = {}
        for eng in self.engines.values():
            fn = getattr(eng, "serving_stats", None)
            if fn is None:
                continue
            try:
                s = fn()
            except Exception:  # noqa: BLE001 — never break the heartbeat
                continue
            if not s:
                continue
            for k in ("submitted", "completed", "rejected", "admitted",
                      "decode_rounds", "chunked_admissions",
                      "batched_waves", "preemptions", "resumes",
                      "preempted_too_often", "cancelled", "migrated",
                      "abandoned", "abandoned_predictive"):
                out[k] = out.get(k, 0) + int(s.get(k, 0) or 0)
            for k in ("queue_depth", "active_slots"):
                out[k] = out.get(k, 0) + int(s.get(k, 0) or 0)
            if s.get("avg_occupancy") is not None:
                out["avg_occupancy"] = round(
                    float(s.get("avg_occupancy") or 0.0), 3
                )
            if s.get("horizon") is not None:
                out["horizon"] = float(s["horizon"])
        if out:
            # shared-claim ceiling: lets the scheduler GRADE this worker's
            # load (active + queued vs capacity) instead of reading the
            # binary BUSY flag that lies for concurrent batcher serving
            out["capacity"] = self.serving_capacity()
        return out or None

    def _flight_engine_stats(self) -> Optional[Dict[str, Any]]:
        """Flight-recorder payload of every loaded engine (cumulative
        timeline/drop counters + the bounded ring of recently-completed
        timelines) — nested under heartbeat ``engine_stats["flight"]``.
        The plane delta-anchors the counters and idempotently merges the
        ring (direct streams never pass complete_job, so this is their
        only route to the merged timeline store). None when nothing was
        ever traced (payload stays lean)."""
        out: Dict[str, Any] = {}
        recent: List[Dict[str, Any]] = []
        for eng in self.engines.values():
            fn = getattr(eng, "flight_wire_stats", None)
            if fn is None:
                continue
            try:
                s = fn()
            except Exception:  # noqa: BLE001 — never break the heartbeat
                continue
            if not s:
                continue
            for k in ("timelines", "events_dropped"):
                out[k] = out.get(k, 0) + int(s.get(k, 0) or 0)
            r = s.get("recent")
            if isinstance(r, list):
                recent.extend(r)
        if not out:
            return None
        if recent:
            out["recent"] = recent[-16:]
        return out

    def _prefix_summary_payload(self) -> Optional[tuple]:
        """(engine, wire payload) of the first engine advertising a radix
        summary this beat — None when every engine is already in sync
        with the control plane (no payload bloat)."""
        for eng in self.engines.values():
            fn = getattr(eng, "prefix_summary_wire", None)
            if fn is None:
                continue
            try:
                payload = fn()
            except Exception:  # noqa: BLE001 — never break the heartbeat
                continue
            if payload:
                return eng, payload
        return None

    def _collect_checkpoints(self) -> List[Dict[str, Any]]:
        """Portable checkpoints of every in-flight generation across loaded
        engines — piggybacked on heartbeats so a sequence survives this
        worker's death: the control plane attaches the latest checkpoint to
        the requeued job / adoptable stream and the replacement worker
        resumes instead of regenerating."""
        out: List[Dict[str, Any]] = []
        for eng in self.engines.values():
            fn = getattr(eng, "checkpoint_live", None)
            if fn is None:
                continue
            try:
                out.extend(fn() or [])
            except Exception:  # noqa: BLE001 — never break the heartbeat
                log.debug("checkpoint collection failed", exc_info=True)
        return out

    def _heartbeat_once(self) -> None:
        summary_eng = None
        for eng in self.engines.values():
            # PD housekeeping on the heartbeat cadence: adopted slots
            # whose decode stage never came (flow re-prefilled elsewhere)
            # age out instead of pinning KV until the next handoff message
            fn = getattr(eng, "pd_maintain", None)
            if fn is not None:
                try:
                    fn()
                except Exception:  # noqa: BLE001 — never break the beat
                    pass
        try:
            extra: Dict[str, Any] = {}
            engine_stats: Dict[str, Any] = {}
            spec_stats = self._spec_engine_stats()
            if spec_stats:
                engine_stats.update(spec_stats)
            pressure_stats = self._pressure_engine_stats()
            if pressure_stats:
                engine_stats.update(pressure_stats)
            batcher_stats = self._batcher_stats()
            if batcher_stats:
                engine_stats["batcher"] = batcher_stats
            pd_stats = self._pd_engine_stats()
            if pd_stats:
                engine_stats["pd"] = pd_stats
            kv_spill_stats = self._kv_spill_engine_stats()
            if kv_spill_stats:
                engine_stats["kv_spill"] = kv_spill_stats
            kvmig_stats = self._kv_migrate_engine_stats()
            if kvmig_stats:
                engine_stats["kv_migrate"] = kvmig_stats
            flight_stats = self._flight_engine_stats()
            if flight_stats:
                engine_stats["flight"] = flight_stats
            direct = self._direct
            if direct is not None:
                # gray-failure telemetry: per-request direct latencies /
                # served-5xx deltas feed the plane's health scoring; the
                # cumulative hedge-cancel counter delta-anchors
                # hedges_total{outcome="cancelled"}. Omitted while empty
                # so quiet beats stay byte-identical to pre-round ones.
                try:
                    ds = direct.wire_stats()
                except Exception:  # noqa: BLE001 — never break the beat
                    ds = None
                if ds and (ds.get("recent_ms") or ds.get("new_errors")
                           or ds.get("hedge_cancels")):
                    engine_stats["direct"] = ds
            summary = self._prefix_summary_payload()
            if summary is not None:
                # radix summary (full or delta) for cache-aware routing;
                # committed as server-known only after the round-trip
                # succeeds (deltas are diffed against an ACKed base)
                summary_eng, engine_stats["prefix_summary"] = summary
            if any(getattr(eng, "prefix_hot", None) is not None
                   for eng in self.engines.values()):
                # channel-alive marker: lets the server keep our advertised
                # summary fresh on payload-less beats (in sync) without
                # immortalizing summaries of workers that restarted with
                # the channel off
                engine_stats["prefix_summary_live"] = True
            if engine_stats:
                extra["engine_stats"] = engine_stats
            checkpoints = self._collect_checkpoints()
            if checkpoints:
                extra["checkpoints"] = checkpoints
            with self._state_lock:
                active = list(self._active_jobs)
                current_job_id = self.current_job_id
            if len(active) > 1:
                # concurrent shared jobs: current_job_id can only carry one
                # claim — report the full set so the server's stale-claim
                # guard covers every in-flight job, not an arbitrary one
                extra["active_job_ids"] = active
            if self._hb_rtt_ms is not None:
                # previous beat's measured round-trip: a worker whose
                # control path has gone gray (slow NIC, throttled host)
                # reports it here even when no direct traffic lands
                extra["hb_rtt_ms"] = round(self._hb_rtt_ms, 3)
            hb_t0 = time.perf_counter()
            resp = self.api.heartbeat(
                status=self.state.value,
                config_version=self.config.config_version,
                current_job_id=current_job_id,
                loaded_models=[
                    getattr(e, "model_name", None) or str(type(e).__name__)
                    for e in self.engines.values()
                ],
                stats={
                    k: self.stats[k]
                    for k in ("jobs_completed", "jobs_failed")
                },
                **extra,
            )
            self._hb_rtt_ms = (time.perf_counter() - hb_t0) * 1000.0
            self.stats["heartbeats"] += 1
            if summary_eng is not None:
                if resp.get("prefix_summary_applied") is False:
                    # statically un-ingestable (version/basis skew): stop
                    # shipping summaries this plane can never apply
                    summary_eng.prefix_summary_disable()
                elif resp.get("prefix_summary_resync") is False:
                    # explicit "applied": commit the pending snapshot
                    summary_eng.prefix_summary_ack()
                else:
                    # asked to resync, OR the server never answered for
                    # the payload (engine_stats dropped oversize, legacy
                    # plane): acking would commit a base the server does
                    # not hold — fall back to a full snapshot
                    summary_eng.prefix_summary_resync()
                summary_eng = None
            plane_id = resp.get("plane_id")
            if isinstance(plane_id, str) and plane_id:
                if self._last_plane_id is not None \
                        and plane_id != self._last_plane_id:
                    # plane failover: a DIFFERENT replica answered this
                    # beat. Its registry has no ACKed base for our delta
                    # chain (and may hold nothing at all for us) — force a
                    # full-snapshot resync now, even on in-sync beats that
                    # carry no payload, so affinity routing converges
                    # within one round-trip instead of staying blind until
                    # the next cache mutation. Runs AFTER the ack block:
                    # an ack from the new plane must not commit a base it
                    # only just learned.
                    log.info(
                        "control plane changed (%s -> %s); resyncing "
                        "prefix summary", self._last_plane_id, plane_id,
                    )
                    self.stats["plane_failovers"] = \
                        self.stats.get("plane_failovers", 0) + 1
                    for eng in self.engines.values():
                        fn = getattr(eng, "prefix_summary_resync", None)
                        if fn is not None:
                            try:
                                fn()
                            except Exception:  # noqa: BLE001 — advisory
                                pass
                self._last_plane_id = plane_id
            hints = resp.get("kv_replicate")
            if hints:
                # proactive prefix replication (round 20): the plane
                # predicts a storm for prefixes we don't hold — hand the
                # hints to the first migrate-capable engine, which pulls
                # on a daemon thread under the reactive driver's own
                # budget/backoff (never in this heartbeat loop)
                for eng in self.engines.values():
                    fn = getattr(eng, "kv_replicate", None)
                    if fn is None:
                        continue
                    try:
                        if fn(hints):
                            self.stats["kv_replicate_hints"] = \
                                self.stats.get("kv_replicate_hints", 0) \
                                + len(hints)
                            break
                    except Exception:  # noqa: BLE001 — advisory prefetch
                        pass
            if resp.get("stale_job") and self.current_job_id:
                # the server requeued our claim (we looked dead): the
                # in-flight inference cannot be cancelled mid-graph, but
                # flag it loudly — the eventual complete_job will hit the
                # 409/duplicate path and the result will be discarded
                log.warning(
                    "server reports job %s is no longer ours (requeued "
                    "after a heartbeat gap); finishing as zombie work",
                    self.current_job_id,
                )
                self.stats["stale_claims"] = \
                    self.stats.get("stale_claims", 0) + 1
            for jid in resp.get("stale_jobs") or []:
                log.warning(
                    "server reports job %s is no longer ours (requeued "
                    "after a heartbeat gap); finishing as zombie work",
                    jid,
                )
                self.stats["stale_claims"] = \
                    self.stats.get("stale_claims", 0) + 1
            if resp.get("config_changed"):
                self._fetch_remote_config()
        except APIError as exc:
            if summary_eng is not None:
                # the beat carrying our summary delta was lost: the server
                # never applied it, so the next delta's base would be wrong
                # — fall back to a full snapshot
                try:
                    summary_eng.prefix_summary_resync()
                except Exception:  # noqa: BLE001
                    pass
            if exc.status == 401:
                try:
                    self.api.refresh_credentials()
                except APIError:
                    log.error("token refresh failed; re-registering")
                    self.api.auth_token = None
                    try:
                        self.register()
                    except APIError as reg_exc:
                        log.error("re-registration failed: %s", reg_exc)
            else:
                log.warning("heartbeat failed: %s", exc)

    def _heartbeat_loop(self) -> None:
        while not self._shutdown.wait(self.config.heartbeat_interval_s):
            try:
                self._heartbeat_once()
            except Exception:  # noqa: BLE001 - the thread must survive
                # outages (even re-registration failing); next tick retries
                log.exception("heartbeat iteration failed")

    # -- load control (reference worker_config.py:195, main loop gates) ------

    def gates_open(self, now: Optional[float] = None) -> bool:
        """Job-independent load-control gates, checked BEFORE claiming a job
        so a gated worker never pulls work it will bounce back (working
        hours, cooldown, hourly cap, global acceptance sampling)."""
        lc = self.config.load_control
        now = time.time() if now is None else now
        if lc.working_hours:
            start_h, end_h = lc.working_hours
            hour = time.localtime(now).tm_hour
            inside = (
                start_h <= hour < end_h if start_h <= end_h
                else hour >= start_h or hour < end_h
            )
            if not inside:
                return False
        if lc.cooldown_seconds > 0 and \
                now - self._last_job_done_at < lc.cooldown_seconds:
            return False
        if lc.max_jobs_per_hour > 0:
            with self._state_lock:
                # prune + read under the lock: pool/direct threads append
                # concurrently via note_job_done, and a rebind would drop
                # their append on the floor
                self._hour_window = [
                    t for t in self._hour_window if now - t < 3600
                ]
                if len(self._hour_window) >= lc.max_jobs_per_hour:
                    return False
        if lc.acceptance_rate < 1.0 and self._rng.random() > lc.acceptance_rate:
            return False
        return True

    def should_accept_job(self, job: Dict[str, Any],
                          now: Optional[float] = None) -> bool:
        """Full admission check (gates + per-type weight). The type-weight
        throttle is one-shot per job: a job this worker already released once
        is accepted on re-encounter, so a probabilistic throttle can delay
        head-of-queue work but never starve it (release→re-claim ping-pong)."""
        if not self.gates_open(now=now):
            return False
        lc = self.config.load_control
        job_id = job.get("id")
        if job_id and job_id in self._released_once:
            return True
        weight = lc.job_type_weights.get(job.get("type", ""), 1.0)
        if weight < 1.0 and self._rng.random() > weight:
            return False
        return True

    def note_job_done(self, started: float) -> None:
        """Load-control bookkeeping shared by queued AND direct jobs —
        called from pool/direct threads concurrently."""
        with self._state_lock:
            self._last_job_done_at = time.time()
            self._hour_window.append(started)

    # -- busy-state acquisition (poll loop vs direct server) -----------------

    def try_begin_job(self) -> bool:
        """Atomically claim the worker for one EXCLUSIVE inference
        (IDLE→BUSY). Returns False when busy/draining — the caller must
        back off. Exclusive claims never coexist with shared serving
        claims (``try_begin_serving``), so engines without a batcher are
        never driven concurrently."""
        with self._state_lock:
            if self.state != WorkerState.IDLE:
                return False
            self.state = WorkerState.BUSY
            return True

    def end_job(self) -> None:
        with self._state_lock:
            if self.state == WorkerState.BUSY:
                self.state = WorkerState.IDLE

    def serving_capacity(self) -> int:
        """Concurrent shared-claim ceiling — server-pushed
        ``load_control.max_concurrent_jobs`` (the batcher's queue_limit
        guards depth beyond it)."""
        return max(1, int(self.config.load_control.max_concurrent_jobs or 1))

    def try_begin_serving(self) -> bool:
        """Claim ONE shared serving slot (batcher-backed engines): the
        request joins the engine's continuous batch instead of waiting for
        an idle worker. Shared claims coexist with each other up to
        :meth:`serving_capacity` but never with an exclusive claim, and a
        draining worker accepts nothing."""
        with self._state_lock:
            if self.state == WorkerState.IDLE:
                self.state = WorkerState.BUSY
                self._serving_jobs = 1
                return True
            if self.state == WorkerState.BUSY and self._serving_jobs > 0 \
                    and self._serving_jobs < self.serving_capacity():
                self._serving_jobs += 1
                return True
            return False

    def end_serving(self) -> None:
        with self._state_lock:
            if self._serving_jobs > 0:
                self._serving_jobs -= 1
                if self._serving_jobs == 0 and \
                        self.state == WorkerState.BUSY:
                    self.state = WorkerState.IDLE

    def _upgrade_serving_to_exclusive(self) -> bool:
        """Convert OUR shared claim into the exclusive claim — only
        possible when no other shared work is in flight (the poll loop
        uses this when a fetched job turns out to need exclusivity)."""
        with self._state_lock:
            if self.state == WorkerState.BUSY and self._serving_jobs == 1:
                self._serving_jobs = 0
                return True
            return False

    # -- job processing (reference main.py:335-402) --------------------------

    def _report_completion(self, job_id: str, success: bool,
                           result: Optional[Dict[str, Any]] = None,
                           error: Optional[str] = None,
                           deadline_s: float = 45.0,
                           **complete_kw: Any) -> Dict[str, Any]:
        """Report a terminal job outcome, riding out transient plane-side
        store brownouts (round 19): the plane answers a failed durable
        write with a retryable 503 (``store_unavailable`` + Retry-After),
        and the client's own 5xx ladder exhausts well inside a multi-
        second disk_full window — so keep re-reporting until
        ``deadline_s``. Safe to repeat: terminal completes are idempotent
        on the server (duplicates answer ``{"ok": true}``) and zombie
        results are epoch-fenced with a 409, which is NOT retried."""
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                return self.api.complete_job(
                    job_id, success=success, result=result, error=error,
                    **complete_kw
                )
            except APIError as exc:
                if exc.status < 500 or self._shutdown.is_set() \
                        or time.monotonic() > deadline:
                    raise
                log.warning("completion report for %s bounced (%s); "
                            "retrying", job_id, exc)
                time.sleep(0.5)

    def process_job(self, job: Dict[str, Any],
                    release: Optional[Callable[[], None]] = None) -> None:
        """Run one claimed job. Caller must hold a claim: the exclusive
        BUSY state (``try_begin_job``, the default release) or a shared
        serving slot (``try_begin_serving`` — pass ``release=end_serving``).

        Failover-capable engines get a ``_failover_ctx`` (job id, assignment
        epoch, and the claim's server-held checkpoint, if any): they resume
        a requeued generation instead of regenerating, register it for
        heartbeat checkpointing, and — on graceful drain — freeze it and
        raise :class:`JobMigrated`, which hands the checkpoint back to the
        control plane WITHOUT burning a retry. Completions carry the
        assignment epoch so a zombie's late result is fenced with a 409."""
        job_id = job["id"]
        task_type = job.get("type", "llm")
        engine = self.engines.get(task_type)
        with self._state_lock:
            self._active_jobs.add(job_id)
            self.current_job_id = job_id
        started = time.time()
        epoch = int(job.get("assignment_epoch") or 0)
        fenced = "assignment_epoch" in job
        complete_kw: Dict[str, Any] = (
            {"assignment_epoch": epoch} if fenced else {}
        )
        try:
            if engine is None:
                raise RuntimeError(f"no engine loaded for type {task_type!r}")
            params = dict(job.get("params") or {})
            # reserved keys: never accept a client-submitted failover
            # context or flight stamps from job params — the worker mints
            # them below
            params.pop("_failover_ctx", None)
            params.pop("_flight_picked_up_ts", None)
            params.pop("_flight_tl", None)
            if params.get("trace_id"):
                # flight recorder: the poll-pickup instant (claim landed →
                # engine dispatched) — the engine adopts it into the
                # request's timeline, closing the server-side queue-wait
                # phase at the worker boundary
                params["_flight_picked_up_ts"] = time.time()
            if job.get("priority") is not None:
                # control-plane priority reaches the batcher's admission
                # heap (higher-priority jobs admit first, and KV-pressure
                # victims are picked lowest-priority-first)
                params.setdefault("priority", job.get("priority"))
            if getattr(engine, "supports_failover", False):
                params["_failover_ctx"] = {
                    "key": job_id, "kind": "job", "epoch": epoch,
                    "checkpoint": job.get("checkpoint"),
                }
            result = engine.inference(params)
            # the completion report gets its own fault domain: the result
            # is already computed, so a bounced POST (plane store
            # brownout → typed store_unavailable 503, or a raw 5xx past
            # the client's retry ladder) must NOT reclassify the JOB as
            # failed — ride out the window and report the success
            try:
                self._report_completion(
                    job_id, success=True, result=result, **complete_kw
                )
            except APIError:
                # window outlasted the deadline: leave the claim for the
                # sweeps/epoch fence to requeue — a rerun beats a
                # spuriously FAILED job with a perfectly good result
                log.error("could not report completion for job %s "
                          "(store brownout outlasted retries)", job_id)
            else:
                with self._state_lock:
                    self.stats["jobs_completed"] += 1
        except JobMigrated as mig:
            log.info("job %s migrated on drain (%d tokens checkpointed)",
                     job_id, mig.tokens)
            try:
                self.api.checkpoint_job(
                    job_id, epoch, mig.checkpoint, migrate=True
                )
            except APIError:
                # the server's offline requeue still reruns the job from
                # the last heartbeat-piggybacked checkpoint
                log.error("could not push drain checkpoint for %s", job_id)
            with self._state_lock:
                self.stats["jobs_migrated"] += 1
        except Exception as exc:  # noqa: BLE001 - job failure is a result
            if self._shutdown.is_set():
                # the worker is dying (hard kill / unload), not the job:
                # every in-flight batcher future resolves "batcher
                # stopped" and racing those reports against api.close()
                # used to let a few land as terminal FAILURES — marking
                # work failed that any other replica can run. Release the
                # claim instead (conditional RUNNING→QUEUED, retry_count
                # untouched); if the plane is already unreachable the
                # heartbeat-timeout sweep / boot_id fence requeues it
                # anyway. (Round-12 overload suite caught this: a kill
                # mid-burst failed the burst's tail.)
                log.warning("job %s aborted by shutdown (%s): releasing",
                            job_id, exc)
                try:
                    self.api.release_job(job_id)
                except Exception:  # noqa: BLE001 — the sweeps own it then
                    pass
                with self._state_lock:
                    self.stats["jobs_released_on_shutdown"] = \
                        self.stats.get("jobs_released_on_shutdown", 0) + 1
                return
            log.exception("job %s failed", job_id)
            code = getattr(exc, "error_code", None)
            if code:
                # machine-readable failure class (ServingError —
                # request_timeout vs shed_overload) rides the job result
                # next to the human-readable error text
                complete_kw["result"] = {"error_code": str(code)}
            try:
                self._report_completion(
                    job_id, success=False, error=str(exc), **complete_kw
                )
            except APIError:
                log.error("could not report failure for job %s", job_id)
            with self._state_lock:
                self.stats["jobs_failed"] += 1
        finally:
            self.note_job_done(started)
            with self._state_lock:
                self._active_jobs.discard(job_id)
                self.current_job_id = next(iter(self._active_jobs), None)
            (release or self.end_job)()

    def _llm_serving_active(self) -> bool:
        """True when the llm engine serves through a live batcher — queued
        llm jobs then run under SHARED claims and concurrent jobs share
        decode rounds."""
        serving = getattr(self.engines.get("llm"), "serving", None)
        return serving is not None and getattr(serving, "active", False)

    def _job_runs_shared(self, job: Dict[str, Any]) -> bool:
        """A fetched job may join the continuous batch iff it targets the
        batcher-backed llm engine. PD stage jobs ride shared claims too
        (round 11 — the split topology as a LIVE deployment mode): a
        decode-fleet worker co-batches many adopted sequences through
        ``batcher.adopt_slot``, and a prefill-fleet worker overlaps one
        job's KV push with the next job's prefill — an exclusive claim per
        stage would serialize the very fleets the split exists to scale.
        The engine work inside each stage is already serialized with live
        decode rounds (engine lock + ``run_exclusive``). Non-batcher
        engines keep the legacy exclusive claim."""
        if job.get("type", "llm") != "llm":
            return False
        return self._llm_serving_active()

    def _dispatch_shared(self, job: Dict[str, Any]) -> None:
        """Run a shared-claim job on the job pool: the poll loop returns to
        polling immediately, so several queued jobs decode concurrently in
        one batch (the claim was taken by the caller; process_job's finally
        releases it)."""
        if self._job_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._job_pool = ThreadPoolExecutor(
                max_workers=self._job_pool_width, thread_name_prefix="job"
            )
        with self._state_lock:
            self._pool_inflight += 1

        def run() -> None:
            try:
                self.process_job(job, release=self.end_serving)
            except Exception:  # noqa: BLE001 — pool thread must not die silently
                log.exception("shared job %s crashed", job.get("id"))
            finally:
                with self._state_lock:
                    self._pool_inflight -= 1

        self._job_pool.submit(run)

    def _poll_once(self) -> bool:
        """One poll iteration; returns True if a job was processed (or
        dispatched to the shared pool)."""
        if not self.gates_open():  # gated: don't even claim work
            return False
        shared_mode = self._llm_serving_active()
        if shared_mode:
            if self._pool_inflight >= self._job_pool_width:
                # every pool thread is busy: a further claim would start
                # its server-side clock while sitting unstarted in the
                # pool queue (stale-sweep requeue → duplicate compute)
                return False
            with self._state_lock:
                other_shared = self._serving_jobs > 0
            if other_shared and time.time() < self._exclusive_defer_until:
                # head-of-queue work needs exclusivity we cannot grant
                # while shared claims run: stop the claim/release churn
                # and give other workers (or our own drain) a window
                return False
            # claim a shared slot up front: queued jobs keep flowing while
            # direct streams (other shared claims) are in flight
            if not self.try_begin_serving():
                return False
            release = self.end_serving
        else:
            if not self.try_begin_job():  # direct inference in flight / draining
                return False
            release = self.end_job
        job = None
        try:
            job = self.api.fetch_next_job()
        except APIError as exc:
            log.warning("poll failed: %s", exc)
        if job is None:
            release()
            return False
        if not self.should_accept_job(job):
            self.stats["jobs_rejected"] += 1
            self._released_once.add(job["id"])
            try:
                # requeue, don't fail: another worker can run it, and WE will
                # take it if it comes back (one-shot throttle, no starvation)
                self.api.release_job(job["id"])
            except APIError:
                pass
            release()
            return False
        self._released_once.discard(job["id"])
        if not shared_mode:
            self.process_job(job)
            return True
        if self._job_runs_shared(job):
            self._dispatch_shared(job)   # claim travels with the job
            return True
        # the fetched job needs exclusivity (PD stage / non-llm engine):
        # upgrade — only possible when we hold the sole shared claim
        if self._upgrade_serving_to_exclusive():
            self.process_job(job)
            return True
        # other shared work in flight: hand the job back for another
        # worker rather than stalling the batch, and back off from
        # polling briefly (it would come straight back each interval)
        try:
            self.api.release_job(job["id"])
        except APIError:
            pass
        self.end_serving()
        self._exclusive_defer_until = time.time() + max(
            5.0, 5 * self.config.poll_interval_s
        )
        return False

    def _main_loop(self) -> None:
        while not self._shutdown.is_set():
            busy = self._poll_once()
            if not busy:
                self._shutdown.wait(self.config.poll_interval_s)
        self._drained.set()

    # -- lifecycle (reference main.py:404-496) -------------------------------

    def start(self, install_signal_handlers: bool = True,
              block: bool = True) -> None:
        self.register()
        self.load_engines()
        for eng in self.engines.values():
            # stream-checkpoint cadence between heartbeats (llm engine):
            # admission + every checkpoint_interval_tokens
            if hasattr(eng, "checkpoint_sink"):
                eng.checkpoint_sink = self.push_stream_checkpoint
        if self.config.direct.enabled:
            from .direct_server import DirectServer

            self._direct = DirectServer(
                self, host=self.config.direct.host,
                port=self.config.direct.port,
            )
            self._direct.start()
        if self.config.pd_data_plane_url and "llm" in self.engines:
            # decode-capable PD worker: run a data plane so prefill peers
            # can push KV handoffs (server/pd_flow.py stage 2)
            from urllib.parse import urlparse

            from ..comm.data_plane import DataPlaneServer

            llm_eng = self.engines["llm"]
            port = urlparse(self.config.pd_data_plane_url).port or 8472
            self._pd_plane = DataPlaneServer(
                _PDReceiverShim(llm_eng), port=port,
                kv_receiver=llm_eng.kv_receiver,
                kv_exporter=getattr(llm_eng, "kv_export", None),
            )
            self._pd_plane.start()
        self.state = WorkerState.IDLE
        if install_signal_handlers:
            try:
                signal.signal(signal.SIGTERM, self._signal_handler)
                signal.signal(signal.SIGINT, self._signal_handler)
            except ValueError:  # pragma: no cover - non-main thread
                pass
        self._heartbeat_thread = threading.Thread(
            target=self._heartbeat_loop, name="heartbeat", daemon=True
        )
        self._heartbeat_thread.start()
        self._heartbeat_once()
        if block:
            self._main_loop()
            self._finalize_shutdown()

    def _signal_handler(self, signum: int, frame: Any) -> None:  # pragma: no cover
        log.info("signal %s: graceful shutdown", signum)
        self.request_shutdown()

    def request_shutdown(self) -> None:
        """Graceful drain (reference main.py:444-463): stop accepting,
        MIGRATE the in-flight generation instead of finishing it (failover-
        capable engines freeze at the next step boundary and the checkpoint
        requeues the job — seconds instead of a full generation's tail),
        then notify the server."""
        if self._shutdown.is_set():
            return
        with self._state_lock:
            self.state = WorkerState.DRAINING
        for eng in self.engines.values():
            interrupt = getattr(eng, "interrupt_live", None)
            if interrupt is not None:
                try:
                    interrupt()
                except Exception:  # noqa: BLE001
                    pass
        try:
            self.api.going_offline()
        except APIError:
            pass
        self._shutdown.set()

    # -- stream failover (direct server drives these) ------------------------

    def adopt_stream_checkpoint(self, stream_id: str
                                ) -> Optional[Dict[str, Any]]:
        """Fetch-and-fence a dropped stream's checkpoint from the control
        plane (epoch bumps to this worker). None when no checkpoint exists
        — the direct server then answers the resume with a 409."""
        try:
            return self.api.adopt_stream(stream_id)
        except APIError as exc:
            if exc.status == 404:
                return None
            raise

    def push_stream_checkpoint(self, entry: Dict[str, Any]) -> None:
        """Checkpoint sink for the llm engine's stream cadence: push one
        stream checkpoint (or its ``done`` retirement) to the control
        plane. Job-kind entries only ride heartbeats — pushing them here
        would double-report."""
        if entry.get("kind") != "stream":
            return
        self.api.checkpoint_stream(
            entry["key"], int(entry.get("epoch") or 0),
            entry.get("state"), done=bool(entry.get("done")),
        )

    def _finalize_shutdown(self) -> None:
        if self._job_pool is not None:
            # shared queued jobs: interrupt_live (request_shutdown) already
            # told them to freeze at the next step boundary — wait for the
            # JobMigrated checkpoints to land before reporting offline
            self._job_pool.shutdown(wait=True)
        try:
            requeued = self.api.offline()
            if requeued:
                log.info("server requeued jobs: %s", requeued)
        except APIError:
            pass
        self.state = WorkerState.OFFLINE
        if getattr(self, "_direct", None) is not None:
            self._direct.stop()
        if getattr(self, "_pd_plane", None) is not None:
            self._pd_plane.stop()
        if self._heartbeat_thread is not None:
            self._heartbeat_thread.join(timeout=5.0)
        for eng in self.engines.values():
            try:
                eng.unload()
            except Exception:  # noqa: BLE001
                pass
        self.api.close()

    # -- introspection -------------------------------------------------------

    def get_status(self) -> Dict[str, Any]:
        return {
            "worker_id": self.api.worker_id,
            "state": self.state.value,
            "current_job_id": self.current_job_id,
            "task_types": list(self.config.task_types),
            "topology": self.topology.to_dict(),
            "stats": dict(self.stats),
        }


def main() -> None:  # pragma: no cover - manual entry point
    import argparse

    from ..utils.config import load_worker_config

    ap = argparse.ArgumentParser(description="TPU inference worker")
    ap.add_argument("--config", default="config.yaml")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    cfg = load_worker_config(args.config)
    Worker(cfg).start()


if __name__ == "__main__":  # pragma: no cover
    main()
