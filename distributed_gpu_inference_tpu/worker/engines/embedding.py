"""Embedding engine: mean-pooled final hidden states of the decoder.

The reference exposes embeddings as a task type in its engine registry
(``worker/engines/__init__.py`` task families) without a first-party
implementation (delegated to backends). Here it is first-party: one jitted
forward over the same Llama params as the LLM engine, masked mean-pool of the
final-norm hidden states, L2-normalised — the standard decoder-as-embedder
recipe, all on the MXU.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import numpy as np

from .base import BaseEngine, EngineLoadError


class EmbeddingEngine(BaseEngine):
    """config keys: model, tokenizer / tokenizer_id, max_seq_len."""

    task_type = "embedding"

    def __init__(self, config: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(config)
        self.tokenizer = self.config.get("tokenizer")
        self._fwd = None
        self._params = None
        self._cfg = None

    def load_model(self) -> None:
        import jax
        import jax.numpy as jnp

        from ...models import llama
        from ...models.configs import get_model_config
        from ...models.loader import load_or_init_params

        model_name = self.config.get("model", "llama3-mini")
        self._cfg = get_model_config(model_name)
        self._params = load_or_init_params(
            self._cfg, checkpoint_path=self.config.get("checkpoint_path")
        )
        if self.tokenizer is None:
            tok_id = self.config.get("tokenizer_id")
            if tok_id:
                from .llm import _load_hf_tokenizer

                self.tokenizer = _load_hf_tokenizer(tok_id)
            else:
                from .llm import ByteTokenizer

                self.tokenizer = ByteTokenizer()
        max_len = int(self.config.get("max_seq_len", 512))
        cfg = self._cfg

        @functools.partial(jax.jit, static_argnames=())
        def embed(params, token_ids, lengths):
            # [B, S] -> hidden [B, S, H] (no KV needed: single full-seq pass)
            b, s = token_ids.shape
            positions = jnp.arange(s)[None, :].astype(jnp.int32)
            mask_valid = positions < lengths[:, None]
            positions = jnp.where(mask_valid, positions, -1)
            kv = llama.init_kv_pools(cfg, num_blocks=1 + b * ((s + 15) // 16),
                                     block_size=16)
            tables = (
                1 + jnp.arange(b * ((s + 15) // 16), dtype=jnp.int32)
            ).reshape(b, -1)
            out = llama.forward_chunk(
                cfg, params, token_ids, positions, kv, tables,
                jnp.zeros((b,), jnp.int32), block_size=16, last_only=False,
            )
            hidden = llama.rms_norm(
                out.hidden, params["final_norm"], cfg.rms_norm_eps,
                cfg.norm_offset,
            ).astype(jnp.float32)
            m = mask_valid[..., None].astype(jnp.float32)
            pooled = (hidden * m).sum(1) / jnp.maximum(m.sum(1), 1.0)
            return pooled / jnp.maximum(
                jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9
            )

        self._fwd = embed
        self._max_len = max_len
        self.loaded = True

    def unload(self) -> None:
        self._fwd = None
        self._params = None
        super().unload()

    def inference(self, params: Dict[str, Any]) -> Dict[str, Any]:
        if not self.loaded:
            raise EngineLoadError("engine not loaded")
        texts = params.get("texts")
        if texts is None:
            texts = [params.get("text") or params.get("prompt") or ""]
        import jax.numpy as jnp

        ids: List[List[int]] = [
            list(self.tokenizer.encode(t))[: self._max_len] for t in texts
        ]
        lengths = np.array([max(1, len(i)) for i in ids], np.int32)
        s = max(8, int(max(lengths)))
        batch = np.zeros((len(ids), s), np.int32)
        for r, seq in enumerate(ids):
            batch[r, : len(seq)] = seq
        out = np.asarray(
            self._fwd(self._params, jnp.asarray(batch), jnp.asarray(lengths))
        )
        total_tokens = int(lengths.sum())
        return {
            "embeddings": out.tolist(),
            "dim": int(out.shape[-1]),
            "usage": {"prompt_tokens": total_tokens,
                      "total_tokens": total_tokens},
        }
