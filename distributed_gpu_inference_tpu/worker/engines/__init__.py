"""Engine registry: pluggable per-task-type inference engines.

Behavioral parity with the reference's ``worker/engines/__init__.py``:
registry with lazy imports of heavy backends (:51-105), aliases (:66), and an
auto-pick order (:172-193). The reference's ladder was SGLang > vLLM >
native-Transformers; here the "native" engine IS the TPU-first path (jitted
paged-KV serving, ``runtime/engine.py``) so it is also the best one — the
registry survives for task-type dispatch (llm / embedding / image_gen /
vision / whisper) and for test doubles.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable, Dict, List, Optional

from .base import BaseEngine, EngineLoadError, GenerationConfig, GenerationResult

# task type → module path : class name (lazy, heavy deps import on first use)
ENGINE_REGISTRY: Dict[str, str] = {
    "llm": "distributed_gpu_inference_tpu.worker.engines.llm:TPULLMEngine",
    "embedding": (
        "distributed_gpu_inference_tpu.worker.engines.embedding:EmbeddingEngine"
    ),
    "image_gen": (
        "distributed_gpu_inference_tpu.worker.engines.image_gen:ImageGenEngine"
    ),
    "vision": "distributed_gpu_inference_tpu.worker.engines.vision:VisionEngine",
    "whisper": "distributed_gpu_inference_tpu.worker.engines.whisper:WhisperEngine",
}

# friendly aliases (reference __init__.py:66)
ALIASES: Dict[str, str] = {
    "text": "llm",
    "chat": "llm",
    "text-generation": "llm",
    "embed": "embedding",
    "embeddings": "embedding",
    "image": "image_gen",
    "txt2img": "image_gen",
    "vlm": "vision",
    "image_qa": "vision",
    "asr": "whisper",
    "speech": "whisper",
}

_OVERRIDES: Dict[str, Callable[..., BaseEngine]] = {}


def resolve_task_type(task_type: str) -> str:
    t = task_type.lower().strip()
    return ALIASES.get(t, t)


def register_engine(task_type: str, factory: Callable[..., BaseEngine]) -> None:
    """Test/extension hook: override a task type with a custom factory."""
    _OVERRIDES[resolve_task_type(task_type)] = factory


def available_task_types() -> List[str]:
    return sorted(set(ENGINE_REGISTRY) | set(_OVERRIDES))


def get_engine_class(task_type: str) -> Callable[..., BaseEngine]:
    t = resolve_task_type(task_type)
    if t in _OVERRIDES:
        return _OVERRIDES[t]
    spec = ENGINE_REGISTRY.get(t)
    if spec is None:
        raise KeyError(
            f"no engine for task type {task_type!r}; "
            f"known: {available_task_types()}"
        )
    module_path, _, cls_name = spec.partition(":")
    module = importlib.import_module(module_path)
    return getattr(module, cls_name)


def create_engine(task_type: str, config: Optional[Dict[str, Any]] = None
                  ) -> BaseEngine:
    """Instantiate (not yet loaded) the engine for a task type."""
    cls = get_engine_class(task_type)
    return cls(config or {})


__all__ = [
    "BaseEngine",
    "EngineLoadError",
    "GenerationConfig",
    "GenerationResult",
    "ENGINE_REGISTRY",
    "ALIASES",
    "available_task_types",
    "create_engine",
    "get_engine_class",
    "register_engine",
    "resolve_task_type",
]
