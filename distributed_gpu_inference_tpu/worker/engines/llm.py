"""TPU-native LLM engine: tokenizer + jitted paged-KV serving engine.

The reference's role split was ``worker/engines/llm.py`` (HF Transformers
generate) vs ``llm_vllm.py``/``llm_sglang.py`` (wrapped serving frameworks).
Here there is ONE first-party path: :class:`runtime.engine.TPUEngine` (jitted
prefill + multi-step decode over paged KV with prefix caching) IS the serving
framework, so this module only adds what the reference engines layered on
top — chat templating, tokenization, stop strings, and the
``GenerationResult`` surface.

Tokenizers are pluggable: pass ``tokenizer`` in config (anything with
``encode``/``decode``), name a HF tokenizer via ``tokenizer_id``, or fall
back to a deterministic byte-level tokenizer (hermetic tests / air-gapped
boxes — no network fetch, mirroring the reference's offline-test strategy).
"""

from __future__ import annotations

import asyncio
import queue as _queue_mod
import random
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import httpx

from ...runtime.batcher import (
    BatcherConfig,
    BatcherServing,
    RequestMigrated,
    synthesize_checkpoint,
)
from ...testing import faults as _faults
from ...utils.backoff import full_jitter_delay
from ...runtime.engine import EngineConfig, PreemptedSequence, TPUEngine
from ...runtime.flight import NULL_TIMELINE, timeline_for
from ...runtime.prefix_summary import TIER_HOST, TIER_SPILL, PrefixHotSet
from ...utils.config import ServingConfig
from ...utils.data_structures import InferenceRequest, SamplingParams
from .base import (
    EngineLoadError,
    GenerationConfig,
    GenerationResult,
    JobMigrated,
    LLMBaseEngine,
    ServingError,
)


def _raise_serving(resp: Any) -> None:
    """Raise the serving failure carried by an InferenceResponse,
    preserving the machine-readable ``error_code`` (request_timeout /
    shed_overload / …) so job results and SSE error events can surface
    the class, not just the message."""
    raise ServingError(resp.error,
                       error_code=getattr(resp, "error_code", None))

# Worker-YAML / remote-config serving knobs (``engines.llm.serving.*``) —
# THE SLO configuration surface measured by the round-5 frontier. The
# single source of truth for keys AND defaults is the pydantic-validated
# YAML surface, ``utils.config.ServingConfig``; this dict is derived from
# it so plain-dict engine construction (benchmarks, tests) can never
# drift from YAML-configured workers.
SERVING_DEFAULTS: Dict[str, Any] = ServingConfig().model_dump()

# remote-config ``serving`` keys that may retune a LIVE batcher (pushed via
# WorkerRemoteConfig; the compile-affecting admission knobs are excluded)
SERVING_REMOTE_KEYS: Dict[str, str] = {
    "target_step_ms": "target_step_latency_ms",
    "max_horizon": "max_multi_step",
    "min_horizon": "min_multi_step",
    "multi_step": "multi_step",
    "adaptive": "adaptive",
    "max_wait_ms": "max_wait_ms",
    "queue_limit": "queue_limit",
    "default_timeout_s": "default_timeout_s",
    "max_preemptions": "max_preemptions",
    "spec_max_batch": "spec_max_batch",
    "spec_max_active": "spec_max_active",
    # ragged rounds (round 6): remote-flippable so a fleet can A/B the
    # ragged vs legacy admission path live (None = auto, the default)
    "ragged": "ragged",
    # long-context round shaping: the per-round prefill token budget and
    # the per-admission chunk width are both read per-round (widths bucket
    # through compiled prefill_buckets), so they retune live without a
    # recompile — push them to trade 32k prefill throughput against
    # co-batched decode ITL
    "prefill_budget": "prefill_budget",
    "ragged_chunk": "ragged_chunk",
    # gray-failure round: hopeless-deadline abandonment is a policy read
    # per step-boundary scan — flip it live to shed doomed work fleet-wide
    "abandon_deadlines": "abandon_deadlines",
    "deadline_grace_s": "deadline_grace_s",
    # round 20: fire the same projection BEFORE the deadline passes
    "predictive_abandon": "predictive_abandon",
}


class ByteTokenizer:
    """Deterministic fallback: UTF-8 bytes offset past special ids.

    vocab = 256 + specials; id 0 = pad/bos, 1 = eos. Keeps the whole stack
    runnable hermetically (tests, benchmarks with random weights).
    """

    eos_token_id = 1
    bos_token_id = 0

    def __init__(self, offset: int = 4) -> None:
        self._offset = offset
        self.vocab_size = 256 + offset

    def encode(self, text: str) -> List[int]:
        return [b + self._offset for b in text.encode("utf-8")]

    def decode(self, ids: List[int]) -> str:
        # ids beyond the byte range (models with vocab > 256+offset emit
        # them under random weights) are dropped, not crashed on
        data = bytes(
            i - self._offset for i in ids
            if self._offset <= i < self._offset + 256
        )
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: List[Dict[str, str]]) -> str:
        parts = [f"<|{m.get('role', 'user')}|>{m.get('content', '')}"
                 for m in messages]
        return "".join(parts) + "<|assistant|>"


def _load_hf_tokenizer(tokenizer_id: str):
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(tokenizer_id)
    except Exception as exc:  # noqa: BLE001 — offline box, bad id, ...
        raise EngineLoadError(f"cannot load tokenizer {tokenizer_id!r}: {exc}")


class _StreamSplicer:
    """Per-snapshot token→chunk derivation shared by BOTH stream drivers
    (batcher-backed ``_stream_serving`` and legacy ``_stream_direct``).

    This is the one block the exactly-once streaming contract requires to
    stay byte-identical across serving modes: resume-splice re-derivation,
    whole-sequence re-decode (multi-byte chars and cross-chunk stop
    strings stay correct), the stop scan, holdback, and delta/new-ids
    emission. The chaos suites assert the two drivers emit identical
    event streams — one implementation, not two copies.

    ``advance(gen, finished)`` consumes a monotonic generated-token
    prefix and returns ``(chunk | None, stop_cut)``; the caller stamps
    and yields the chunk (offset = ``sent_tokens``) and handles the
    driver-specific abort when ``stop_cut`` is True."""

    def __init__(self, tokenizer, cfg, holdback: int,
                 resume_from: int, resume_text: int) -> None:
        self.tokenizer = tokenizer
        self.cfg = cfg
        self.holdback = holdback
        self.resume_text = resume_text
        self.sent_tokens = 0
        self.sent_text = ""
        # splice point of a resumed stream: the client already consumed
        # tokens [0, resume_from) — regenerate silently up to it, then
        # re-derive the exact text the ORIGINAL stream had delivered at
        # that offset (same holdback formula, same deterministic tokens)
        self.splice: Optional[int] = resume_from if resume_from > 0 else None
        self.finish_override: Optional[str] = None

    @staticmethod
    def _trim_partial_tail(text: str, floor: int) -> str:
        """Withhold trailing replacement characters: a U+FFFD at the very
        end of an incremental decode is (usually) an INCOMPLETE multi-byte
        sequence the next token's bytes complete — emitting it now would
        bake the wrong character into the stream, and the whole-stream
        text would diverge from the batch decode of the same tokens
        (fleet chaos suite caught exactly this). Held-back chars are
        delivered once resolved, or verbatim at finish (a genuine lone
        invalid byte still reaches the client). Never trims below
        ``floor`` (text already delivered)."""
        while len(text) > floor and text.endswith("�"):
            text = text[:-1]
        return text

    def advance(self, gen: List[int], finished: bool):
        if self.splice is not None and (len(gen) >= self.splice or finished):
            self.sent_tokens = min(self.splice, len(gen))
            raw = self.tokenizer.decode(gen[: self.sent_tokens])
            self.sent_text = raw
            if self.holdback:
                self.sent_text = self.sent_text[
                    : max(len(self.sent_text) - self.holdback, 0)
                ]
            # mirror the live stream's partial-tail holdback: at offset
            # ``splice`` the original stream had NOT yet delivered a
            # trailing replacement char, so the re-derived consumed text
            # must not count it either
            self.sent_text = self._trim_partial_tail(self.sent_text, 0)
            if self.resume_text > len(self.sent_text):
                # a holdback flush reached the client before the drop:
                # its characters are consumed even though the token
                # offset didn't advance
                self.sent_text = raw[: self.resume_text]
            self.splice = None
        if self.splice is not None or \
                (len(gen) <= self.sent_tokens and not finished):
            return None, False
        # decode the WHOLE sequence: multi-byte characters and
        # cross-chunk stop strings stay correct
        full = self.tokenizer.decode(gen)
        stop_idx = -1
        for st in self.cfg.stop:
            idx = full.find(st)
            if idx >= 0 and (stop_idx < 0 or idx < stop_idx):
                stop_idx = idx
        if stop_idx >= 0:
            target = full[:stop_idx]
            self.finish_override = "stop"
        elif finished:
            target = full
        else:
            target = full[: max(len(full) - self.holdback,
                                len(self.sent_text))]
            target = self._trim_partial_tail(target, len(self.sent_text))
        delta = target[len(self.sent_text):]
        # token ids past a stop cut are not emitted
        new_ids = [] if stop_idx >= 0 else list(gen[self.sent_tokens:])
        self.sent_text = target
        self.sent_tokens = len(gen)
        # emit on new token ids even when the text delta is empty (id
        # outside the tokenizer's decodable range, or held back):
        # exactly-once delivery means every sampled id reaches the client
        # in some chunk — silently skipped ids would desync the splice
        chunk = ({"text_delta": delta, "token_ids": new_ids}
                 if (delta or new_ids) else None)
        return chunk, stop_idx >= 0


class _CheckpointPusher:
    """Latest-wins background pusher for stream-cadence checkpoints.

    The sink is a blocking control-plane HTTP call, which must never stall
    the decode loop (a hung control plane would otherwise freeze every
    live SSE stream for a full timeout per push). One pending entry per
    key is kept — a newer checkpoint supersedes an unsent older one, so a
    slow plane costs checkpoint STALENESS (bounded extra recompute on
    failover), never tokens/sec."""

    def __init__(self, sink) -> None:
        self._sink = sink
        self._latest: Dict[str, Dict[str, Any]] = {}
        self._cv = threading.Condition()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ckpt-pusher"
        )
        self._thread.start()

    def put(self, entry: Dict[str, Any]) -> None:
        with self._cv:
            self._latest[str(entry.get("key"))] = entry
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._latest:
                    self._cv.wait()
                _, entry = self._latest.popitem()
            try:
                self._sink(entry)
            except Exception:  # noqa: BLE001 — best-effort by contract
                pass


class TPULLMEngine(LLMBaseEngine):
    """config keys: model (name in models/configs registry), tokenizer /
    tokenizer_id, max_batch_size, max_seq_len, multi_step,
    enable_prefix_cache, checkpoint_path (orbax/HF weights via models.loader),
    quantization (int8 | fp8 weight-only, ops/quantization.py).
    """

    task_type = "llm"
    # the worker injects a ``_failover_ctx`` (job id, assignment epoch,
    # server-held checkpoint) only into engines that advertise this — the
    # llm engine then checkpoints in-flight generations to the control
    # plane and resumes a requeued job from its checkpoint
    supports_failover = True

    def __init__(self, config: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(config)
        self.engine: Optional[TPUEngine] = None
        # batcher-backed serving front-end (the DEFAULT worker path since
        # round 6): all queued jobs and direct/SSE requests share decode
        # rounds through one ContinuousBatcher; ``serving.mode: direct``
        # restores the legacy per-request engine driving
        self.serving: Optional[BatcherServing] = None
        self._spec = None            # EAGLE-style decoder (engine=jax-speculative)
        self.tokenizer = self.config.get("tokenizer")
        # PD disaggregation: kv_cache_key → (slot, seq, adopted_at) — an
        # adopted (or locally retained) sequence awaiting its decode-stage
        # job. ``seq`` identity-guards late frees (the slot index may be
        # recycled), ``adopted_at`` drives the TTL purge: a decode job that
        # never arrives (decode child swept, parent re-prefilled elsewhere)
        # must not pin its KV blocks for the life of the engine.
        self._pd_slots: Dict[str, tuple] = {}
        self.pd_slot_ttl_s = float(
            self.config.get("pd_slot_ttl_s", 180.0) or 180.0
        )
        # sender/receiver handoff lifecycle counters — cumulative totals,
        # heartbeat engine_stats["pd"] → delta-anchored
        # pd_handoffs_total{outcome} / pd_handoff_bytes_total on the plane
        self.pd_stats: Dict[str, int] = {
            "handoffs_committed": 0,
            "handoffs_failed": 0,
            "handoffs_aborted": 0,
            "handoffs_local": 0,
            "handoff_bytes": 0,
            "piece_retries": 0,
            "adopted_expired": 0,
        }
        # per-piece push robustness knobs (satellite: a transport blip must
        # not fail the whole handoff on the first try)
        self._pd_push_timeout_s = float(
            self.config.get("pd_push_timeout_s", 30.0) or 30.0
        )
        self._pd_push_retries = int(
            self.config.get("pd_push_retries", 3) or 0
        )
        self._pd_push_backoff_s = float(
            self.config.get("pd_push_backoff_s", 0.2) or 0.2
        )
        self._pd_rng = random.Random(0x9D5)
        # serializes engine mutation between the job path and the
        # data-plane KV receiver thread (adoption arrives asynchronously)
        self._engine_lock = threading.Lock()
        # streamed-handoff session machine (created with the engine)
        self._handoff_rx = None
        # crash-safe generation: key → live in-flight generation metadata
        # (request_id to find the slot, kind job|stream, assignment epoch).
        # The heartbeat thread snapshots these via checkpoint_live WITHOUT
        # the engine lock — snapshots read host-side Python/numpy mirrors
        # only, and a torn read degrades to a skipped checkpoint, never a
        # stalled heartbeat behind a whole generation.
        self._live: Dict[str, Dict[str, Any]] = {}
        self._live_lock = threading.Lock()
        # graceful drain: set by interrupt_live(); queued-job drivers freeze
        # their sequence at the next step boundary and raise JobMigrated
        self._interrupt = threading.Event()
        # optional push cadence between heartbeats: the worker points this
        # at its control-plane client; the stream path calls it once at
        # admission and every checkpoint_interval_tokens afterwards
        self.checkpoint_sink = None
        self._ckpt_pusher: Optional[_CheckpointPusher] = None
        # corrupt server-held checkpoints refused at resume (bad crc /
        # unparseable row): each one degrades to a from-scratch recompute —
        # counted here, ships via kv_spill_wire_stats (round 19)
        self.ckpt_corrupt = 0
        self._ckpt_interval = int(
            self.config.get("checkpoint_interval_tokens", 8) or 0
        )
        # cache-aware routing: bounded hot-set of prefix boundary
        # fingerprints (runtime/prefix_summary.py) — rides heartbeats as
        # this worker's radix summary so the control plane can route
        # prefix-sharing requests back here. prefix_summary_top_n=0
        # disables the channel.
        top_n = int(self.config.get("prefix_summary_top_n", 128) or 0)
        self.prefix_hot: Optional[PrefixHotSet] = (
            PrefixHotSet(top_n) if top_n > 0 else None
        )
        self._prefix_evictions_seen = 0
        # cluster-wide KV migration (round 13): pull a hot prefix from a
        # peer's /kv/export instead of re-prefilling, and serve peers'
        # pulls from our own radix + spill tiers. Worker-side default ON;
        # whether any request actually migrates is the ROUTER's per-request
        # cost-model decision (RoutingConfig.kv_migrate, default off).
        self.kv_migrate_enabled = bool(self.config.get("kv_migrate", True))
        self._kvmig_max_blocks = int(
            self.config.get("kv_migrate_max_blocks", 64) or 64
        )
        self._kvmig_timeout_s = float(
            self.config.get("kv_migrate_pull_timeout_s", 20.0) or 20.0
        )
        # migration budget: concurrent pulls beyond this recompute instead
        # of stacking network reads (a migrate-hint storm must degrade to
        # PR 7 behavior, never amplify the overload that caused it)
        self._kvmig_budget = int(self.config.get("kv_migrate_budget", 2) or 2)
        self._kvmig_backoff_s = float(
            self.config.get("kv_migrate_backoff_s", 1.0) or 1.0
        )
        self._kvmig_lock = threading.Lock()
        self._kvmig_inflight = 0
        # peer url → (consecutive failures, monotonic deadline): after a
        # failed pull the peer is skipped under jittered exponential
        # backoff — the PD re-prefill contract shape (first failure falls
        # back immediately, repeats spread past the outage)
        self._kvmig_backoff: Dict[str, tuple] = {}
        self._kvmig_rng = random.Random(0x5CAF)
        # cumulative counters → heartbeat engine_stats["kv_migrate"] →
        # kv_migrations_total{outcome} / kv_migration_bytes_total
        self.kv_migrate_stats: Dict[str, int] = {
            "pulled": 0, "fallback_recompute": 0, "aborted": 0,
            "local_hits": 0,
            "pull_bytes": 0, "pull_blocks": 0,
            "exports": 0, "export_bytes": 0,
            # proactive replication (round 20): plane-hinted prefetch pulls
            "replicated": 0, "replicate_miss": 0, "replicate_aborted": 0,
        }
        # fingerprint → prompt token ids, for fp-keyed exports (round 18
        # proactive replication: the COLD puller knows only the text-space
        # fingerprint the plane hinted; this worker — the warm exporter —
        # resolves it back to the exact token ids its radix is keyed by).
        # Bounded LRU, populated per built request alongside the hot-set
        # note; entries for one prompt share one token list.
        self._kvmig_fp_tokens: "OrderedDict[str, List[int]]" = OrderedDict()
        self._kvmig_fp_cap = 512
        # request flight recorder (round 14): per-request Timelines for
        # traced requests (params carry a trace_id). Completed timelines
        # ride job results (complete_job) AND a bounded heartbeat ring
        # (direct streams never pass complete_job); cumulative counters
        # delta-anchor into flight_timelines_total / events_dropped on the
        # plane. Always advisory — a recorder problem never fails a job.
        self.flight_stats: Dict[str, int] = {
            "timelines": 0, "events_dropped": 0,
        }
        from collections import deque as _deque

        self._flight_recent: Any = _deque(maxlen=8)

    # -- lifecycle -----------------------------------------------------------

    def load_model(self) -> None:
        model_name = self.config.get("model", "llama3-mini")
        if self.tokenizer is None:
            tok_id = self.config.get("tokenizer_id")
            self.tokenizer = (
                _load_hf_tokenizer(tok_id) if tok_id else ByteTokenizer()
            )
        # KV spill tiers: host-RAM L2 block budget + optional L3 remote
        # store from a config URL (redis://host:port/db — the real RESP
        # client in runtime/redis_kv.py; memory:// for single-node tests)
        from distributed_gpu_inference_tpu.runtime.redis_kv import (
            remote_store_from_url,
        )

        sv = self._serving_config()
        eng_cfg = EngineConfig(
            max_batch_size=int(self.config.get("max_batch_size", 8)),
            max_seq_len=int(self.config.get("max_seq_len", 2048)),
            multi_step=int(self.config.get("multi_step", 16)),
            enable_prefix_cache=bool(
                self.config.get("enable_prefix_cache", True)
            ),
            quantization=self.config.get("quantization"),
            # KV-pool storage dtype (int8 | fp8 | None = activation dtype)
            # — previously engine-API-only; spec verify reads int8 pools
            # through the ragged kernel's in-kernel dequant since round 8,
            # so the worker config can finally compose quantized KV with
            # speculative serving
            kv_cache_dtype=self.config.get("kv_cache_dtype"),
            spill_host_blocks=int(self.config.get("kv_spill_host_blocks", 0)),
            spill_remote_store=remote_store_from_url(
                self.config.get("kv_remote_url"),
                ttl_s=float(self.config.get("kv_remote_ttl_s", 3600.0)),
            ),
            # SLO admission shaping (compile-affecting: load-time only)
            admission_subwave=int(sv["subwave"]),
            admission_interleave_steps=int(sv["interleave"]),
            # long-context pool sizing: the default rule (1.5x batch x
            # max_blocks_per_seq) assumes every slot can run max_seq_len
            # deep — at 32k that is mostly pad, so deployments size the
            # pool for the actual working set instead
            num_blocks=(int(self.config["num_blocks"])
                        if self.config.get("num_blocks") else None),
        )
        if self.config.get("prefill_buckets"):
            eng_cfg.prefill_buckets = tuple(
                sorted(int(w) for w in self.config["prefill_buckets"])
            )
        # long-context chunk width: per-round knob, so load-time config is
        # just the initial value (remote pushes can retune it live)
        if sv.get("ragged_chunk"):
            eng_cfg.ragged_chunk = int(sv["ragged_chunk"])
        # engine-INTEGRATED speculative decoding (EngineConfig.speculative):
        # every decode round runs fused draft→verify→accept steps committing
        # 1..K+1 tokens per slot — unlike engine=jax-speculative below,
        # which routes a SUBSET of requests to a standalone tree decoder.
        # Greedy outputs stay byte-identical; sampled requests ride the same
        # graph at one token per step.
        if self.config.get("speculative_decode"):
            from ...runtime.speculative import SpecDecodeConfig

            try:
                oracle = self.config.get("spec_oracle_accept")
                eng_cfg.speculative = SpecDecodeConfig(
                    num_draft_tokens=int(
                        self.config.get("spec_num_draft_tokens", 4)
                    ),
                    # acceptance-adaptive draft depth (per-slot EMA
                    # selects K from a static set — one compiled graph)
                    adaptive=bool(self.config.get("spec_adaptive", False)),
                    adaptive_min_k=int(
                        self.config.get("spec_adaptive_min_k", 1)
                    ),
                    # bench-only oracle draft: force the acceptance rate
                    # (fraction of drafted tokens) — real cost, forced
                    # decision; outputs are garbage, pair with ignore_eos
                    oracle_accept_rate=(
                        None if oracle is None else float(oracle)
                    ),
                )
                eng_cfg.speculative.validate(eng_cfg)
            except (ValueError, TypeError) as exc:
                raise EngineLoadError(
                    f"speculative_decode config invalid: {exc}"
                ) from exc
            if self.config.get("engine") in ("jax-speculative",
                                             "speculative"):
                # config-only conflict: fail BEFORE weights load / the
                # draft head distills, not after minutes of work
                raise EngineLoadError(
                    "speculative_decode (engine-integrated) and "
                    "engine=jax-speculative (standalone tree decoder) are "
                    "mutually exclusive — pick one"
                )
        # first-class TP: tp_size > 1 builds a model-axis mesh over local
        # devices (the reference forwarded tensor_parallel_size to vLLM;
        # here the engine itself shards, llm_vllm.py:56 / SURVEY §2.2)
        mesh = None
        tp = int(self.config.get("tp_size") or
                 (self.config.get("extra") or {}).get("tp_size") or 1)
        if tp > 1:
            import jax

            from ...parallel.mesh import MeshPlan, make_mesh

            devices = jax.local_devices()  # only addressable chips: a mesh
            # over another process's devices would fail or diverge per host
            if len(devices) < tp:
                raise EngineLoadError(
                    f"tp_size={tp} but only {len(devices)} local devices"
                )
            mesh = make_mesh(MeshPlan(model=tp), devices[:tp],
                             keep_trivial_axes=False)
        try:
            self.engine = TPUEngine(
                model_name,
                eng_cfg,
                checkpoint_path=self.config.get("checkpoint_path"),
                mesh=mesh,
            )
        except ValueError as exc:
            # invalid mesh/model combination must drop the task type, not
            # kill worker startup (load_engines catches EngineLoadError)
            raise EngineLoadError(str(exc)) from exc
        if eng_cfg.speculative is not None and \
                int(self.config.get("spec_distill_steps", 0)) > 0:
            # optional on-load draft distillation against the engine's own
            # target weights; a random head is still correct, just ~0
            # acceptance, so failures here must not kill the task type
            try:
                self.engine.distill_draft(
                    steps=int(self.config["spec_distill_steps"])
                )
            except Exception as exc:  # noqa: BLE001 — optax absent, OOM, ...
                raise EngineLoadError(
                    f"speculative draft distillation failed: {exc}"
                ) from exc
        # engine=jax-speculative: short-prompt greedy requests route through
        # the EAGLE-style tree decoder (shares the TARGET weights with the
        # paged engine but owns its own KV pool — sized to exactly one
        # batch's worst case to bound the extra HBM); sampled, streaming,
        # and beyond-bucket-length requests keep using the paged TPUEngine.
        if self.config.get("engine") in ("jax-speculative", "speculative"):
            try:
                from ...runtime.speculative import (
                    SpeculativeConfig,
                    SpeculativeDecoder,
                )

                raw_w = self.config.get("spec_widths") or (4, 2, 2)
                if isinstance(raw_w, str):          # CLI/env: "4,2,2"
                    raw_w = [p for p in raw_w.split(",") if p.strip()]
                widths = tuple(int(w) for w in raw_w)
                if not widths or any(w < 1 for w in widths):
                    raise ValueError(f"invalid spec_widths {widths}")
                blocks_per_seq = -(-eng_cfg.max_seq_len // eng_cfg.block_size)
                self._spec = SpeculativeDecoder(
                    model_name,
                    params=self.engine.params,
                    spec_cfg=SpeculativeConfig(widths=widths),
                    max_batch_size=eng_cfg.max_batch_size,
                    max_seq_len=eng_cfg.max_seq_len,
                    num_blocks=eng_cfg.max_batch_size * blocks_per_seq + 2,
                    prefill_buckets=eng_cfg.prefill_buckets,
                )
            except (ValueError, TypeError) as exc:
                # a bad speculative config drops the task type, never kills
                # worker startup
                raise EngineLoadError(
                    f"speculative engine config invalid: {exc}"
                ) from exc
        if str(sv["mode"]) == "batcher":
            try:
                self.serving = BatcherServing(
                    self.engine, self._batcher_config(sv), spec=self._spec
                )
            except (ValueError, RuntimeError) as exc:
                raise EngineLoadError(
                    f"batcher serving config invalid: {exc}"
                ) from exc
        self.loaded = True

    def _serving_config(self) -> Dict[str, Any]:
        """Merged serving knobs: defaults < ``config['serving']`` (worker
        YAML ``engines.llm.serving.*``) < ``extra['serving']``."""
        from ...utils.config import warn_deprecated_serving_key

        out = dict(SERVING_DEFAULTS)
        for src in (self.config.get("serving"),
                    (self.config.get("extra") or {}).get("serving")):
            if isinstance(src, dict):
                # plain-dict construction (benchmarks, tests) bypasses the
                # pydantic surface, so the obsoleted-knob deprecation
                # warning fires here too — but only for values that differ
                # from the defaults (CLI surfaces pass their whole arg
                # namespace through; a knob nobody touched must not warn)
                for k, v in src.items():
                    if v is not None and v != SERVING_DEFAULTS.get(k):
                        warn_deprecated_serving_key(
                            k, "engine serving config"
                        )
                out.update({k: v for k, v in src.items() if v is not None})
        return out

    @staticmethod
    def _batcher_config(sv: Dict[str, Any]) -> BatcherConfig:
        return BatcherConfig(
            max_wait_ms=float(sv["max_wait_ms"]),
            multi_step=int(sv["multi_step"]),
            min_multi_step=int(sv["min_horizon"]),
            max_multi_step=int(sv["max_horizon"]),
            adaptive=bool(sv["adaptive"]),
            target_step_latency_ms=float(sv["target_step_ms"]),
            queue_limit=int(sv["queue_limit"]),
            default_timeout_s=float(sv["default_timeout_s"]),
            max_preemptions=int(sv["max_preemptions"]),
            spec_max_batch=int(sv["spec_max_batch"]),
            spec_max_active=int(sv["spec_max_active"]),
            ragged=(None if sv.get("ragged") is None
                    else bool(sv["ragged"])),
            prefill_budget=int(sv.get("prefill_budget") or 0),
            abandon_deadlines=bool(sv.get("abandon_deadlines") or False),
            deadline_grace_s=float(sv.get("deadline_grace_s") or 0.5),
            predictive_abandon=bool(sv.get("predictive_abandon") or False),
        )

    def apply_serving_config(self, updates: Optional[Dict[str, Any]]) -> None:
        """Server-pushed SLO retune (remote config ``serving`` section):
        applied to the LIVE batcher between rounds. Compile-affecting
        admission knobs (``subwave``/``interleave``) and ``mode`` are
        load-time only and ignored here."""
        if self.serving is None or not updates:
            return
        kw = {
            SERVING_REMOTE_KEYS[k]: v
            for k, v in updates.items()
            if k in SERVING_REMOTE_KEYS and v is not None
        }
        if kw:
            self.serving.reconfigure(**kw)

    def serving_stats(self) -> Optional[Dict[str, Any]]:
        """Live batcher stats (occupancy, queue depth, chunked admissions,
        preemption counters, horizon) — ride the worker heartbeat into the
        control plane's ``/metrics``. None when serving mode is direct."""
        if self.serving is None or not self.serving.active:
            return None
        return self.serving.get_stats()

    def prefix_summary_wire(self) -> Optional[Dict[str, Any]]:
        """Next heartbeat radix-summary payload (full snapshot or delta —
        ``runtime/prefix_summary.py`` wire format), or None when the
        control plane is up to date. Before encoding, cold entries are
        tier-demoted in proportion to pool evictions since the last wire
        — an advertised ``dev`` entry whose block was evicted would
        otherwise overpromise until the staleness TTL."""
        hot = self.prefix_hot   # snapshot vs concurrent disable()
        if hot is None:
            return None
        eng = self.engine
        if eng is not None and getattr(eng, "manager", None) is not None:
            ev = int(eng.manager.stats.evictions or 0)
            delta = ev - self._prefix_evictions_seen
            if delta > 0 and len(hot):
                frac = min(1.0, delta / len(hot))
                if eng.manager.spill_on_evict:
                    # evicted blocks landed in a spill tier: restorable,
                    # but pricier than device-resident — demote to the
                    # tier they ACTUALLY landed in, so the router's cost
                    # model prices a host-RAM pull vs a remote-store one
                    # (host wins when both exist: spill writes through L2
                    # first and probes hit it first)
                    tier = (TIER_HOST if eng.manager.host_store is not None
                            else TIER_SPILL)
                    hot.demote(frac, tier=tier)
                else:
                    # no spill tier: evicted KV is GONE — advertising it
                    # at any weight would over-promise for a full TTL
                    hot.drop(frac)
            self._prefix_evictions_seen = ev
        return hot.wire()

    def prefix_summary_ack(self) -> None:
        hot = self.prefix_hot
        if hot is not None:
            hot.ack()

    def prefix_summary_resync(self) -> None:
        hot = self.prefix_hot
        if hot is not None:
            hot.resync()

    def prefix_summary_disable(self) -> None:
        """The control plane statically rejected our summaries (wire
        version / fingerprint-basis skew): stop shipping them — a
        payload the server can never apply would otherwise ping-pong
        full snapshots on every heartbeat until redeploy."""
        self.prefix_hot = None

    def _exclusive(self, fn: Any) -> Any:
        """Serialize out-of-band engine work (PD stages, handoff adoption)
        with the batcher's decode rounds: the callable runs on the
        batcher's single engine-executor thread. Without a batcher the
        caller's ``_engine_lock`` is the only serialization needed."""
        if self.serving is not None and self.serving.active:
            return self.serving.run_exclusive(fn)
        return fn()

    def unload(self) -> None:
        if self.serving is not None:
            self.serving.stop(drain=False)
            self.serving = None
        self.engine = None
        self._spec = None
        super().unload()

    # -- core generate ---------------------------------------------------------

    def _to_prompt(self, prompt_or_messages: Any) -> str:
        if isinstance(prompt_or_messages, str):
            return prompt_or_messages
        if isinstance(prompt_or_messages, list):  # chat messages
            tmpl = getattr(self.tokenizer, "apply_chat_template", None)
            if tmpl is not None:
                try:
                    out = tmpl(prompt_or_messages, tokenize=False,
                               add_generation_prompt=True)
                except TypeError:  # ByteTokenizer's simpler signature
                    out = tmpl(prompt_or_messages)
                return out
            return "\n".join(m.get("content", "") for m in prompt_or_messages)
        raise ValueError(f"bad prompt type {type(prompt_or_messages)}")

    def _stop_ids(self, cfg: GenerationConfig) -> tuple:
        ids = list(cfg.stop_token_ids)
        eos = getattr(self.tokenizer, "eos_token_id", None)
        if eos is not None and eos not in ids:
            ids.append(int(eos))
        return tuple(ids[:4])

    def _sampling_from(self, cfg: GenerationConfig) -> SamplingParams:
        """THE GenerationConfig → SamplingParams mapping — every request
        construction path (interactive, batch, PD prefill) goes through
        here so per-request knobs like ``ignore_eos`` cannot be honored on
        one path and dropped on another."""
        return SamplingParams(
            max_new_tokens=cfg.max_new_tokens,
            temperature=cfg.temperature,
            top_k=cfg.top_k,
            top_p=cfg.top_p,
            stop_token_ids=(() if cfg.ignore_eos else self._stop_ids(cfg)),
            seed=cfg.seed,
            ignore_eos=cfg.ignore_eos,
        )

    def _encode_prompt(self, prompt_or_messages: Any,
                       cfg: GenerationConfig) -> List[int]:
        """THE prompt → token-ids mapping (template, tokenize, truncate)
        shared by request building and the KV-migration pull driver — the
        pulled prefix must key on exactly the tokens the admission will
        probe with."""
        text = self._to_prompt(prompt_or_messages)
        token_ids = list(self.tokenizer.encode(text))
        max_prompt = self.engine.cfg.max_seq_len - cfg.max_new_tokens - 1
        if len(token_ids) > max_prompt > 0:
            token_ids = token_ids[-max_prompt:]  # keep the tail (recency)
        return token_ids

    def _build_request(self, prompt_or_messages: Any,
                       cfg: GenerationConfig,
                       token_ids: Optional[List[int]] = None
                       ) -> InferenceRequest:
        """One request builder for the blocking AND streaming paths — the
        two must never diverge on tokenization/truncation/sampling.
        ``token_ids``: pre-encoded prompt (the KV-migration pull driver
        already ran ``_encode_prompt`` on the same inputs — hinted
        requests must not pay template+tokenize twice)."""
        if not self.loaded or self.engine is None:
            raise EngineLoadError("engine not loaded")
        hot = self.prefix_hot   # snapshot: the heartbeat thread may
        if hot is not None and \
                self.engine.cfg.enable_prefix_cache:  # disable() to None
            # every built request's prefix will be radix-cached on
            # completion — record its boundary fingerprints for the
            # heartbeat summary (advisory; one O(prefix) hash pass)
            from ...utils.prefixes import (
                canonical_prompt_text,
                prefix_fingerprints,
            )
            fps = prefix_fingerprints(
                canonical_prompt_text(prompt_or_messages),
                hot.block_chars, hot.max_blocks,
            )
            hot.note_fingerprints(fps)
            if fps and self.kv_migrate_enabled:
                if token_ids is None:
                    token_ids = self._encode_prompt(prompt_or_messages, cfg)
                # fp-keyed export resolution (proactive replication): a
                # cold puller hints only the text-space fingerprint; map
                # every boundary of this prompt to its token ids so
                # kv_export can serve the pull. One shared list per prompt
                with self._kvmig_lock:
                    for fp in fps:
                        self._kvmig_fp_tokens[fp] = token_ids
                        self._kvmig_fp_tokens.move_to_end(fp)
                    while len(self._kvmig_fp_tokens) > self._kvmig_fp_cap:
                        self._kvmig_fp_tokens.popitem(last=False)
        if token_ids is None:
            token_ids = self._encode_prompt(prompt_or_messages, cfg)
        return InferenceRequest(
            prompt_token_ids=token_ids,
            sampling=self._sampling_from(cfg),
            # EDF input: the batcher orders same-priority admissions by
            # absolute deadline and prefers slack-rich preemption victims
            deadline_s=cfg.deadline_s,
        )

    # -- PD disaggregation stages (server/pd_flow.py drives these) ----------

    def inference(self, params: Dict[str, Any]) -> Dict[str, Any]:
        # the lock covers EVERY engine-touching job path, not just the PD
        # stages: the data-plane kv_receiver thread adopts handoffs
        # asynchronously, and an unlocked ordinary generate would race it
        # on the same engine. pd_prefill manages its own lock scope — the
        # KV push is network I/O that must happen OUTSIDE the lock (two
        # hybrid workers pushing to each other while holding their locks
        # would deadlock until the HTTP timeout).
        stage = params.get("pd_stage")
        if stage == "prefill":
            return self.pd_prefill(params)
        if stage is None:
            # flight recorder: the request's Timeline is minted here (the
            # single entry point for non-PD inference) and stashed through
            # params so the migrate hook and the terminal driver share it
            tl = self._flight_timeline(params)
            if tl.enabled:
                params["_flight_tl"] = tl
            # router-hinted KV migration: pull the hot prefix from the
            # named peer BEFORE admission (never under the engine lock —
            # the peer's export serializes on ITS engine; ours adopts the
            # frames through kv_receiver's own serialization)
            self._maybe_migrate_kv(params)
        if self.serving is not None and self.serving.active:
            # batcher-backed serving: the batcher owns engine serialization
            # (every engine call runs on its one executor thread), so
            # concurrent jobs/streams need no engine lock — they share
            # decode rounds instead of queueing on it
            if stage == "decode":
                return self.pd_decode(params)
            ctx = params.get("_failover_ctx")
            if isinstance(ctx, dict):
                return self._job_inference(params, ctx)
            return self._serving_inference(params)
        with self._engine_lock:
            if stage == "decode":
                return self.pd_decode(params)
            ctx = params.get("_failover_ctx")
            if isinstance(ctx, dict):
                # queued-job failover path: interruptible driver that
                # registers for heartbeat checkpointing and resumes from a
                # server-held checkpoint when the claim carries one
                return self._job_inference(params, ctx)
            tl = params.pop("_flight_tl", NULL_TIMELINE)
            tl.note("worker.start", path="legacy")
            out = super().inference(params)
        tl.note("worker.done")
        self._flight_finish(tl, out if isinstance(out, dict) else None)
        return out

    def _serving_inference(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Blocking request through the batcher front-end (direct server /
        plain jobs): same tokenization, stop handling, and result payload
        as the legacy ``_generate`` path, but concurrent callers share
        decode rounds via slot-level continuous batching."""
        cfg = GenerationConfig.from_params(params)
        tl = params.pop("_flight_tl", NULL_TIMELINE)
        tl.note("worker.start", path="serving")
        req = self._build_request(
            params.get("messages") or params.get("prompt") or "", cfg,
            token_ids=params.pop("_kvmig_token_ids", None),
        )
        if params.get("priority") is not None:
            req.priority = int(params.get("priority") or 0)
        if params.get("speculative") is False:
            req.params["speculative"] = False
        # hedged dispatch: the direct server mints a cancel event for
        # requests carrying a hedge key — the losing racer's abort rides
        # the batcher's step-boundary cancel path (partial output with
        # finish_reason="abort", never an error)
        cancel = params.pop("_cancel_evt", None)
        t0 = time.perf_counter()
        resp = self.serving.submit(req, cancel=cancel,
                                   flight=tl if tl.enabled else None)
        if resp.error is not None:
            _raise_serving(resp)
        tl.note("worker.done")
        payload = self._finish_payload(
            list(resp.token_ids), resp.prompt_tokens, resp.cached_tokens,
            resp.finish_reason or "stop", cfg, resp.ttft_ms,
            time.perf_counter() - t0,
        )
        self._flight_finish(tl, payload)
        return payload

    def _pd_push(self, client: Any, url: str, content: bytes) -> Any:
        """POST one handoff message with a per-piece timeout and a bounded
        full-jitter retry ladder (``utils.backoff`` — the same formula as
        the APIClient's): a transport blip or transient 5xx must not fail
        the whole handoff on its first occurrence. Receiver-side begin and
        commit are idempotent (duplicate-delivery tolerant), and piece
        re-staging is a no-op on already-staged blocks, so retrying any
        message kind is safe. Retries are counted (``piece_retries``) so a
        flaky link is VISIBLE in /metrics, not silently absorbed."""
        from distributed_gpu_inference_tpu.runtime.kv_handoff import (
            message_kind,
        )

        kind = message_kind(content)
        attempt = 0
        while True:
            try:
                r = _faults.wrap_http(
                    "worker.pd.push",
                    lambda: client.post(
                        url, content=content,
                        headers={"content-type": "application/octet-stream"},
                        timeout=self._pd_push_timeout_s,
                    ),
                    worker=str(getattr(self, "fault_tag", "") or ""),
                    kind=kind,
                )
                if r.status_code < 500:
                    r.raise_for_status()   # 4xx: receiver rejected — no retry
                    return r
                last = RuntimeError(
                    f"KV push {kind} answered HTTP {r.status_code}: "
                    f"{r.text[:200]}"
                )
            except httpx.TransportError as exc:
                last = exc
            if attempt >= self._pd_push_retries:
                raise last
            delay = full_jitter_delay(
                self._pd_push_backoff_s, attempt, self._pd_rng
            )
            time.sleep(delay or 0.0)
            attempt += 1
            self.pd_stats["piece_retries"] += 1

    def _purge_stale_pd_slots(self) -> None:
        """Free adopted/retained PD slots whose decode-stage job never
        arrived within ``pd_slot_ttl_s`` (decode child swept, parent
        re-prefilled elsewhere, stale attempt completing late) — an
        orphaned adoption must not pin its KV blocks for the life of the
        engine. Caller holds ``_engine_lock``; frees run serialized with
        decode rounds and are identity-guarded against slot recycling."""
        if not self._pd_slots:
            return
        now = time.monotonic()
        eng = self.engine
        for key, (slot, seq, adopted_at) in list(self._pd_slots.items()):
            if now - adopted_at <= self.pd_slot_ttl_s:
                continue
            # pop-to-claim: pd_decode pops WITHOUT the engine lock, so
            # the dict pop is the one atomic arbiter — if the decode
            # stage won the entry between our snapshot and now, the
            # sequence is live (being adopted into the batch) and is NOT
            # ours to free
            if self._pd_slots.pop(key, None) is None:
                continue
            self.pd_stats["adopted_expired"] += 1
            if eng is not None:
                self._release_adopted_slot(eng, slot, seq)

    def pd_maintain(self) -> None:
        """Periodic PD housekeeping (worker heartbeat cadence): age out
        adopted slots whose decode stage never came — a re-prefilled flow
        cancels its stale decode child, but the KV its prefill already
        pushed would otherwise sit adopted until message-driven purging
        happens to run. Non-blocking: a busy engine lock skips this beat
        (the next one retries)."""
        if not self._pd_slots or self.engine is None:
            return
        if not self._engine_lock.acquire(blocking=False):
            return
        try:
            self._purge_stale_pd_slots()
        finally:
            self._engine_lock.release()

    def pd_prefill(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Prefill stage: run the prompt, sample the first token (TTFT),
        export the sequence's KV pages, and push them to the decode worker's
        data plane (``/kv/transfer`` — HTTP twin of grpc TransferKVCache).
        When this worker IS the decode target (KV affinity), the slot is
        simply retained — zero migration bytes."""
        from distributed_gpu_inference_tpu.runtime.kv_handoff import (
            export_slot_kv,
            serialize_handoff,
        )

        if not self.loaded or self.engine is None:
            raise EngineLoadError("engine not loaded")
        cfg = GenerationConfig.from_params(params)
        prompt = params.get("prompt_token_ids") or params.get("messages") \
            or params.get("prompt") or ""
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            req = InferenceRequest(
                prompt_token_ids=[int(t) for t in prompt],
                sampling=self._sampling_from(cfg),
            )
        else:
            req = self._build_request(prompt, cfg)
        key = params.get("kv_cache_key") or f"pd-{req.request_id}"
        # the key rides IN the handoff (session_id) so the receiver can
        # index the adopted slot for the decode-stage job
        req.session_id = key
        # flight recorder: the prefill child's events merge into the PD
        # parent's trace (children inherit parent params, trace_id included)
        tl = self._flight_timeline(params)
        tl.note("pd.prefill.start", key=key)
        decode_url = params.get("decode_url")
        local = not decode_url or params.get("decode_worker") in (
            None, params.get("target_worker"),
        )
        # streamed push (VERDICT r3 #3): chunk the export per page range and
        # overlap the wire hop with remaining prefill compute. Default on
        # for cross-host pushes; sliding-window models fall back to the
        # one-shot blob (the streamed protocol rejects them).
        stream_ok = (
            not local
            and bool(params.get("pd_stream",
                                self.config.get("pd_stream", True)))
            and self.engine.model_cfg.sliding_window is None
        )
        if stream_ok:
            return self._pd_prefill_streamed(
                req, key, decode_url,
                piece_blocks=int(
                    params.get("pd_stream_piece_blocks")
                    or self.config.get("pd_stream_piece_blocks", 4)
                ),
                tl=tl,
            )
        def _prefill_and_export():
            # engine-touching block: under a batcher it runs on the engine
            # executor thread (serialized with live decode rounds) — the
            # admitted slot composes with concurrently-decoding slots
            slot = self.engine.submit_batch([req])[0]
            s = self.engine.slots[slot]
            first_token = int(self.engine._last_tokens[slot])
            ttft_ms = (
                (s.first_token_time - s.start_time) * 1000.0
                if s.first_token_time else None
            )
            prompt_tokens = s.prompt_len
            if local:
                # KV affinity: this worker decodes too — retain the slot.
                # A re-run of the same child (lost completion report)
                # supersedes its previous retained slot — free it or it
                # leaks with no TTL entry (we're on the engine executor:
                # freeing directly is serialized with decode rounds).
                prev = self._pd_slots.get(key)
                if prev is not None and prev[0] != slot and \
                        self.engine.slots[prev[0]] is prev[1]:
                    self.pd_stats["adopted_expired"] += 1
                    self.engine.finish_slot(prev[0], cache=False)
                self._pd_slots[key] = (slot, s, time.monotonic())
                return slot, first_token, ttft_ms, prompt_tokens, None
            try:
                handoff = export_slot_kv(self.engine, slot)
                return slot, first_token, ttft_ms, prompt_tokens, \
                    serialize_handoff(handoff)
            finally:
                # donor side is done with the sequence once the bytes are
                # serialized: free the slot before the network hop so a
                # failed or slow push cannot leak it
                self.engine.finish_slot(slot)

        with self._engine_lock:
            slot, first_token, ttft_ms, prompt_tokens, raw = \
                self._exclusive(_prefill_and_export)
        tl.note("pd.prefill.done", ttft_ms=ttft_ms)
        if local:
            self.pd_stats["handoffs_local"] += 1
            tl.note("handoff.local")
            out = {
                "pd_stage": "prefill", "kv_cache_key": key,
                "first_token": first_token, "ttft_ms": ttft_ms,
                "migration_bytes": 0, "migration_ms": 0.0,
                "decode_slot": slot, "local": True,
                # prefill compute billed on this child; the decode child
                # bills the completion (usage shape = units_from_result)
                "usage": {"prompt_tokens": prompt_tokens,
                          "completion_tokens": 0,
                          "total_tokens": prompt_tokens},
            }
            self._flight_finish(tl, out)
            return out
        # network push OUTSIDE the engine lock: a peer pushing to US can
        # adopt concurrently (kv_receiver takes the lock the engine work
        # above released) — no crossed-push deadlock
        t0 = time.perf_counter()
        tl.note("handoff.begin", bytes=len(raw))
        try:
            with httpx.Client() as client:
                resp = self._pd_push(
                    client, decode_url.rstrip("/") + "/kv/transfer", raw
                )
        except Exception:
            self.pd_stats["handoffs_failed"] += 1
            tl.note("handoff.failed")
            self._flight_finish(tl)   # ships via the heartbeat ring
            raise
        migration_ms = (time.perf_counter() - t0) * 1000.0
        remote = resp.json()
        self.pd_stats["handoffs_committed"] += 1
        self.pd_stats["handoff_bytes"] += len(raw)
        tl.note("handoff.commit", bytes=len(raw))
        out = {
            "pd_stage": "prefill", "kv_cache_key": key,
            "first_token": first_token, "ttft_ms": ttft_ms,
            "migration_bytes": len(raw), "migration_ms": migration_ms,
            "decode_slot": remote.get("slot"), "local": False,
            "usage": {"prompt_tokens": prompt_tokens,
                      "completion_tokens": 0,
                      "total_tokens": prompt_tokens},
        }
        self._flight_finish(tl, out)
        return out

    def _pd_prefill_streamed(self, req: InferenceRequest, key: str,
                             decode_url: str,
                             piece_blocks: int = 4,
                             tl: Any = NULL_TIMELINE) -> Dict[str, Any]:
        """Streamed prefill stage: pages cross the wire WHILE the prompt is
        still computing (``runtime.kv_handoff.StreamedExport``). A sender
        thread drains the message queue so network I/O never runs under the
        engine lock (same no-crossed-push-deadlock stance as the one-shot
        path); ``migration_ms`` is the decode-ready delay — first token
        sampled → commit acked — the number the one-shot path pays in full
        after prefill."""
        import queue as _queue

        from distributed_gpu_inference_tpu.runtime.kv_handoff import (
            StreamedExport,
            abort_message,
        )

        url = decode_url.rstrip("/") + "/kv/transfer"
        exp = StreamedExport(self.engine, req, key,
                             piece_blocks=piece_blocks)
        q: "_queue.SimpleQueue" = _queue.SimpleQueue()
        state: Dict[str, Any] = {"exc": None, "last": None, "t_ack": None}

        def _sender() -> None:
            with httpx.Client(timeout=60.0) as client:
                while True:
                    item = q.get()
                    if item is None:
                        return
                    if state["exc"] is not None:
                        continue        # drain after failure
                    try:
                        # per-piece timeout + bounded jittered retry
                        # (_pd_push): a transport blip mid-stream retries
                        # the piece instead of failing the whole handoff
                        r = self._pd_push(client, url, item)
                        state["last"] = r.json()
                        state["t_ack"] = time.perf_counter()
                    except Exception as exc:  # noqa: BLE001
                        state["exc"] = exc

        sender = threading.Thread(target=_sender, daemon=True,
                                  name="pd-stream-sender")
        sender.start()
        t_prefill_end = None

        def _abort_remote() -> None:
            # direct POST, not via the queue — the sender drains (skips)
            # queued items once state["exc"] is set, and the receiver's
            # half-built session would otherwise pin its KV blocks.
            # Each failed handoff is counted EXACTLY ONCE across the
            # pd_handoffs_total outcome labels: "aborted" = a streamed
            # handoff failed and its abort was sent (this path);
            # "failed" = a one-shot push failed (no session to abort).
            self.pd_stats["handoffs_aborted"] += 1
            try:
                httpx.post(url, content=abort_message(key), timeout=10.0)
            except Exception:  # noqa: BLE001
                pass

        gen = exp.messages()

        def _drive_export() -> Optional[float]:
            # the generator's cleanup (abort_chunked/finish_slot)
            # mutates the engine, so it must run INSIDE the serialized
            # region — close explicitly rather than leaving it to GC
            # (it would race the kv_receiver thread / a decode round)
            t_end = None
            try:
                for msg in gen:
                    if state["exc"] is not None:
                        # fail fast: the push is already doomed — stop
                        # prefilling/gathering and release the engine
                        raise state["exc"]
                    if t_end is None and exp.first_token is not None:
                        t_end = time.perf_counter()
                    q.put(msg)
            finally:
                gen.close()
            return t_end

        tl.note("handoff.begin", streamed=True)
        try:
            with self._engine_lock:
                t_prefill_end = self._exclusive(_drive_export)
        except Exception:
            q.put(None)
            sender.join(timeout=60.0)
            _abort_remote()
            tl.note("handoff.failed")
            self._flight_finish(tl)   # ships via the heartbeat ring
            raise
        q.put(None)
        # generous wire budget: bytes / ~1 MB/s, floor 120 s — a slower link
        # is treated as failed, never silently reported as success
        sender.join(timeout=max(120.0, exp.bytes_sent / 1e6))
        if sender.is_alive():
            state["exc"] = state["exc"] or TimeoutError(
                f"streamed KV push did not finish ({exp.bytes_sent} bytes)"
            )
        if state["exc"] is not None:
            _abort_remote()
            tl.note("handoff.failed")
            self._flight_finish(tl)
            raise state["exc"]
        remote = state["last"] or {}
        self.pd_stats["handoffs_committed"] += 1
        self.pd_stats["handoff_bytes"] += exp.bytes_sent
        migration_ms = (
            (state["t_ack"] - t_prefill_end) * 1000.0
            if state["t_ack"] is not None and t_prefill_end is not None
            else None
        )
        # perf_counter stamps → wall clock for the timeline (one shared
        # offset; sub-ms drift over a handoff is noise)
        wall_minus_perf = time.time() - time.perf_counter()
        if t_prefill_end is not None:
            tl.note_at("pd.prefill.done", t_prefill_end + wall_minus_perf,
                       ttft_ms=exp.ttft_ms)
        else:
            tl.note("pd.prefill.done", ttft_ms=exp.ttft_ms)
        if state["t_ack"] is not None:
            tl.note_at("handoff.commit", state["t_ack"] + wall_minus_perf,
                       bytes=exp.bytes_sent, pieces=exp.pieces_sent)
        else:
            tl.note("handoff.commit", bytes=exp.bytes_sent)
        out = {
            "pd_stage": "prefill", "kv_cache_key": key,
            "first_token": exp.first_token, "ttft_ms": exp.ttft_ms,
            "migration_bytes": exp.bytes_sent,
            "migration_ms": migration_ms,
            "pd_streamed": True,
            "pieces": exp.pieces_sent,
            "bytes_before_first_token": exp.bytes_before_first_token,
            "decode_slot": remote.get("slot"), "local": False,
            "usage": {"prompt_tokens": exp.prompt_tokens,
                      "completion_tokens": 0,
                      "total_tokens": exp.prompt_tokens},
        }
        self._flight_finish(tl, out)
        return out

    def pd_decode(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Decode stage: resume the adopted (or retained) slot and stream
        the rest of the generation. TTFT/E2E stay end-to-end truthful — the
        handoff carries the original start/first-token times."""
        if not self.loaded or self.engine is None:
            raise EngineLoadError("engine not loaded")
        key = params.get("kv_cache_key") or ""
        tl = self._flight_timeline(params)
        tl.note("pd.decode.start", key=key)
        if tl.enabled and self._handoff_rx is not None:
            # adopt the receiver-side handoff instants (begin/commit were
            # observed by the data-plane thread, which knows only the
            # session key) into this request's timeline
            tl.extend_at(self._handoff_rx.pop_flight(key))
        entry = self._pd_slots.pop(key, None)
        if entry is None:
            raise RuntimeError(
                f"no adopted KV for key {key!r} — handoff never arrived"
            )
        slot, _adopted_seq, _adopted_at = entry
        eng = self.engine
        if eng.slots[slot] is not _adopted_seq:
            # the adoption was reclaimed (TTL purge raced this claim, or
            # the slot was recycled after an engine-side abort): the KV is
            # gone — fail like a lost handoff so the flow re-prefills
            raise RuntimeError(
                f"adopted KV for key {key!r} was reclaimed before the "
                "decode stage claimed it"
            )
        if self.serving is not None and self.serving.active:
            # batcher-backed: the adopted slot joins the shared decode
            # rounds instead of monopolizing the engine for its whole
            # generation (it preempts/resumes like any other sequence)
            seq = eng.slots[slot]
            try:
                resp = self.serving.adopt_slot(
                    slot, flight=tl if tl.enabled else None
                )
            except Exception:
                self._release_adopted_slot(eng, slot, seq)
                raise
            if resp.error is not None:
                self._release_adopted_slot(eng, slot, seq)
                _raise_serving(resp)
        else:
            try:
                while eng.slots[slot] is not None and \
                        eng.slots[slot].finish_reason is None:
                    eng.decode_multi()
                    self._raise_if_pressured(eng, slot)
            except Exception:
                # the job fails, so the adopted slot MUST be released — a
                # leaked slot would hold its KV blocks forever and compound
                # the very pressure that aborted it
                if eng.slots[slot] is not None:
                    eng.finish_slot(slot, cache=False)
                raise
            resp = eng.finish_slot(slot)
        text = self.tokenizer.decode(resp.token_ids) if self.tokenizer else ""
        tl.note("pd.decode.done", tokens=resp.completion_tokens)
        out = {
            "pd_stage": "decode", "kv_cache_key": key,
            "text": text,
            "token_ids": list(resp.token_ids),
            "prompt_tokens": resp.prompt_tokens,
            "completion_tokens": resp.completion_tokens,
            "finish_reason": resp.finish_reason,
            "ttft_ms": resp.ttft_ms,
            "e2e_ms": resp.e2e_ms,
            # decode child bills the completion (prefill child billed the
            # prompt — together they equal the non-PD job's total)
            "usage": {"prompt_tokens": 0,
                      "completion_tokens": resp.completion_tokens,
                      "total_tokens": resp.completion_tokens},
        }
        self._flight_finish(tl, out)
        return out

    def _release_adopted_slot(self, eng: TPUEngine, slot: int,
                              seq: Any) -> None:
        """Legacy-path parity: a failed PD decode MUST free its adopted
        slot — leaked KV blocks would hold their pages for the life of the
        engine. Identity-guarded: batcher error paths that already released
        the slot (preemption cap, engine-error abort) may have recycled the
        index for another sequence, which is not ours to finish."""
        def _free() -> None:
            if eng.slots[slot] is seq:
                eng.finish_slot(slot, cache=False)

        try:
            if self.serving is not None and self.serving.active:
                # serialize with live decode rounds
                self.serving.run_exclusive(_free)
                return
        except Exception:  # noqa: BLE001 — loop stopping: free directly
            pass
        try:
            _free()
        except Exception:  # noqa: BLE001 — release is best-effort
            pass

    @staticmethod
    def _raise_if_pressured(eng: TPUEngine, slot: int) -> None:
        """Single-sequence drivers (PD decode, token streaming) have no
        scheduler above them to preempt a victim for: when the engine
        freezes THIS slot at a pressure boundary, surface the pre-existing
        OutOfBlocksError contract instead of spinning on empty rounds.
        (The continuous batcher path recovers gracefully via
        preempt → spill → resume; these paths report the job as failed
        exactly as they did before pressure became a scheduling event.)"""
        from ...runtime.kv_cache import OutOfBlocksError

        p = eng.take_pressure()
        if p is not None and slot in p.slots:
            raise OutOfBlocksError(
                f"KV pool exhausted while decoding slot {slot} and no "
                "scheduler is attached to preempt for it"
            )

    def kv_receiver(self, raw: bytes) -> Dict[str, Any]:
        """Data-plane ``/kv/transfer`` hook: adopt a pushed handoff into this
        engine and index the slot by the kv_cache_key. Handles both the
        one-shot blob AND the streamed begin/piece/commit/abort messages
        (``runtime.kv_handoff.HandoffReceiver`` dispatches on the frame
        magic) — one endpoint, two wire modes."""
        from distributed_gpu_inference_tpu.runtime.kv_handoff import (
            HandoffReceiver,
        )

        if not self.loaded or self.engine is None:
            raise EngineLoadError("engine not loaded")
        with self._engine_lock:
            if self._handoff_rx is None or \
                    self._handoff_rx.engine is not self.engine:
                self._handoff_rx = HandoffReceiver(self.engine)
            # orphaned adoptions (decode job never came) age out here, on
            # the same serialized path that created them
            self._purge_stale_pd_slots()
            # adoption mutates the engine (block allocation + slot bind):
            # under a batcher it runs on the engine executor thread,
            # serialized with live decode rounds
            result = self._exclusive(lambda: self._handoff_rx.handle(raw))
            if result.get("slot") is not None:
                slot = result["slot"]
                key = result["kv_cache_key"]
                # pop-to-claim (same arbiter as the TTL purge): a decode
                # stage that already popped this key owns its sequence
                prev = self._pd_slots.pop(key, None)
                if prev is not None and prev[0] != slot:
                    # a re-run of the same prefill child (requeued after
                    # its completion report was lost post-commit) pushed
                    # the SAME key again: the new adoption supersedes the
                    # old one — free the superseded slot NOW. Overwriting
                    # the index without freeing would orphan it with no
                    # TTL entry, leaking the slot for the engine's life.
                    self.pd_stats["adopted_expired"] += 1
                    self._release_adopted_slot(self.engine, prev[0],
                                               prev[1])
                self._pd_slots[key] = (
                    slot, self.engine.slots[slot], time.monotonic()
                )
        return result

    # -- cluster-wide KV migration (round 13) --------------------------------

    def kv_export(self, raw: bytes) -> bytes:
        """Data-plane ``/kv/export`` hook: a cold peer asks for the longest
        locally-cached full-block prefix of its request's token ids. The
        answer is a framed sequence of the SAME chaos-hardened streamed
        handoff messages the ``/kv/transfer`` push path uses (prefix-only
        begin/piece/commit — ``runtime.kv_handoff.export_prefix_frames``),
        sourced from the device radix AND the host/remote spill tiers. An
        empty body means "nothing cached" and the peer recomputes."""
        from distributed_gpu_inference_tpu.runtime.kv_handoff import (
            _frame_blobs,
            export_prefix_frames,
            unpack_export_request,
        )

        if not self.loaded or self.engine is None:
            raise EngineLoadError("engine not loaded")
        if not self.kv_migrate_enabled:
            raise ValueError("kv migration disabled on this worker")
        req = unpack_export_request(raw)
        eng = self.engine
        if req.get("model_name") != eng.model_cfg.name:
            raise ValueError(
                f"model mismatch: engine={eng.model_cfg.name} "
                f"request={req.get('model_name')}"
            )
        if int(req.get("block_size") or 0) != eng.cfg.block_size:
            raise ValueError("block_size mismatch between engines")
        if bool(req.get("int8_kv")) != ("k_scale" in eng.kv):
            raise ValueError(
                "kv_cache_dtype mismatch: int8 pools can only export to "
                "int8 pools (and vice versa)"
            )
        max_blocks = min(
            self._kvmig_max_blocks, int(req.get("max_blocks") or 64)
        )
        token_ids = req.get("token_ids") or []
        if not token_ids and req.get("fp"):
            # fp-keyed pull (proactive replication): the cold puller never
            # saw the prompt — resolve the hinted fingerprint back to the
            # token ids our radix is keyed by. A miss (LRU churn, restart)
            # answers empty: an honest "nothing cached", never an error
            with self._kvmig_lock:
                token_ids = self._kvmig_fp_tokens.get(
                    str(req["fp"])) or []
        with self._engine_lock:
            frames, info = self._exclusive(lambda: export_prefix_frames(
                eng, token_ids, str(req.get("key") or ""),
                max_blocks=max_blocks,
                start_block=int(req.get("start_block") or 0),
            ))
        body = _frame_blobs(*frames) if frames else b""
        if frames:
            self.kv_migrate_stats["exports"] += 1
            self.kv_migrate_stats["export_bytes"] += len(body)
        return body

    def _kvmig_peer_allowed(self, url: str) -> bool:
        """Budget + per-peer backoff gate (taken together under one lock):
        a pull is only attempted when the concurrent-pull budget has room
        AND the peer is not inside a failure backoff window."""
        with self._kvmig_lock:
            _, until = self._kvmig_backoff.get(url, (0, 0.0))
            if time.monotonic() < until or \
                    self._kvmig_inflight >= self._kvmig_budget:
                return False
            self._kvmig_inflight += 1
            return True

    # a peer that REJECTED a pull (4xx: model/dtype/geometry mismatch or
    # migration disabled) is pinned out for this long — retrying a
    # permanent incompatibility after every backoff window would burn an
    # HTTP round-trip per hinted request forever
    _KVMIG_REJECT_PIN_S = 600.0

    def _kvmig_peer_result(self, url: str, ok: bool,
                           permanent: bool = False) -> None:
        with self._kvmig_lock:
            self._kvmig_inflight = max(0, self._kvmig_inflight - 1)
            if ok:
                self._kvmig_backoff.pop(url, None)
                return
            fails, _ = self._kvmig_backoff.get(url, (0, 0.0))
            fails += 1
            if permanent:
                self._kvmig_backoff[url] = (
                    fails, time.monotonic() + self._KVMIG_REJECT_PIN_S
                )
                return
            # PD re-prefill shape: the FIRST failure only falls back (no
            # wait — the request recomputes immediately); repeats arm a
            # jittered exponential window so a storm of hinted requests
            # doesn't hammer a dead peer
            delay = full_jitter_delay(
                self._kvmig_backoff_s, fails - 1, self._kvmig_rng
            ) if fails > 1 else 0.0
            self._kvmig_backoff[url] = (fails, time.monotonic() + delay)

    def _maybe_migrate_kv(self, params: Dict[str, Any]) -> None:
        """Honor a router ``kv_migrate_from`` hint: pull the hot prefix
        from the named peer BEFORE admission, landing it in our radix so
        the ragged prefill that follows reuses it. Every failure mode —
        peer dead mid-pull, corrupt piece, budget/backoff, no match —
        falls back to a plain recompute; a migration can never fail the
        request (counted: pulled / aborted / fallback_recompute)."""
        # never trust an inbound stash (the key is worker-internal: the
        # admission reuses the token ids THIS method encodes)
        params.pop("_kvmig_token_ids", None)
        hint = params.get("kv_migrate_from")
        if not isinstance(hint, dict):
            return
        tl = params.get("_flight_tl") or NULL_TIMELINE
        url = str(hint.get("data_plane_url") or "").rstrip("/")
        stats = self.kv_migrate_stats
        if not url or not self.kv_migrate_enabled or not self.loaded \
                or self.engine is None \
                or not self.engine.cfg.enable_prefix_cache:
            stats["fallback_recompute"] += 1
            tl.note("kv_migrate.fallback", reason="disabled")
            return
        if not self._kvmig_peer_allowed(url):
            stats["fallback_recompute"] += 1
            tl.note("kv_migrate.fallback", reason="budget_or_backoff")
            return
        import uuid as _uuid

        from distributed_gpu_inference_tpu.runtime.kv_handoff import (
            abort_message,
            pack_export_request,
            split_frames,
        )

        eng = self.engine
        key = f"kvmig-{_uuid.uuid4().hex[:12]}"
        begun = False
        try:
            cfg = GenerationConfig.from_params(params)
            token_ids = self._encode_prompt(
                params.get("messages") or params.get("prompt") or "", cfg
            )
            # hand the encode to the admission that follows (the request
            # builder skips its own template+tokenize pass)
            params["_kvmig_token_ids"] = token_ids
            if len(token_ids) < eng.cfg.block_size:
                stats["fallback_recompute"] += 1
                tl.note("kv_migrate.fallback", reason="short_prompt")
                self._kvmig_peer_result(url, ok=True)
                return
            # already warm locally? The router hints until OUR summary
            # advertises the prefix (a heartbeat cadence away — 30 s in
            # production), and a storm means MANY hinted requests for one
            # prefix: re-pulling what the first pull landed would
            # re-transfer the whole prefix per request and stall the warm
            # peer's decode rounds under its export executor. Probe the
            # local radix first (serialized like any engine read) and skip
            # when it already covers the request's full-block prefix (the
            # final block is forgone at worst — admission's
            # keep-one-token-fresh rule usually recomputes it anyway).
            bs = eng.cfg.block_size
            n_full = len(token_ids) // bs

            def _local_depth() -> int:
                return len(eng.manager.radix.match_prefix(token_ids))

            with self._engine_lock:
                local = self._exclusive(_local_depth)
            if local >= max(1, n_full - 1):
                stats["local_hits"] += 1
                tl.note("kv_migrate.local_hit", blocks=local)
                self._kvmig_peer_result(url, ok=True)
                return
            tl.note("kv_migrate.begin", peer=hint.get("worker_id"),
                    matched_blocks=hint.get("matched_blocks"))
            # source tier the router priced the pull at (validated — the
            # hint crosses the wire): keys the per-tier bandwidth counters
            # the plane's cost calibration delta-anchors
            tier = hint.get("tier")
            if tier not in ("dev", "host", "spill"):
                tier = "dev"
            t_pull = time.monotonic()
            req_raw = pack_export_request(
                key=key, token_ids=token_ids,
                model_name=eng.model_cfg.name,
                block_size=eng.cfg.block_size,
                int8_kv="k_scale" in eng.kv,
                max_blocks=self._kvmig_max_blocks,
                # the peer ships only what we are missing — our cached
                # leading blocks satisfy the commit coverage check locally
                start_block=local,
            )
            r = _faults.wrap_http(
                "worker.kv.pull",
                lambda: httpx.post(
                    url + "/kv/export", content=req_raw,
                    headers={"content-type": "application/octet-stream"},
                    timeout=self._kvmig_timeout_s,
                ),
                worker=str(getattr(self, "fault_tag", "") or ""),
            )
            r.raise_for_status()
            frames = split_frames(r.content)
            if not frames:
                # peer has nothing cached (evicted since the router's
                # summary): an honest miss, not a peer failure
                stats["fallback_recompute"] += 1
                tl.note("kv_migrate.fallback", reason="peer_miss")
                self._kvmig_peer_result(url, ok=True)
                return
            committed = None
            for frame in frames:
                # each frame runs through our own HandoffReceiver (via
                # kv_receiver — the chaos seam, duplicate tolerance, and
                # corrupt-piece session aborts all apply to pulls too)
                begun = True
                res = self.kv_receiver(frame)
                if res.get("state") == "committed":
                    committed = res
            if committed is None:
                raise ValueError("kv export response ended without commit")
            stats["pulled"] += 1
            # blocks the pull actually DELIVERED: the session chain minus
            # what our own cache already covered (partial-overlap pulls
            # ship only the missing tail)
            stats["pull_blocks"] += max(0, int(committed.get("blocks") or 0)
                                        - (int(committed.get("cached_tokens")
                                               or 0)
                                           // eng.cfg.block_size))
            pull_bytes = sum(len(f) for f in frames)
            stats["pull_bytes"] += pull_bytes
            # per-tier measured transfer: cumulative (bytes, wall-ms)
            # pairs whose heartbeat deltas give the plane one bandwidth
            # sample per pull (server/calibration.py)
            pull_ms = max(1, int((time.monotonic() - t_pull) * 1000.0))
            stats[f"pull_bytes_{tier}"] = (
                stats.get(f"pull_bytes_{tier}", 0) + pull_bytes)
            stats[f"pull_ms_{tier}"] = (
                stats.get(f"pull_ms_{tier}", 0) + pull_ms)
            tl.note("kv_migrate.pulled",
                    blocks=int(committed.get("blocks") or 0),
                    bytes=pull_bytes)
            self._kvmig_peer_result(url, ok=True)
        except Exception as exc:  # noqa: BLE001 — migration is best-effort
            stats["aborted"] += 1
            tl.note("kv_migrate.aborted")
            # a 4xx is the peer REJECTING the pull (incompatible engine,
            # migration disabled) — pin it out instead of re-knocking
            # after every backoff window (mirrors _pd_push's no-retry-4xx)
            permanent = (
                isinstance(exc, httpx.HTTPStatusError)
                and exc.response is not None
                and 400 <= exc.response.status_code < 500
            )
            self._kvmig_peer_result(url, ok=False, permanent=permanent)
            if begun:
                # drop a half-built session NOW instead of letting it pin
                # blocks until the receiver's TTL purge
                try:
                    self.kv_receiver(abort_message(key))
                except Exception:  # noqa: BLE001 — abort is best-effort
                    pass

    def kv_replicate(self, hints: Any) -> int:
        """Plane-hinted proactive prefix replication (round 20): the
        heartbeat response named hot prefixes this worker does NOT hold
        that a warm peer exports — pull them NOW, ahead of the predicted
        storm, over the same chaos-hardened ``/kv/export`` protocol the
        reactive migrate driver uses (same budget, same per-peer backoff,
        same recompute-on-any-failure stance). Pulls run on a daemon
        thread — a prefetch must never sit in the heartbeat loop. Returns
        the number of hints accepted (0 = all malformed/disabled; a
        budget-full drop happens later, on the thread, and the plane
        simply re-hints after its cooldown)."""
        if not self.kv_migrate_enabled or not self.loaded \
                or self.engine is None \
                or not self.engine.cfg.enable_prefix_cache:
            return 0
        todo = []
        for h in hints if isinstance(hints, list) else []:
            if not isinstance(h, dict):
                continue
            fps = h.get("fps")
            url = str(h.get("data_plane_url") or "").rstrip("/")
            if not url or not isinstance(fps, list) or not fps \
                    or not all(isinstance(f, str) for f in fps):
                continue
            todo.append((h, url, [str(f) for f in fps]))
        if not todo:
            return 0
        threading.Thread(
            target=self._kv_replicate_run, args=(todo,),
            name="kv-replicate", daemon=True,
        ).start()
        return len(todo)

    def _kv_replicate_run(self, todo: List[tuple]) -> None:
        for hint, url, fps in todo:
            try:
                self._kv_replicate_pull(hint, url, fps)
            except Exception:  # noqa: BLE001 — prefetch is best-effort
                pass

    def _kv_replicate_pull(self, hint: Dict[str, Any], url: str,
                           fps: List[str]) -> None:
        eng = self.engine
        hot = self.prefix_hot
        stats = self.kv_migrate_stats
        if eng is None:
            return
        if hot is not None and fps[-1] in hot.snapshot():
            return   # a racing request already landed it — nothing to do
        if not self._kvmig_peer_allowed(url):
            return   # budget/backoff: drop; the plane re-hints past its
            #          cooldown, and prefetch must never amplify load
        import uuid as _uuid

        from distributed_gpu_inference_tpu.runtime.kv_handoff import (
            abort_message,
            pack_export_request,
            split_frames,
        )

        key = f"kvrep-{_uuid.uuid4().hex[:12]}"
        tier = hint.get("tier")
        if tier not in ("dev", "host", "spill"):
            tier = "dev"
        begun = False
        try:
            t_pull = time.monotonic()
            # fp-keyed: we never saw the prompt — the warm exporter
            # resolves the fingerprint to its own token ids, and the
            # begin frame carries them back, so our HandoffReceiver
            # commits into the radix keyed exactly as an admission probes
            req_raw = pack_export_request(
                key=key, token_ids=[],
                model_name=eng.model_cfg.name,
                block_size=eng.cfg.block_size,
                int8_kv="k_scale" in eng.kv,
                max_blocks=self._kvmig_max_blocks,
                fp=fps[-1],
            )
            r = _faults.wrap_http(
                "worker.kv.pull",
                lambda: httpx.post(
                    url + "/kv/export", content=req_raw,
                    headers={"content-type": "application/octet-stream"},
                    timeout=self._kvmig_timeout_s,
                ),
                worker=str(getattr(self, "fault_tag", "") or ""),
            )
            r.raise_for_status()
            frames = split_frames(r.content)
            if not frames:
                # the exporter's fp→tokens map churned it out, or its
                # cache evicted: an honest miss, not a peer failure
                stats["replicate_miss"] += 1
                self._kvmig_peer_result(url, ok=True)
                return
            committed = None
            for frame in frames:
                begun = True
                res = self.kv_receiver(frame)
                if res.get("state") == "committed":
                    committed = res
            if committed is None:
                raise ValueError("kv export response ended without commit")
            stats["replicated"] += 1
            pull_bytes = sum(len(f) for f in frames)
            stats["pull_bytes"] += pull_bytes
            pull_ms = max(1, int((time.monotonic() - t_pull) * 1000.0))
            stats[f"pull_bytes_{tier}"] = (
                stats.get(f"pull_bytes_{tier}", 0) + pull_bytes)
            stats[f"pull_ms_{tier}"] = (
                stats.get(f"pull_ms_{tier}", 0) + pull_ms)
            if hot is not None:
                # advertise the adopted prefix so the next summary stops
                # the hints (advisory like every entry: a shallower-than-
                # hinted pull costs at most one partial re-prefill)
                hot.note_fingerprints(fps)
            self._kvmig_peer_result(url, ok=True)
        except Exception as exc:  # noqa: BLE001 — prefetch is best-effort
            stats["replicate_aborted"] += 1
            permanent = (
                isinstance(exc, httpx.HTTPStatusError)
                and exc.response is not None
                and 400 <= exc.response.status_code < 500
            )
            self._kvmig_peer_result(url, ok=False, permanent=permanent)
            if begun:
                try:
                    self.kv_receiver(abort_message(key))
                except Exception:  # noqa: BLE001 — abort is best-effort
                    pass

    def kv_migrate_wire_stats(self) -> Optional[Dict[str, int]]:
        """Cumulative KV-migration counters (pull outcomes + export
        service) — heartbeat ``engine_stats["kv_migrate"]``, delta-anchored
        into ``kv_migrations_total{outcome}`` / ``kv_migration_bytes_total``
        on the control plane. None when this engine never migrated."""
        out = {k: int(v) for k, v in self.kv_migrate_stats.items() if v}
        rx = self._handoff_rx
        if rx is not None:
            v = int(rx.stats.get("prefix_commits", 0) or 0)
            if v:
                out["prefix_commits"] = v
        return out or None

    def kv_spill_wire_stats(self) -> Optional[Dict[str, int]]:
        """Cumulative spill-tier IO health counters (put/get errors,
        corrupt-entry quarantines, breaker states/trips) plus refused
        corrupt checkpoints — heartbeat ``engine_stats["kv_spill"]``,
        delta-anchored into ``kv_spill_errors_total{tier}`` /
        ``spill_quarantined_total{tier,reason}`` / ``io_breaker_state``
        on the control plane. None when every counter is zero and all
        breakers are closed (no payload bloat)."""
        eng = self.engine
        mgr = getattr(eng, "manager", None) if eng is not None else None
        out: Dict[str, int] = {}
        if mgr is not None:
            ws = mgr.spill_wire_stats()
            out.update({k: int(v) for k, v in ws.items() if v})
            if out:
                # once anything has fired, ship breaker states INCLUDING
                # zeros: a recovered breaker must drive the plane's
                # io_breaker_state gauge back to healthy, not freeze it
                # at its sickest reading
                out.update({k: int(v) for k, v in ws.items()
                            if k.endswith("_state")})
        if self.ckpt_corrupt:
            out["ckpt_corrupt"] = int(self.ckpt_corrupt)
        return out or None

    def _ckpt_from_wire(self, ckpt: Any) -> Optional[PreemptedSequence]:
        """Parse a claim's server-held checkpoint, degrading CORRUPTION to
        a fresh recompute: a torn/bit-flipped store row (bad crc, missing
        fields, wrong version) returns None — the driver falls through to
        its from-scratch path — instead of failing the whole resumed job.
        Mirrors the spill-tier quarantine contract: persisted state is an
        optimization, never a single point of failure."""
        if not isinstance(ckpt, dict):
            return None
        try:
            return PreemptedSequence.from_wire(ckpt)
        except Exception:  # noqa: BLE001 — ValueError + anything torn JSON does
            self.ckpt_corrupt += 1
            return None

    # -- request flight recorder (round 14) ---------------------------------

    def _flight_timeline(self, params: Dict[str, Any]) -> Any:
        """A Timeline for the request iff it carries a ``trace_id`` (the
        shared no-op NULL_TIMELINE otherwise — hot paths note
        unconditionally). Adopts the poll-pickup instant the worker claim
        path stamped into params before dispatch."""
        tl = timeline_for(
            params, source=str(getattr(self, "fault_tag", "") or "")
        )
        ts = params.pop("_flight_picked_up_ts", None)
        if ts is not None and tl.enabled:
            tl.note_at("worker.picked_up", ts)
        return tl

    def _flight_finish(self, tl: Any,
                       payload: Optional[Dict[str, Any]] = None) -> None:
        """Close one request's timeline: count it, retain it in the
        bounded heartbeat ring (the channel direct streams ship through),
        and attach the wire to the result payload when one is given (the
        complete_job channel). Never raises — the recorder is advisory."""
        try:
            if not getattr(tl, "enabled", False):
                return
            wire = tl.wire(done=True)
            if wire is None:
                return
            self.flight_stats["timelines"] += 1
            if tl.dropped:
                self.flight_stats["events_dropped"] += int(tl.dropped)
            self._flight_recent.append(wire)
            if payload is not None:
                payload["timeline"] = wire
        except Exception:  # noqa: BLE001 — never fail a request for this
            pass

    def flight_wire_stats(self) -> Optional[Dict[str, Any]]:
        """Heartbeat ``engine_stats["flight"]`` payload: cumulative
        counters (delta-anchored on the plane, restart re-anchors) plus
        the bounded ring of recently-completed timelines. The ring is
        re-shipped every beat — the plane's ingest unions events per
        (trace, source) keyed by name+timestamp, so duplicate delivery
        is a no-op.
        None while nothing was ever traced (no payload bloat)."""
        if not self.flight_stats["timelines"]:
            return None
        return {
            "timelines": int(self.flight_stats["timelines"]),
            "events_dropped": int(self.flight_stats["events_dropped"]),
            "recent": list(self._flight_recent),
        }

    # -- crash-safe generation: live checkpoints + resumable drivers --------

    @property
    def handoff_sessions_purged(self) -> int:
        """Cumulative abandoned streamed-handoff sessions purged by this
        engine's receiver — rides the heartbeat into
        ``kv_handoff_sessions_purged_total``."""
        rx = self._handoff_rx
        return int(rx.stats.get("sessions_purged", 0)) if rx is not None else 0

    def pd_wire_stats(self) -> Optional[Dict[str, int]]:
        """Cumulative PD handoff lifecycle counters (sender outcomes +
        receiver abort/purge reasons) — heartbeat ``engine_stats["pd"]``,
        delta-anchored into ``pd_handoffs_total{outcome}`` /
        ``pd_handoff_bytes_total`` on the control plane. None when this
        engine never touched a handoff (no payload bloat)."""
        out = {k: int(v) for k, v in self.pd_stats.items() if v}
        rx = self._handoff_rx
        if rx is not None:
            for src, dst in (("rx_aborts", "rx_aborts"),
                             ("purged_ttl", "rx_purged_ttl"),
                             ("purged_no_progress", "rx_purged_no_progress"),
                             ("purged_cap", "rx_purged_cap")):
                v = int(rx.stats.get(src, 0) or 0)
                if v:
                    out[dst] = v
        return out or None

    def _register_live(self, key: str, kind: str, epoch: int,
                       request_id: str) -> None:
        with self._live_lock:
            self._live[key] = {
                "kind": kind, "epoch": int(epoch), "request_id": request_id,
            }

    def _unregister_live(self, key: str) -> None:
        with self._live_lock:
            self._live.pop(key, None)

    def interrupt_live(self) -> None:
        """Graceful drain: queued-job drivers freeze at the next step
        boundary and raise :class:`JobMigrated` with their checkpoint.
        Direct streams keep running to completion (they checkpoint
        continuously, so a client of a worker that then vanishes resumes
        from the last checkpoint on a failover peer)."""
        self._interrupt.set()

    def _snapshot_live(self, key: str,
                       info: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Portable checkpoint entry for one live generation, or None when
        the slot is gone/finished/unreadable. Runs WITHOUT the engine lock
        (heartbeat thread): a torn read mid-finish degrades to a skipped
        sample — the next heartbeat retries."""
        eng = self.engine
        if eng is None:
            return None
        try:
            for slot, s in enumerate(list(eng.slots)):
                if s is None or s.request.request_id != info["request_id"]:
                    continue
                pre = eng.snapshot_slot(slot)
                if pre.request.request_id != info["request_id"]:
                    # the slot was freed and reused by ANOTHER request
                    # between the scan and the snapshot (we read without
                    # the engine lock): a foreign sequence must never be
                    # checkpointed under this key — skip the sample
                    return None
                return {
                    "kind": info["kind"], "key": key,
                    "epoch": info["epoch"], "state": pre.to_wire(),
                }
        except Exception:  # noqa: BLE001 — checkpointing must never break serving
            return None
        return None

    def checkpoint_live(self) -> List[Dict[str, Any]]:
        """Checkpoint entries for every in-flight generation — the payload
        the worker piggybacks on heartbeats (``checkpoints`` field)."""
        with self._live_lock:
            live = dict(self._live)
        out = []
        for key, info in live.items():
            entry = self._snapshot_live(key, info)
            if entry is not None:
                out.append(entry)
        return out

    def _push_checkpoint(self, entry: Optional[Dict[str, Any]],
                         sync: bool = False) -> None:
        """Push one checkpoint through the configured sink (control-plane
        client); sink failures are swallowed — a flaky control plane must
        never abort the generation it is trying to protect.

        ``sync=True`` blocks (the one-time ADMISSION checkpoint: a kill at
        token 1 must already find a resumable record, and the pre-first-
        token cost is noise next to prefill). Cadence pushes go through
        the latest-wins background pusher so the decode loop never waits
        on the control plane."""
        if self.checkpoint_sink is None or entry is None:
            return
        if sync:
            try:
                self.checkpoint_sink(entry)
            except Exception:  # noqa: BLE001
                pass
            return
        if self._ckpt_pusher is None:
            self._ckpt_pusher = _CheckpointPusher(self._sink_now)
        self._ckpt_pusher.put(entry)

    def _sink_now(self, entry: Dict[str, Any]) -> None:
        sink = self.checkpoint_sink          # resolved at drain time
        if sink is not None:
            sink(entry)

    def _job_inference(self, params: Dict[str, Any],
                       ctx: Dict[str, Any]) -> Dict[str, Any]:
        """Queued-job driver with failover support: submits (or RESUMES from
        the claim's server-held checkpoint), registers for heartbeat
        checkpointing, decodes in bounded multi-step rounds so a drain
        interrupt lands at a step boundary, and raises :class:`JobMigrated`
        with the frozen state instead of finishing when interrupted.

        Continuations are byte-identical greedy / seed-stable sampled: the
        resume path restores the PRNG key words and recomputes only the
        suffix the prefix cache / spill tiers don't still hold."""
        cfg = GenerationConfig.from_params(params)
        key = str(ctx.get("key") or "")
        epoch = int(ctx.get("epoch") or 0)
        ckpt = ctx.get("checkpoint")
        eng = self.engine
        if eng is None or not self.loaded:
            raise EngineLoadError("engine not loaded")
        if self.serving is not None and self.serving.active:
            return self._job_inference_serving(params, cfg, key, epoch, ckpt)
        tl = params.pop("_flight_tl", NULL_TIMELINE)
        tl.note("worker.start", path="job")
        if not isinstance(ckpt, dict) and self._spec is not None \
                and cfg.temperature <= 0.0:
            # standalone tree-speculative decoder (engine=jax-speculative):
            # its fused tree rounds are neither interruptible nor
            # checkpointable, but the multi-x decode speedup should not be
            # lost on every queued job. Fresh spec-eligible jobs take the
            # legacy fast path — a drain finishes them and a crash replays
            # from scratch, exactly the pre-failover contract.
            return super().inference(params)
        t0 = time.perf_counter()
        pre = self._ckpt_from_wire(ckpt)
        if pre is not None:
            remaining = (pre.request.sampling.max_new_tokens
                         - len(pre.generated))
            if remaining <= 0:
                # the checkpoint already holds the whole generation: the
                # previous worker died between its last decode and its
                # complete_job — deliver without touching the engine
                return self._finish_payload(
                    list(pre.generated), pre.prompt_len,
                    pre.cached_tokens, "length", cfg, None,
                    time.perf_counter() - t0,
                )
            slot = eng.resume(pre)
            request_id = pre.request.request_id
        else:
            req = self._build_request(
                params.get("messages") or params.get("prompt") or "", cfg,
                token_ids=params.pop("_kvmig_token_ids", None),
            )
            slot = eng.submit(req)
            request_id = req.request_id
        self._register_live(key, "job", epoch, request_id)
        try:
            while eng.slots[slot] is not None and \
                    eng.slots[slot].finish_reason is None:
                if self._interrupt.is_set():
                    pre = eng.preempt_slot(slot)
                    raise JobMigrated(pre.to_wire(),
                                      tokens=len(pre.generated))
                eng.decode_multi()
                slot = self._ride_out_pressure(eng, slot)
        except JobMigrated:
            raise
        except Exception:
            if eng.slots[slot] is not None:
                eng.finish_slot(slot, cache=False)
            raise
        finally:
            self._unregister_live(key)
        resp = eng.finish_slot(slot)
        if tl.enabled and resp.extra.get("t_first_token") is not None:
            # engine-observed instant, not loop-observed
            tl.note_at("batcher.first_token", resp.extra["t_first_token"])
        tl.note("worker.done")
        payload = self._finish_payload(
            list(resp.token_ids), resp.prompt_tokens, resp.cached_tokens,
            resp.finish_reason or "stop", cfg, resp.ttft_ms,
            time.perf_counter() - t0,
        )
        self._flight_finish(tl, payload)
        return payload

    def _job_inference_serving(self, params: Dict[str, Any],
                               cfg: GenerationConfig, key: str, epoch: int,
                               ckpt: Any) -> Dict[str, Any]:
        """Queued-job driver through the batcher front-end: resumes from
        the claim's server-held checkpoint, shares decode rounds with every
        other in-flight request, registers for heartbeat checkpointing, and
        converts a drain interrupt (``interrupt_live``) into
        :class:`JobMigrated` — the batcher freezes the sequence at the next
        step boundary and hands back the portable checkpoint."""
        t0 = time.perf_counter()
        tl = params.pop("_flight_tl", NULL_TIMELINE)
        tl.note("worker.start", path="job_serving")
        pre = self._ckpt_from_wire(ckpt)
        if pre is not None:
            remaining = (pre.request.sampling.max_new_tokens
                         - len(pre.generated))
            tl.note("worker.resume_from_checkpoint",
                    tokens=len(pre.generated))
            if remaining <= 0:
                # the checkpoint already holds the whole generation: the
                # previous worker died between its last decode and its
                # complete_job — deliver without touching the engine
                payload = self._finish_payload(
                    list(pre.generated), pre.prompt_len,
                    pre.cached_tokens, "length", cfg, None,
                    time.perf_counter() - t0,
                )
                self._flight_finish(tl, payload)
                return payload
            req = pre.request
        else:
            req = self._build_request(
                params.get("messages") or params.get("prompt") or "", cfg,
                token_ids=params.pop("_kvmig_token_ids", None),
            )
            if params.get("priority") is not None:
                req.priority = int(params.get("priority") or 0)
        # parity with the legacy driver: a FRESH spec-eligible greedy job
        # keeps the standalone tree decoder's multi-x speedup by waiving
        # failover hooks (the wave is neither interruptible nor
        # checkpointable — a drain finishes it, a crash replays it)
        spec_fast = (
            pre is None and self._spec is not None
            and cfg.temperature <= 0.0
            and params.get("speculative") is not False
        )
        interrupt = None if spec_fast else self._interrupt
        if not spec_fast:
            self._register_live(key, "job", epoch, req.request_id)
        try:
            resp = self.serving.submit(
                req, resume_from=pre, interrupt=interrupt,
                flight=tl if tl.enabled else None,
            )
        except RequestMigrated as mig:
            raise JobMigrated(mig.pre.to_wire(),
                              tokens=len(mig.pre.generated)) from None
        finally:
            if not spec_fast:
                self._unregister_live(key)
        if resp.error is not None:
            _raise_serving(resp)
        tl.note("worker.done")
        payload = self._finish_payload(
            list(resp.token_ids), resp.prompt_tokens, resp.cached_tokens,
            resp.finish_reason or "stop", cfg, resp.ttft_ms,
            time.perf_counter() - t0,
        )
        self._flight_finish(tl, payload)
        return payload

    def _ride_out_pressure(self, eng: TPUEngine, slot: int) -> int:
        """Queued-job KV-pressure recovery without a batcher above us:
        when the engine freezes THIS slot at a pressure boundary, preempt
        it (releasing reserved tails and parking its blocks in the
        evictable prefix cache) and resume immediately — that recovers
        every self-caused squeeze the batcher path would. No wait loop:
        this runs UNDER the engine lock, and the paths that free
        externally-pinned blocks (handoff adopt-sessions, retained PD
        slots) need that same lock, so sleeping here could never observe
        a free. If the pool still cannot hold the sequence the blocks are
        genuinely pinned — fail the job honestly. A drain interrupt
        converts the frozen state into :class:`JobMigrated` instead (the
        checkpoint is already in hand)."""
        from ...runtime.kv_cache import OutOfBlocksError

        p = eng.take_pressure()
        if p is None or slot not in p.slots:
            return slot
        pre = eng.preempt_slot(slot)
        if self._interrupt.is_set():
            raise JobMigrated(pre.to_wire(), tokens=len(pre.generated))
        try:
            return eng.resume(pre)
        except OutOfBlocksError:
            raise OutOfBlocksError(
                "KV pool cannot hold the queued job's sequence even after "
                "preempt/evict — blocks are pinned by concurrent sessions"
            ) from None

    def _finish_payload(self, token_ids: List[int], prompt_tokens: int,
                        cached_tokens: int, finish_reason: str,
                        cfg: GenerationConfig, ttft_ms: Optional[float],
                        e2e_s: float) -> Dict[str, Any]:
        """Result payload shared by the fresh and resumed queued paths —
        same decode + stop-string truncation as ``_generate``."""
        out_text = self.tokenizer.decode(token_ids) if self.tokenizer else ""
        finish = finish_reason
        for s in cfg.stop:
            idx = out_text.find(s)
            if idx >= 0:
                out_text = out_text[:idx]
                finish = "stop"
                break
        return GenerationResult(
            text=out_text,
            prompt_tokens=prompt_tokens,
            completion_tokens=len(token_ids),
            cached_tokens=cached_tokens,
            finish_reason=finish,
            ttft_ms=ttft_ms if ttft_ms is not None else e2e_s * 1000.0,
        ).to_result_payload()

    def _generate(self, prompt_or_messages: Any,
                  cfg: GenerationConfig) -> GenerationResult:
        req = self._build_request(prompt_or_messages, cfg)
        t0 = time.perf_counter()
        # speculative path only for greedy prompts within one prefill
        # bucket: the tree decoder's prefill is single-shot, so longer
        # prompts take the paged engine's CHUNKED prefill instead of
        # compiling per prompt length
        use_spec = (
            self._spec is not None
            and cfg.temperature <= 0.0
            and len(req.prompt_token_ids or [])
            <= self.engine.cfg.prefill_buckets[-1]
        )
        if use_spec:
            resp = self._spec.generate([req])[0]
        else:
            resp = self.engine.generate([req], use_multi_step=True)[0]
        e2e_ms = (time.perf_counter() - t0) * 1000.0
        out_text = self.tokenizer.decode(resp.token_ids)
        finish = resp.finish_reason or "stop"
        for s in cfg.stop:  # host-side stop strings (tokenizer-agnostic)
            idx = out_text.find(s)
            if idx >= 0:
                out_text = out_text[:idx]
                finish = "stop"
                break
        return GenerationResult(
            text=out_text,
            prompt_tokens=resp.prompt_tokens,
            completion_tokens=resp.completion_tokens,
            cached_tokens=resp.cached_tokens,
            finish_reason=finish,
            ttft_ms=resp.ttft_ms if resp.ttft_ms is not None else e2e_ms,
        )

    # -- token streaming (reference SSE path, llm_sglang.py:358-416) ---------

    def stream(self, params: Dict[str, Any],
               cancel: Optional[Any] = None):
        """Sync generator of SSE chunks — dispatches to the batcher-backed
        serving stream (default: the sequence SHARES decode rounds with
        every other in-flight request) or the legacy per-step engine driver
        (``serving.mode: direct``). Both emit the same chunk contract:
        ``{"text_delta", "token_ids", "offset"}...`` then a final
        ``{"done": True, "finish_reason", "usage", "offset"}``."""
        tl = self._flight_timeline(params)
        if tl.enabled:
            params["_flight_tl"] = tl
        self._maybe_migrate_kv(params)
        if self.serving is not None and self.serving.active:
            return self._stream_serving(params, cancel=cancel)
        if tl.enabled:
            params.pop("_flight_tl", None)
            return self._stream_direct_traced(tl, params, cancel)
        return self._stream_direct(params, cancel=cancel)

    def _stream_direct_traced(self, tl: Any, params: Dict[str, Any],
                              cancel: Optional[Any] = None):
        """Traced wrapper for the legacy per-step stream driver: the
        driver itself predates the recorder, so the wrapper notes the
        stream boundaries and closes the timeline — attaching the wire to
        the final chunk exactly like ``_stream_serving`` does (streams
        never pass ``complete_job``; the heartbeat ring ships it too)."""
        tl.note("worker.stream.start", path="direct")
        done = False
        try:
            for chunk in self._stream_direct(params, cancel=cancel):
                if isinstance(chunk, dict) and chunk.get("done"):
                    done = True
                    tl.note("worker.stream.done",
                            finish_reason=chunk.get("finish_reason"))
                    self._flight_finish(tl, chunk)
                yield chunk
        finally:
            if not done:
                # abandoned stream (client hung up / chaos kill): the
                # partial timeline still ships via the heartbeat ring
                tl.note("worker.stream.done", finish_reason="abandoned")
                self._flight_finish(tl)

    def _stream_checkpoint_tail(self, pre: PreemptedSequence,
                                cfg: GenerationConfig, stamp: Any,
                                holdback: int, resume_from: int,
                                resume_text: int):
        """Serve the un-consumed tail of a COMPLETE checkpoint (the donor
        died between its last decode and the final SSE flush) straight from
        it, through the SAME stop-string/holdback machinery the live loop
        uses — the client must receive exactly the text an undropped run
        would have (incl. the held-back chars and the stop-truncated
        finish)."""
        gen = list(pre.generated)
        m = min(resume_from, len(gen))
        full = self.tokenizer.decode(gen)
        stop_idx = -1
        for st_ in cfg.stop:
            idx = full.find(st_)
            if idx >= 0 and (stop_idx < 0 or idx < stop_idx):
                stop_idx = idx
        finish = "length"
        target = full
        if stop_idx >= 0:
            target = full[:stop_idx]
            finish = "stop"
        raw_prev = self.tokenizer.decode(gen[:m])
        prev = raw_prev
        if holdback:
            prev = prev[:max(len(prev) - holdback, 0)]
        if resume_text > len(prev):
            # the client already received part of the held-back tail (a
            # flush crossed before the drop) — never re-deliver those
            # characters
            prev = target[:resume_text]
        delta = target[len(prev):] if len(prev) < len(target) else ""
        tail = [] if stop_idx >= 0 else gen[m:]
        if delta or tail:
            yield stamp({"text_delta": delta, "token_ids": tail}, len(gen))
        yield stamp({
            "done": True, "finish_reason": finish,
            "usage": {
                "prompt_tokens": pre.prompt_len,
                "completion_tokens": len(gen),
                "total_tokens": pre.prompt_len + len(gen),
                "cached_tokens": pre.cached_tokens,
            },
        }, len(gen))

    def _stream_serving(self, params: Dict[str, Any],
                        cancel: Optional[Any] = None):
        """Batcher-backed token streaming: the request is submitted to the
        serving front-end with a per-round observer; deltas are derived
        from the observer's monotonic token snapshots with the exact
        stop-string/holdback/splice machinery of the legacy per-step
        driver, so exactly-once token offsets and checkpoint/resume hold
        while the sequence shares decode rounds with other slots."""
        cfg = GenerationConfig.from_params(params)
        tl = params.pop("_flight_tl", NULL_TIMELINE)
        tl.note("worker.stream.start")
        ctx = params.get("_failover_ctx")
        ctx = ctx if isinstance(ctx, dict) else {}
        key = str(ctx.get("key") or params.get("stream_id") or "") or None
        epoch = int(ctx.get("epoch") or 0)
        ckpt = ctx.get("checkpoint")
        resume_from = int(ctx.get("offset") or 0)
        resume_text = int(ctx.get("text_offset") or 0)

        def stamp(chunk: Dict[str, Any], offset: int) -> Dict[str, Any]:
            if key is not None:
                chunk["stream_id"] = key
                chunk["offset"] = offset
            return chunk

        holdback = max((len(s) for s in cfg.stop), default=0)
        holdback = max(holdback - 1, 0)
        pre = self._ckpt_from_wire(ckpt)
        if pre is not None:
            remaining = (pre.request.sampling.max_new_tokens
                         - len(pre.generated))
            if remaining <= 0:
                yield from self._stream_checkpoint_tail(
                    pre, cfg, stamp, holdback, resume_from, resume_text
                )
                return
            req = pre.request
        else:
            req = self._build_request(
                params.get("messages") or params.get("prompt") or "", cfg,
                token_ids=params.pop("_kvmig_token_ids", None),
            )
            if params.get("priority") is not None:
                req.priority = int(params.get("priority") or 0)
        # spec waves buffer whole generations — a stream needs per-round
        # progress, so it always decodes through the paged slots
        req.params["speculative"] = False
        request_id = req.request_id
        live_info = {"kind": "stream", "epoch": epoch,
                     "request_id": request_id}

        snaps: "_queue_mod.Queue" = _queue_mod.Queue()
        _DONE = object()
        stop_evt = threading.Event()   # batcher-side abort (cancel / stop cut)
        fut = self.serving.submit_async(
            req, observer=lambda toks: snaps.put(toks),
            cancel=stop_evt, resume_from=pre,
            flight=tl if tl.enabled else None,
        )
        fut.add_done_callback(lambda f: snaps.put(_DONE))

        last_ckpt = len(pre.generated) if pre is not None else 0
        if key is not None:
            self._register_live(key, "stream", epoch, request_id)
            # admission checkpoint (synchronous): even a worker killed
            # before its first heartbeat leaves a resumable record. The
            # request may still be QUEUED, so the record is synthesized
            # engine-free (the resumed prefix when resuming, zero tokens
            # when fresh) — cadence pushes below carry live slot state.
            self._push_checkpoint({
                "kind": "stream", "key": key, "epoch": epoch,
                "state": (pre or synthesize_checkpoint(req)).to_wire(),
            }, sync=True)
        sp = _StreamSplicer(self.tokenizer, cfg, holdback,
                            resume_from, resume_text)
        stopping = False               # stop string matched: drain silently
        final = None
        try:
            while True:
                try:
                    item = snaps.get(timeout=0.05) if cancel is not None \
                        else snaps.get()
                except _queue_mod.Empty:
                    # cancel-poll timeout: honor a client disconnect even
                    # while the request is still queued (no snapshots yet)
                    if cancel is not None and cancel.is_set():
                        stop_evt.set()
                    continue
                if item is _DONE:
                    final = fut.result()   # raises on engine/submit failure
                    if final.error is not None:
                        _raise_serving(final)
                    gen = list(final.token_ids)
                    finished = True
                else:
                    gen = list(item)
                    finished = False
                # a round snapshot may carry SEVERAL new tokens — process
                # them one at a time so the SSE cadence (one event per
                # token, each stamped with its offset) is identical to the
                # legacy per-step driver: clients, resume splices, and the
                # chaos kill points all count events
                ks = list(range(sp.sent_tokens + 1, len(gen) + 1))
                if not ks and finished:
                    ks = [len(gen)]       # flush held-back chars at EOS
                for k in ks:
                    if stopping:
                        break
                    fin_k = finished and k == len(gen)
                    chunk, stop_cut = sp.advance(gen[:k], fin_k)
                    if chunk is not None:
                        yield stamp(chunk, sp.sent_tokens)
                    if stop_cut and not fin_k:
                        # release the slot; the final (abort) response
                        # still carries the full usage accounting
                        stopping = True
                        stop_evt.set()
                if finished:
                    break
                if cancel is not None and cancel.is_set():
                    stop_evt.set()
                if key is not None and self._ckpt_interval > 0 \
                        and len(gen) - last_ckpt >= self._ckpt_interval:
                    self._push_checkpoint(
                        self._snapshot_live(key, live_info)
                    )
                    last_ckpt = len(gen)
        finally:
            stop_evt.set()     # no-op when already resolved; aborts a run
            #                    abandoned by a closed generator
            if key is not None:
                self._unregister_live(key)
        finish = sp.finish_override or final.finish_reason
        tl.note("worker.stream.done", finish_reason=finish)
        done_chunk = {
            "done": True,
            "finish_reason": finish,
            "usage": {
                "prompt_tokens": final.prompt_tokens,
                "completion_tokens": final.completion_tokens,
                "total_tokens": final.prompt_tokens
                + final.completion_tokens,
                "cached_tokens": final.cached_tokens,
            },
        }
        # the final SSE chunk carries the worker-side timeline (streams
        # never pass complete_job) — the heartbeat ring ships it to the
        # plane too, so either consumer can attribute the stream's phases
        self._flight_finish(tl, done_chunk)
        yield stamp(done_chunk, sp.sent_tokens)
        # NOTE: as in the legacy driver, the server-held checkpoint is NOT
        # retired on completion — the worker cannot know the final SSE
        # bytes reached the client; the control plane ages streams out.

    def _stream_direct(self, params: Dict[str, Any],
                       cancel: Optional[Any] = None):
        """Legacy per-step engine driver (``serving.mode: direct``).
        Sync generator of chunks:
        ``{"text_delta", "token_ids", "offset"}...`` then a final
        ``{"done": True, "finish_reason", "usage", "offset"}``. Drives the
        engine per-step so tokens flush as they are sampled.

        ``cancel``: a ``threading.Event``-like object; when set, generation
        stops at the next step boundary and the slot is released (client
        disconnects must not keep burning decode budget).

        Stop-string handling matches the blocking path exactly: the last
        ``len(longest_stop) - 1`` characters are held back until the stop
        scan clears them, so a stop sequence spanning chunk boundaries never
        leaks its prefix.

        Crash-safe streams: when the caller supplies a ``_failover_ctx``
        (direct server) the stream registers for heartbeat checkpointing,
        pushes checkpoints through ``checkpoint_sink`` at admission and
        every ``checkpoint_interval_tokens``, and stamps every event with a
        monotonic token ``offset``. A resume context (checkpoint + the
        client's consumed offset) restores the sequence via
        ``TPUEngine.resume`` and SPLICES: tokens the client already holds
        are regenerated (deterministically) but never re-emitted — no gap,
        no duplicate."""
        cfg = GenerationConfig.from_params(params)
        ctx = params.get("_failover_ctx")
        ctx = ctx if isinstance(ctx, dict) else {}
        key = str(ctx.get("key") or params.get("stream_id") or "") or None
        epoch = int(ctx.get("epoch") or 0)
        ckpt = ctx.get("checkpoint")
        resume_from = int(ctx.get("offset") or 0)
        # characters the client already consumed: holdback flushes advance
        # text WITHOUT advancing the token offset, so the token splice
        # alone could re-deliver (or withhold) the flushed tail
        resume_text = int(ctx.get("text_offset") or 0)
        eng = self.engine

        def stamp(chunk: Dict[str, Any], offset: int) -> Dict[str, Any]:
            if key is not None:
                chunk["stream_id"] = key
                chunk["offset"] = offset
            return chunk

        holdback = max((len(s) for s in cfg.stop), default=0)
        holdback = max(holdback - 1, 0)
        pre = self._ckpt_from_wire(ckpt)
        if pre is not None:
            remaining = (pre.request.sampling.max_new_tokens
                         - len(pre.generated))
            if remaining <= 0:
                # the checkpoint already holds the full generation (the
                # donor died between its last decode and the final SSE
                # flush): serve the un-consumed tail straight from it
                yield from self._stream_checkpoint_tail(
                    pre, cfg, stamp, holdback, resume_from, resume_text
                )
                return
            slot = eng.resume(pre)
            request_id = pre.request.request_id
        else:
            req = self._build_request(
                params.get("messages") or params.get("prompt") or "", cfg,
                token_ids=params.pop("_kvmig_token_ids", None),
            )
            slot = eng.submit(req)
            request_id = req.request_id
        live_info = {"kind": "stream", "epoch": epoch,
                     "request_id": request_id}
        last_ckpt = len(eng.slots[slot].generated)
        if key is not None:
            self._register_live(key, "stream", epoch, request_id)
            # admission checkpoint (synchronous): even a worker killed
            # before its first heartbeat leaves a resumable record (the
            # replacement regenerates from the prompt and splices)
            self._push_checkpoint(self._snapshot_live(key, live_info),
                                  sync=True)
        sp = _StreamSplicer(self.tokenizer, cfg, holdback,
                            resume_from, resume_text)
        try:
            while True:
                s = eng.slots[slot]
                gen = list(s.generated)
                finished = s.finish_reason is not None
                chunk, stop_cut = sp.advance(gen, finished)
                if chunk is not None:
                    yield stamp(chunk, sp.sent_tokens)
                if stop_cut:
                    s.finish_reason = "stop"
                    finished = True
                if finished:
                    break
                if cancel is not None and cancel.is_set():
                    s.finish_reason = s.finish_reason or "abort"
                    break
                if eng.cfg.speculative is not None:
                    # one draft→verify→accept round per flush: up to K+1
                    # tokens reach the stream per device round instead of 1
                    # (same emission contract incl. stop handling)
                    eng.spec_decode_step()
                else:
                    eng.decode_step()
                self._raise_if_pressured(eng, slot)
                if key is not None and self._ckpt_interval > 0:
                    s2 = eng.slots[slot]
                    n = len(s2.generated) if s2 is not None else last_ckpt
                    if n - last_ckpt >= self._ckpt_interval:
                        self._push_checkpoint(
                            self._snapshot_live(key, live_info)
                        )
                        last_ckpt = n
        finally:
            if key is not None:
                self._unregister_live(key)
            resp = self.engine.finish_slot(slot)
        finish = sp.finish_override or resp.finish_reason
        yield stamp({
            "done": True,
            "finish_reason": finish,
            "usage": {
                "prompt_tokens": resp.prompt_tokens,
                "completion_tokens": resp.completion_tokens,
                "total_tokens": resp.prompt_tokens + resp.completion_tokens,
                "cached_tokens": resp.cached_tokens,
            },
        }, sp.sent_tokens)
        # NOTE: the server-held checkpoint is deliberately NOT retired on
        # completion. The worker cannot know the final SSE bytes reached
        # the client (TCP buffers): a client that lost the tail must still
        # be able to resume, with the last checkpoint regenerating (stop)
        # or serving (length) the missing suffix. The control plane ages
        # stream checkpoints out instead (sweep_stale_stream_checkpoints).

    async def stream_inference(self, params: Dict[str, Any]):
        """Async wrapper: the sync per-step generator runs in a worker
        thread; chunks flow through a queue as they are produced. Closing
        this generator early (client disconnect) signals the pump thread to
        abort AND waits for it — the engine is guaranteed quiet when control
        returns to the caller."""
        import threading

        loop = asyncio.get_running_loop()
        q: "asyncio.Queue" = asyncio.Queue()
        _END = object()
        cancel = threading.Event()

        def pump():
            try:
                for chunk in self.stream(params, cancel=cancel):
                    loop.call_soon_threadsafe(q.put_nowait, chunk)
            except Exception as exc:  # noqa: BLE001 - surface to consumer
                chunk = {"error": str(exc)}
                code = getattr(exc, "error_code", None)
                if code:
                    # machine-readable class rides the SSE error event
                    # (request_timeout vs shed_overload — round 12)
                    chunk["error_code"] = code
                loop.call_soon_threadsafe(q.put_nowait, chunk)
            finally:
                loop.call_soon_threadsafe(q.put_nowait, _END)

        fut = loop.run_in_executor(None, pump)
        try:
            while True:
                chunk = await q.get()
                if chunk is _END:
                    break
                yield chunk
        finally:
            cancel.set()
            await fut  # engine quiet before the caller releases the claim

    # -- batch path straight through the engine (one compiled graph) ----------

    def batch_inference(self, batch: List[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
        if not self.loaded or self.engine is None:
            raise EngineLoadError("engine not loaded")
        reqs, cfgs = [], []
        for params in batch:
            cfg = GenerationConfig.from_params(params)
            cfgs.append(cfg)
            text = self._to_prompt(
                params.get("messages") or params.get("prompt") or ""
            )
            reqs.append(
                InferenceRequest(
                    prompt_token_ids=list(self.tokenizer.encode(text)),
                    sampling=self._sampling_from(cfg),
                )
            )
        resps = self.engine.generate(reqs, use_multi_step=True)
        out = []
        for resp, cfg in zip(resps, cfgs):
            text = self.tokenizer.decode(resp.token_ids)
            for s in cfg.stop:
                idx = text.find(s)
                if idx >= 0:
                    text = text[:idx]
                    break
            out.append(
                GenerationResult(
                    text=text,
                    prompt_tokens=resp.prompt_tokens,
                    completion_tokens=resp.completion_tokens,
                    cached_tokens=resp.cached_tokens,
                    finish_reason=resp.finish_reason or "stop",
                    ttft_ms=resp.ttft_ms,
                ).to_result_payload()
            )
        return out

    def health(self) -> Dict[str, Any]:
        h = super().health()
        if self.engine is not None:
            h["engine_stats"] = self.engine.get_stats()
        stats = self.serving_stats()
        if stats is not None:
            h["serving_stats"] = stats
        return h
