"""Text-to-image engine over the first-party DiT sampler.

Parity surface: reference ``worker/engines/image_gen.py`` (83 LoC,
diffusers pipeline) — seeded generator (:48-50), base64 PNG output
(:64-67), per-request steps/size params. TPU re-design: the whole DDIM
loop is one jitted device call (``models/diffusion.py``).
"""

from __future__ import annotations

import base64
import io
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .base import BaseEngine, EngineLoadError


def _png_b64(img_u8: np.ndarray) -> str:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(img_u8, mode="RGB").save(buf, format="PNG")
    return base64.b64encode(buf.getvalue()).decode("ascii")


class ImageGenEngine(BaseEngine):
    """config keys: model (diffusion registry name), default_steps,
    guidance_scale, checkpoint_path."""

    task_type = "image_gen"

    def __init__(self, config: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(config)
        self._cfg = None
        self._params = None
        self._tokenizer = None

    def load_model(self) -> None:
        import jax

        from ...models import diffusion

        model = self.config.get("model", "tiny-diffusion")
        try:
            self._cfg = diffusion.get_diffusion_config(model)
        except KeyError as exc:
            raise EngineLoadError(str(exc)) from exc
        self._params = diffusion.init_params(
            self._cfg, jax.random.PRNGKey(int(self.config.get("seed", 0)))
        )
        ckpt = self.config.get("checkpoint_path")
        if ckpt:
            from ...models.loader import load_checkpoint

            self._params = load_checkpoint(ckpt, template=self._params)
        from .llm import ByteTokenizer

        self._tokenizer = ByteTokenizer()
        self.model_name = model
        self.loaded = True

    def inference(self, params: Dict[str, Any]) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        from ...models import diffusion

        if self._params is None:
            raise RuntimeError("model not loaded")
        prompt = str(params.get("prompt", ""))
        # explicit 0 values are honored: check presence, not truthiness
        steps = int(
            params["num_inference_steps"]
            if params.get("num_inference_steps") is not None
            else self.config.get("default_steps", 20)
        )
        # clamp: num_steps is a static jit arg (each distinct value compiles
        # a sampler) and bounds per-request device work
        steps = max(1, min(steps, int(self.config.get("max_steps", 250))))
        n = max(1, min(int(params.get("num_images", 1)), 4))
        guidance = float(
            params["guidance_scale"]
            if params.get("guidance_scale") is not None
            else self.config.get("guidance_scale", 3.0)
        )
        seed = params.get("seed")
        key = jax.random.PRNGKey(
            int(seed) if seed is not None else int(time.time_ns() % (2**31))
        )

        toks = self._tokenizer.encode(prompt)[: self._cfg.max_text_len]
        tok_arr = np.zeros((n, self._cfg.max_text_len), np.int32)
        tok_arr[:, : len(toks)] = toks

        t0 = time.time()
        imgs = diffusion.sample_jit(
            self._cfg, self._params, jnp.asarray(tok_arr), key,
            num_steps=steps, guidance_scale=guidance,
        )
        imgs_u8 = np.asarray(
            np.clip(np.asarray(imgs, np.float32) * 255.0, 0, 255), np.uint8
        )
        images: List[str] = [_png_b64(imgs_u8[i]) for i in range(n)]
        return {
            "images": images,
            "format": "png_base64",
            "width": self._cfg.image_size,
            "height": self._cfg.image_size,
            "num_inference_steps": steps,
            "latency_ms": (time.time() - t0) * 1000.0,
            "usage": {"images": n, "pixels": n * self._cfg.image_size**2},
        }

    def unload(self) -> None:
        self._params = None
        self.loaded = False
