"""Vision-language engine: ViT soft-token prefix into the Llama decoder.

Parity surface: reference ``worker/engines/vision.py`` (GLM-4V wrapper;
tasks image_qa / caption / ocr :57-78, base64 image input). TPU re-design:
the VLM is composed first-party — ``models/vit.py`` encodes the image to a
fixed number of soft tokens which enter the decoder as a hidden-state
prefix via ``llama.forward_hidden_chunk``; the answer decodes greedily
against the same paged KV pools the text engine uses.
"""

from __future__ import annotations

import base64
import io
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .base import BaseEngine, EngineLoadError

_TASK_PROMPTS = {
    "image_qa": "Answer the question about the image: ",
    "caption": "Describe the image: ",
    "ocr": "Transcribe all text in the image: ",
}


def _decode_image(params: Dict[str, Any], size: int) -> np.ndarray:
    """base64 PNG/JPEG (``image``) or nested-list pixels (``pixels``) →
    [H, W, 3] float32 in [0, 1], resized to the model geometry."""
    if "pixels" in params:
        arr = np.asarray(params["pixels"], np.float32)
        if arr.ndim != 3 or arr.shape[-1] != 3:
            raise ValueError("pixels must be [H, W, 3]")
    elif "image" in params:
        from PIL import Image

        raw = base64.b64decode(params["image"])
        img = Image.open(io.BytesIO(raw)).convert("RGB")
        arr = np.asarray(img, np.float32) / 255.0
    else:
        raise ValueError("provide 'image' (base64) or 'pixels'")
    if arr.shape[0] != size or arr.shape[1] != size:
        from PIL import Image

        img = Image.fromarray(
            np.asarray(np.clip(arr * 255, 0, 255), np.uint8)
        ).resize((size, size))
        arr = np.asarray(img, np.float32) / 255.0
    return np.clip(arr, 0.0, 1.0)


class VisionEngine(BaseEngine):
    """config keys: model (llama registry), vit_model, max_new_tokens,
    tokenizer / tokenizer_id."""

    task_type = "vision"

    def __init__(self, config: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(config)
        self._llm_cfg = None
        self._llm_params = None
        self._vit_cfg = None
        self._vit_params = None
        self._tokenizer = None
        self._jit = {}

    def load_model(self) -> None:
        import jax

        from ...models import llama, vit
        from ...models.configs import get_model_config
        from ...models.loader import load_or_init_params

        llm_name = self.config.get("model", "llama3-tiny")
        vit_name = self.config.get("vit_model", "tiny-vit")
        try:
            self._llm_cfg = get_model_config(llm_name)
            self._vit_cfg = vit.get_vit_config(vit_name)
        except KeyError as exc:
            raise EngineLoadError(str(exc)) from exc
        if self._vit_cfg.out_dim != self._llm_cfg.hidden_size:
            raise EngineLoadError(
                f"vit out_dim {self._vit_cfg.out_dim} != decoder hidden "
                f"{self._llm_cfg.hidden_size}"
            )
        self._llm_params = load_or_init_params(
            self._llm_cfg, checkpoint_path=self.config.get("checkpoint_path"),
            dtype="float32",
        )
        vit_ckpt = self.config.get("vit_checkpoint_path")
        if vit_ckpt:
            # real pretrained ViT encoder (HF google/vit-* safetensors):
            # everything the architectures share imports exactly; the
            # perceiver resampler head stays fresh (models/loader.py
            # load_hf_vit docstring)
            from ...models.loader import load_hf_vit

            self._vit_params = load_hf_vit(vit_ckpt, self._vit_cfg)
        else:
            self._vit_params = vit.init_params(
                self._vit_cfg, jax.random.PRNGKey(7)
            )
        tok = self.config.get("tokenizer")
        if tok is None:
            tok_id = self.config.get("tokenizer_id")
            if tok_id:
                from .llm import _load_hf_tokenizer

                tok = _load_hf_tokenizer(tok_id)
            else:
                from .llm import ByteTokenizer

                tok = ByteTokenizer()
        self._tokenizer = tok
        self.model_name = f"{vit_name}+{llm_name}"

        # fixed-shape serving state: ONE prefill graph and ONE decode graph
        # serve every request (questions pad to max_text_len; KV pools are
        # allocated once at load and reused — donation keeps them in place)
        import jax.numpy as jnp

        from ...models import llama as llama_mod

        self._block = 16
        self._max_text = int(self.config.get("max_text_len", 64))
        self._max_new_cap = int(self.config.get("max_new_cap", 64))
        total = self._vit_cfg.num_prefix + self._max_text + self._max_new_cap
        self._max_blocks = -(-total // self._block) + 1
        self._kv = llama_mod.init_kv_pools(
            self._llm_cfg, self._max_blocks + 2, self._block, jnp.float32
        )
        self._table = np.arange(1, self._max_blocks + 1, dtype=np.int32)[None]
        self.loaded = True

    # -- decode helpers ------------------------------------------------------

    def _prefill_fn(self):
        import jax

        if "prefill" in self._jit:
            return self._jit["prefill"]
        from ...models import llama

        cfg = self._llm_cfg

        def run(lp, vp, kv, image, tokens, positions, last_idx, table, kv_len):
            from ...models import vit as vit_mod

            prefix = vit_mod.encode_image(self._vit_cfg, vp, image)
            text = llama.embed_tokens(lp, tokens, cfg)
            hidden = jax.numpy.concatenate(
                [prefix.astype(text.dtype), text], axis=1
            )
            hidden, kv = llama.forward_hidden_chunk(
                cfg, lp, hidden, positions, kv, table, kv_len,
                block_size=self._block,
            )
            last = jax.numpy.take_along_axis(
                hidden, last_idx[:, None, None], axis=1
            )
            logits = llama.project_logits(cfg, lp, last)
            return logits[:, 0], kv

        fn = jax.jit(run, donate_argnums=(2,))
        self._jit["prefill"] = fn
        return fn

    def _decode_fn(self):
        import jax

        if "decode" in self._jit:
            return self._jit["decode"]
        from ...models import llama

        cfg = self._llm_cfg

        def run(lp, kv, tok, position, table, kv_len):
            out = llama.forward_chunk(
                cfg, lp, tok, position, kv, table, kv_len,
                block_size=self._block, last_only=True,
            )
            return out.logits[:, 0, :], out.kv

        fn = jax.jit(run, donate_argnums=(1,))
        self._jit["decode"] = fn
        return fn

    def inference(self, params: Dict[str, Any]) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ...models import llama

        if self._llm_params is None:
            raise RuntimeError("model not loaded")
        t0 = time.time()
        task = params.get("task", "image_qa")
        if task not in _TASK_PROMPTS:
            raise ValueError(
                f"unknown vision task {task!r}; known: {sorted(_TASK_PROMPTS)}"
            )
        image = _decode_image(params, self._vit_cfg.image_size)
        question = str(params.get("question") or params.get("prompt") or "")
        text = _TASK_PROMPTS[task] + question
        toks = self._tokenizer.encode(text)[: self._max_text]
        max_new = int(
            params["max_new_tokens"]
            if params.get("max_new_tokens") is not None
            else self.config.get("max_new_tokens", 32)
        )
        max_new = max(1, min(max_new, self._max_new_cap))

        n_prefix = self._vit_cfg.num_prefix
        seq = n_prefix + len(toks)
        # pad text to the fixed bucket: positions -1 mark padding (their KV
        # writes are dropped), so one compiled graph serves every question
        tok_pad = np.zeros((1, self._max_text), np.int32)
        tok_pad[0, : len(toks)] = toks
        positions = np.full((1, n_prefix + self._max_text), -1, np.int32)
        positions[0, :seq] = np.arange(seq)
        fn = self._prefill_fn()
        logits, self._kv = fn(
            self._llm_params, self._vit_params, self._kv,
            jnp.asarray(image[None]), jnp.asarray(tok_pad),
            jnp.asarray(positions), jnp.asarray([seq - 1], jnp.int32),
            jnp.asarray(self._table), jnp.asarray([seq], jnp.int32),
        )
        decode = self._decode_fn()
        out_ids: List[int] = []
        tok = int(np.argmax(np.asarray(logits)[0]))
        eos = getattr(self._tokenizer, "eos_token_id", None)
        kv_len = seq
        while True:
            if tok == eos:
                break
            out_ids.append(tok)
            if len(out_ids) >= max_new:
                break  # budget reached: don't pay a forward we'd discard
            kv_len += 1
            logits, self._kv = decode(
                self._llm_params, self._kv,
                jnp.asarray([[tok]], jnp.int32),
                jnp.asarray([[kv_len - 1]], jnp.int32),
                jnp.asarray(self._table), jnp.asarray([kv_len], jnp.int32),
            )
            tok = int(np.argmax(np.asarray(logits)[0]))
        answer = self._tokenizer.decode(out_ids)
        return {
            "text": answer,
            "task": task,
            "usage": {
                "prompt_tokens": len(toks) + n_prefix,
                "completion_tokens": len(out_ids),
                "total_tokens": len(toks) + n_prefix + len(out_ids),
            },
            "latency_ms": (time.time() - t0) * 1000.0,
        }

    def unload(self) -> None:
        self._llm_params = None
        self._vit_params = None
        self._kv = None
        self._jit.clear()
        self.loaded = False
