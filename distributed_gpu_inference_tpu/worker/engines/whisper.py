"""ASR engine: log-mel → jitted CTC encoder → greedy collapse → text.

Parity surface: the reference's ``whisper`` task family (scheduled by job
type, audio arrives base64). Accepts base64 WAV (stdlib ``wave``), base64
raw float32 PCM (``pcm_f32``), or a plain list of samples; resamples
nothing — callers send 16 kHz mono like the reference's whisper jobs.
"""

from __future__ import annotations

import base64
import io
import time
import wave
from typing import Any, Dict, Optional

import numpy as np

from .base import BaseEngine, EngineLoadError


def _decode_audio(params: Dict[str, Any], sample_rate: int) -> np.ndarray:
    """→ float32 PCM in [-1, 1], mono."""
    if "samples" in params:
        return np.asarray(params["samples"], np.float32)
    fmt = params.get("audio_format", "wav")
    if "audio" not in params:
        raise ValueError("provide 'audio' (base64) or 'samples'")
    raw = base64.b64decode(params["audio"])
    if fmt == "pcm_f32":
        return np.frombuffer(raw, np.float32).copy()
    with wave.open(io.BytesIO(raw), "rb") as w:
        if w.getframerate() != sample_rate:
            raise ValueError(
                f"expected {sample_rate} Hz audio, got {w.getframerate()}"
            )
        data = w.readframes(w.getnframes())
        width = w.getsampwidth()
        if width == 2:
            pcm = np.frombuffer(data, np.int16).astype(np.float32) / 32768.0
        elif width == 4:
            pcm = np.frombuffer(data, np.int32).astype(np.float32) / 2**31
        else:
            raise ValueError(f"unsupported sample width {width}")
        if w.getnchannels() > 1:
            pcm = pcm.reshape(-1, w.getnchannels()).mean(axis=1)
        return pcm


class WhisperEngine(BaseEngine):
    """config keys: model (asr registry name), checkpoint_path."""

    task_type = "whisper"

    def __init__(self, config: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(config)
        self._cfg = None
        self._params = None
        self._encode_jit = None
        self._tokenizer = None

    def load_model(self) -> None:
        import jax

        from ...models import asr

        model = self.config.get("model", "tiny-whisper")
        try:
            self._cfg = asr.get_asr_config(model)
        except KeyError as exc:
            raise EngineLoadError(str(exc)) from exc
        self._params = asr.init_params(
            self._cfg, jax.random.PRNGKey(int(self.config.get("seed", 0)))
        )
        ckpt = self.config.get("checkpoint_path")
        if ckpt:
            from ...models.loader import load_checkpoint

            self._params = load_checkpoint(ckpt, template=self._params)

        cfg = self._cfg

        def run(p, mel):
            return asr.encode(cfg, p, mel)

        self._encode_jit = jax.jit(run)
        from .llm import ByteTokenizer

        self._tokenizer = ByteTokenizer()
        self.model_name = model
        self.loaded = True

    def inference(self, params: Dict[str, Any]) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ...models import asr

        if self._params is None:
            raise RuntimeError("model not loaded")
        t0 = time.time()
        pcm = _decode_audio(params, self._cfg.sample_rate)
        duration_s = len(pcm) / self._cfg.sample_rate
        # fixed-shape window: pad or truncate to the model's horizon; a
        # truncated clip is reported (and billed) as such, never silently
        n = self._cfg.max_samples
        truncated = len(pcm) > n
        transcribed_s = min(duration_s, self._cfg.max_seconds)
        if truncated:
            pcm = pcm[:n]
        else:
            pcm = np.pad(pcm, (0, n - len(pcm)))
        mel = asr.log_mel(self._cfg, pcm[None, :])
        logits = np.asarray(self._encode_jit(self._params, jnp.asarray(mel)))
        ids = asr.ctc_greedy_decode(logits)[0]
        text = self._tokenizer.decode(ids)
        return {
            "text": text,
            "language": params.get("language", "en"),
            "duration_seconds": duration_s,
            "transcribed_seconds": transcribed_s,
            "truncated": truncated,
            "usage": {"audio_seconds": transcribed_s},
            "latency_ms": (time.time() - t0) * 1000.0,
        }

    def unload(self) -> None:
        self._params = None
        self._encode_jit = None
        self.loaded = False
