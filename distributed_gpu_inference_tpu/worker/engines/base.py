"""Engine base classes.

Behavioral parity with the reference's ``worker/engines/base.py`` (BaseEngine
ABC: load/inference/unload, :10-57) and ``llm_base.py`` (LLMBaseEngine:
async/batch/stream variants plus a sync bridge that must not deadlock when
called inside a running event loop, :116-150 — regression-tested in the
reference by ``worker/tests/test_llm_base_inference_event_loop.py``).
"""

from __future__ import annotations

import abc
import asyncio
import concurrent.futures
import threading
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional


class EngineLoadError(RuntimeError):
    """Model/deps unavailable — worker should drop this task type."""


class ServingError(RuntimeError):
    """A serving-path request failed with a machine-readable class.

    ``error_code`` mirrors ``InferenceResponse.error_code``
    (``request_timeout`` / ``shed_overload`` / ``over_capacity`` / …) and
    survives to the job result (worker/main.py attaches it to the
    completion) and the SSE error event (the stream pump copies it onto
    the error chunk) — clients branch on the class instead of parsing
    the message text."""

    def __init__(self, message: str,
                 error_code: Optional[str] = None) -> None:
        super().__init__(message)
        self.error_code = error_code


class JobMigrated(Exception):
    """A generation was interrupted at a step boundary (graceful drain) and
    frozen into a portable checkpoint instead of finishing. The worker
    hands ``checkpoint`` to the control plane, which requeues the job so
    the next claimant resumes it — no tokens lost, no retry burned."""

    def __init__(self, checkpoint: Dict[str, Any], tokens: int = 0) -> None:
        super().__init__(f"job migrated with {tokens} generated tokens")
        self.checkpoint = checkpoint
        self.tokens = tokens


@dataclass
class GenerationConfig:
    """Per-request generation knobs (reference ``__init__.py:24``)."""

    max_new_tokens: int = 256
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop: List[str] = field(default_factory=list)
    # token-level stops (merged with the tokenizer's eos; vLLM-parity knob —
    # the reference forwards it to vLLM as stop_token_ids)
    stop_token_ids: List[int] = field(default_factory=list)
    seed: Optional[int] = None
    # run to the max_new_tokens budget, honoring no stops (benchmark
    # workloads where A/B legs must generate identical token counts)
    ignore_eos: bool = False
    # advisory completion deadline (seconds from admission): within a
    # priority band the batcher admits earlier deadlines first (EDF) and
    # prefers later-deadline slots as preemption victims. None = no
    # deadline — scheduling is byte-identical to the deadline-less path.
    deadline_s: Optional[float] = None

    @classmethod
    def from_params(cls, params: Dict[str, Any]) -> "GenerationConfig":
        dl = params.get("deadline_s")
        return cls(
            deadline_s=float(dl) if dl is not None else None,
            max_new_tokens=int(
                params.get("max_new_tokens") or params.get("max_tokens") or 256
            ),
            temperature=float(params.get("temperature") or 0.0),
            top_k=int(params.get("top_k") or 0),
            top_p=float(params.get("top_p") or 1.0),
            stop=list(params.get("stop") or []),
            stop_token_ids=[int(t) for t in
                            (params.get("stop_token_ids") or [])],
            seed=params.get("seed"),
            ignore_eos=bool(params.get("ignore_eos") or False),
        )


@dataclass
class GenerationResult:
    """Uniform result surface (reference ``__init__.py:35``)."""

    text: str
    prompt_tokens: int = 0
    completion_tokens: int = 0
    cached_tokens: int = 0
    finish_reason: str = "stop"
    ttft_ms: Optional[float] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_result_payload(self) -> Dict[str, Any]:
        """Shape of the job ``result`` JSON the control plane stores/bills."""
        return {
            "text": self.text,
            "finish_reason": self.finish_reason,
            "usage": {
                "prompt_tokens": self.prompt_tokens,
                "completion_tokens": self.completion_tokens,
                "total_tokens": self.prompt_tokens + self.completion_tokens,
                "cached_tokens": self.cached_tokens,
            },
            **({"ttft_ms": self.ttft_ms} if self.ttft_ms is not None else {}),
            **self.extra,
        }


class BaseEngine(abc.ABC):
    """load_model → inference(params) → unload lifecycle."""

    task_type: str = "llm"

    def __init__(self, config: Optional[Dict[str, Any]] = None) -> None:
        self.config = dict(config or {})
        self.loaded = False

    @abc.abstractmethod
    def load_model(self) -> None: ...

    @abc.abstractmethod
    def inference(self, params: Dict[str, Any]) -> Dict[str, Any]: ...

    def unload(self) -> None:
        self.loaded = False

    def health(self) -> Dict[str, Any]:
        return {"loaded": self.loaded, "task_type": self.task_type}


class LLMBaseEngine(BaseEngine):
    """Adds async/batch/stream on top of a sync ``_generate`` core.

    The sync bridge mirrors the reference's deadlock-avoidance contract
    (``llm_base.py:116-150``): calling :meth:`inference` from inside a running
    event loop must hop to a helper thread instead of ``run_until_complete``
    on the current loop.
    """

    def _generate(self, prompt_or_messages: Any,
                  cfg: GenerationConfig) -> GenerationResult:
        raise NotImplementedError

    # -- sync entry (thread-safe, loop-safe) ---------------------------------

    def inference(self, params: Dict[str, Any]) -> Dict[str, Any]:
        cfg = GenerationConfig.from_params(params)
        prompt = params.get("messages") or params.get("prompt") or ""
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            result = self._generate(prompt, cfg)
            return result.to_result_payload()
        # inside a loop: run in a fresh thread so we neither block the loop's
        # callbacks nor nest run_until_complete (reference llm_base.py:116-150)
        with concurrent.futures.ThreadPoolExecutor(max_workers=1) as pool:
            result = pool.submit(self._generate, prompt, cfg).result()
        return result.to_result_payload()

    # -- async + batch + stream ----------------------------------------------

    async def inference_async(self, params: Dict[str, Any]) -> Dict[str, Any]:
        loop = asyncio.get_running_loop()
        cfg = GenerationConfig.from_params(params)
        prompt = params.get("messages") or params.get("prompt") or ""
        result = await loop.run_in_executor(None, self._generate, prompt, cfg)
        return result.to_result_payload()

    def batch_inference(self, batch: List[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
        return [self.inference(p) for p in batch]

    async def batch_inference_async(self, batch: List[Dict[str, Any]]
                                    ) -> List[Dict[str, Any]]:
        return await asyncio.gather(
            *[self.inference_async(p) for p in batch]
        )

    async def stream_inference(self, params: Dict[str, Any]
                               ) -> AsyncIterator[Dict[str, Any]]:
        """Default streaming = one final chunk; token-level engines override."""
        yield await self.inference_async(params)
