"""Worker-hosted direct inference endpoint.

Behavioral parity with the reference's ``worker/direct_server.py`` (140 LoC,
FastAPI): ``/health``, ``/status``, and ``/inference`` which returns **503
while the worker is busy or draining** (:79-85) so clients fall back to the
control-plane queue. aiohttp here (the framework's one HTTP stack — same as
the control plane and the P2P data plane).

Discovery flow (reference SURVEY §3.2 direct-mode variant): clients find this
endpoint via the control plane's ``/api/v1/jobs/direct/nearest`` and POST
job params straight to ``/inference``, skipping the queue entirely.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Dict, Optional

from aiohttp import web


class DirectServer:
    """Serves a Worker's engines over local HTTP (reference DirectServer)."""

    def __init__(self, worker: Any, host: str = "0.0.0.0",
                 port: int = 8471) -> None:
        self.worker = worker
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self.stats: Dict[str, Any] = {"requests": 0, "rejected": 0}

    # -- handlers ------------------------------------------------------------

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "ts": time.time()})

    async def _status(self, request: web.Request) -> web.Response:
        return web.json_response(self.worker.get_status())

    async def _inference(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except ValueError:
            return web.json_response({"detail": "invalid JSON"}, status=400)
        if not isinstance(body, dict):
            return web.json_response(
                {"detail": "body must be a JSON object"}, status=400
            )
        task_type = body.get("type", "llm")
        engine = self.worker.engines.get(task_type)
        if engine is None:
            return web.json_response(
                {"detail": f"task type {task_type!r} not loaded"}, status=404
            )
        # load control applies to direct traffic too — the volunteer's caps
        # (working hours, cooldown, hourly budget) must hold no matter which
        # path the job takes
        accept = getattr(self.worker, "should_accept_job", None)
        if accept is not None and not accept({"type": task_type}):
            self.stats["rejected"] += 1
            return web.json_response(
                {"detail": "declined by load control"}, status=503
            )
        # atomically claim the worker (IDLE→BUSY): a second direct request,
        # or the queue poll loop, sees BUSY and backs off — engines are never
        # driven concurrently. 503 → client falls back to the control-plane
        # queue (reference direct_server.py:79-85).
        if not self.worker.try_begin_job():
            self.stats["rejected"] += 1
            return web.json_response(
                {"detail": f"worker {self.worker.state.value}"}, status=503
            )
        self.stats["requests"] += 1
        started = time.time()
        loop = asyncio.get_running_loop()
        try:
            result = await loop.run_in_executor(
                None, engine.inference, body.get("params") or {}
            )
        except Exception as exc:  # noqa: BLE001 - surface as a job error
            return web.json_response({"detail": str(exc)}, status=500)
        finally:
            note = getattr(self.worker, "note_job_done", None)
            if note is not None:
                note(started)
            self.worker.end_job()
        return web.json_response({"result": result})

    # -- lifecycle -----------------------------------------------------------

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/status", self._status)
        app.router.add_post("/inference", self._inference)
        return app

    def start(self) -> None:
        """Run in a background thread with a private event loop (the worker's
        main loop is a plain thread, reference main.py:386)."""

        def _run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            runner = web.AppRunner(self.make_app())
            loop.run_until_complete(runner.setup())
            self._runner = runner
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            self._started.set()
            loop.run_forever()
            loop.run_until_complete(runner.cleanup())
            loop.close()

        self._thread = threading.Thread(
            target=_run, name="direct-server", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10.0):
            raise RuntimeError("direct server failed to start")

    def stop(self) -> None:
        if self._loop is not None and not self._loop.is_closed():
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
