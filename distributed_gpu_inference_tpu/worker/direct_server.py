"""Worker-hosted direct inference endpoint.

Behavioral parity with the reference's ``worker/direct_server.py`` (140 LoC,
FastAPI): ``/health``, ``/status``, and ``/inference`` which returns **503
while the worker is busy or draining** (:79-85) so clients fall back to the
control-plane queue. aiohttp here (the framework's one HTTP stack — same as
the control plane and the P2P data plane).

Discovery flow (reference SURVEY §3.2 direct-mode variant): clients find this
endpoint via the control plane's ``/api/v1/jobs/direct/nearest`` and POST
job params straight to ``/inference``, skipping the queue entirely.
"""

from __future__ import annotations

import asyncio
import contextlib
import threading
import time
from typing import Any, Dict, Optional

from aiohttp import web


class DirectServer:
    """Serves a Worker's engines over local HTTP (reference DirectServer)."""

    def __init__(self, worker: Any, host: str = "0.0.0.0",
                 port: int = 8471) -> None:
        self.worker = worker
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self.stats: Dict[str, Any] = {"requests": 0, "rejected": 0}

    # -- handlers ------------------------------------------------------------

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "ts": time.time()})

    async def _status(self, request: web.Request) -> web.Response:
        return web.json_response(self.worker.get_status())

    async def _parse_and_admit(self, request: web.Request,
                               require_stream: bool = False):
        """ONE admission pipeline for both inference endpoints (load-control
        caps must hold no matter which path the job takes): returns
        ``(engine, body, None)`` with the worker CLAIMED, or
        ``(None, None, error_response)``. On success the caller owns the
        claim and must call ``_release(started)``."""
        try:
            body = await request.json()
        except ValueError:
            return None, None, web.json_response(
                {"detail": "invalid JSON"}, status=400
            )
        if not isinstance(body, dict):
            return None, None, web.json_response(
                {"detail": "body must be a JSON object"}, status=400
            )
        task_type = body.get("type", "llm")
        engine = self.worker.engines.get(task_type)
        if engine is None:
            return None, None, web.json_response(
                {"detail": f"task type {task_type!r} not loaded"}, status=404
            )
        if require_stream and \
                getattr(engine, "stream_inference", None) is None:
            return None, None, web.json_response(
                {"detail": f"engine for {task_type!r} does not stream"},
                status=501,
            )
        accept = getattr(self.worker, "should_accept_job", None)
        if accept is not None and not accept({"type": task_type}):
            self.stats["rejected"] += 1
            return None, None, web.json_response(
                {"detail": "declined by load control"}, status=503
            )
        # atomically claim the worker (IDLE→BUSY): a second direct request,
        # or the queue poll loop, sees BUSY and backs off — engines are never
        # driven concurrently. 503 → client falls back to the control-plane
        # queue (reference direct_server.py:79-85).
        if not self.worker.try_begin_job():
            self.stats["rejected"] += 1
            return None, None, web.json_response(
                {"detail": f"worker {self.worker.state.value}"}, status=503
            )
        self.stats["requests"] += 1
        return engine, body, None

    def _release(self, started: float) -> None:
        note = getattr(self.worker, "note_job_done", None)
        if note is not None:
            note(started)
        self.worker.end_job()

    async def _inference(self, request: web.Request) -> web.Response:
        engine, body, err = await self._parse_and_admit(request)
        if err is not None:
            return err
        started = time.time()
        loop = asyncio.get_running_loop()
        try:
            result = await loop.run_in_executor(
                None, engine.inference, body.get("params") or {}
            )
        except Exception as exc:  # noqa: BLE001 - surface as a job error
            return web.json_response({"detail": str(exc)}, status=500)
        finally:
            self._release(started)
        return web.json_response({"result": result})

    async def _inference_stream(self, request: web.Request
                                ) -> web.StreamResponse:
        """SSE token streaming (reference SGLang SSE path,
        llm_sglang.py:358-416): each chunk is one ``data:`` event; the final
        event carries done/finish_reason/usage."""
        import json

        engine, body, err = await self._parse_and_admit(
            request, require_stream=True
        )
        if err is not None:
            return err
        started = time.time()
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "X-Accel-Buffering": "no",
            }
        )
        await resp.prepare(request)
        agen = engine.stream_inference(body.get("params") or {})
        try:
            async for chunk in agen:
                await resp.write(
                    f"data: {json.dumps(chunk)}\n\n".encode()
                )
        except ConnectionResetError:
            pass  # client went away mid-stream; aclose() below aborts the run
        finally:
            # closing the generator signals the pump thread to abort and
            # WAITS for it — the engine is quiet before the claim releases,
            # so the next request can never drive the engine concurrently
            await agen.aclose()
            self._release(started)
        with contextlib.suppress(ConnectionResetError):
            await resp.write_eof()
        return resp

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/status", self._status)
        app.router.add_post("/inference", self._inference)
        app.router.add_post("/inference/stream", self._inference_stream)
        return app

    def start(self) -> None:
        """Run in a background thread with a private event loop (the worker's
        main loop is a plain thread, reference main.py:386)."""

        def _run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            runner = web.AppRunner(self.make_app())
            loop.run_until_complete(runner.setup())
            self._runner = runner
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            self._started.set()
            loop.run_forever()
            loop.run_until_complete(runner.cleanup())
            loop.close()

        self._thread = threading.Thread(
            target=_run, name="direct-server", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10.0):
            raise RuntimeError("direct server failed to start")

    def stop(self) -> None:
        if self._loop is not None and not self._loop.is_closed():
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
