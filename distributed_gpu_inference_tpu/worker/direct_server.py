"""Worker-hosted direct inference endpoint.

Behavioral parity with the reference's ``worker/direct_server.py`` (140 LoC,
FastAPI): ``/health``, ``/status``, and ``/inference`` which returns **503
while the worker is busy or draining** (:79-85) so clients fall back to the
control-plane queue. aiohttp here (the framework's one HTTP stack — same as
the control plane and the P2P data plane).

Discovery flow (reference SURVEY §3.2 direct-mode variant): clients find this
endpoint via the control plane's ``/api/v1/jobs/direct/nearest`` and POST
job params straight to ``/inference``, skipping the queue entirely.
"""

from __future__ import annotations

import asyncio
import contextlib
import threading
import time
import uuid
from typing import Any, Dict, Optional

from aiohttp import web

from ..testing import faults as _faults


class DirectServer:
    """Serves a Worker's engines over local HTTP (reference DirectServer)."""

    def __init__(self, worker: Any, host: str = "0.0.0.0",
                 port: int = 8471) -> None:
        self.worker = worker
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self.stats: Dict[str, Any] = {"requests": 0, "rejected": 0,
                                      "hedge_cancels": 0}
        # health-telemetry accumulators, drained into each heartbeat by
        # wire_stats(): per-request wall latencies (ms) and served-5xx
        # counts since the last beat. Handlers run on the direct-server
        # loop thread while the heartbeat drains from the worker thread,
        # so the buffers take a lock.
        self._stats_lock = threading.Lock()
        self._recent_ms: list = []
        self._new_errors = 0
        # hedged dispatch: in-flight requests that registered a client
        # hedge key, cancellable at the next step boundary via
        # POST /inference/cancel — the losing racer's abort path
        self._cancels: Dict[str, threading.Event] = {}

    # -- handlers ------------------------------------------------------------

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "ts": time.time()})

    async def _status(self, request: web.Request) -> web.Response:
        return web.json_response(self.worker.get_status())

    async def _parse_and_admit(self, request: web.Request,
                               require_stream: bool = False):
        """ONE admission pipeline for both inference endpoints (load-control
        caps must hold no matter which path the job takes): returns
        ``(engine, body, release, None)`` with the worker CLAIMED, or
        ``(None, None, None, error_response)``. On success the caller owns
        the claim and must call ``release(started)``.

        Claim kinds: an engine serving through a batcher front-end takes a
        SHARED serving claim (concurrent requests join the batch, capped by
        ``load_control.max_concurrent_jobs``); everything else keeps the
        exclusive IDLE→BUSY claim (engines without a batcher are never
        driven concurrently). Workers without the shared-claim surface
        (older shims, tests) always get the exclusive claim."""
        try:
            body = await request.json()
        except ValueError:
            return None, None, None, web.json_response(
                {"detail": "invalid JSON"}, status=400
            )
        if not isinstance(body, dict):
            return None, None, None, web.json_response(
                {"detail": "body must be a JSON object"}, status=400
            )
        task_type = body.get("type", "llm")
        engine = self.worker.engines.get(task_type)
        if engine is None:
            return None, None, None, web.json_response(
                {"detail": f"task type {task_type!r} not loaded"}, status=404
            )
        if require_stream and \
                getattr(engine, "stream_inference", None) is None:
            return None, None, None, web.json_response(
                {"detail": f"engine for {task_type!r} does not stream"},
                status=501,
            )
        # reserved internal key: the failover context is MINTED by this
        # server / the worker claim path, never accepted from a client —
        # a forged checkpoint would otherwise drive the resume path with
        # arbitrary state (bypassing request validation) and poison the
        # stream's control-plane checkpoints
        params = body.get("params")
        if isinstance(params, dict):
            params.pop("_failover_ctx", None)
            # flight recorder: the arrival stamps are worker-minted too —
            # a client-forged pickup time would skew phase attribution
            params.pop("_flight_picked_up_ts", None)
            params.pop("_flight_tl", None)
            if params.get("trace_id"):
                # direct requests skip the queue: the "pickup" is the
                # moment this server admitted the request
                params["_flight_picked_up_ts"] = time.time()
        accept = getattr(self.worker, "should_accept_job", None)
        if accept is not None and not accept({"type": task_type}):
            self.stats["rejected"] += 1
            return None, None, None, web.json_response(
                {"detail": "declined by load control"}, status=503
            )
        serving = getattr(engine, "serving", None)
        begin_shared = getattr(self.worker, "try_begin_serving", None)
        is_pd = isinstance(params, dict) and params.get("pd_stage")
        if serving is not None and getattr(serving, "active", False) \
                and begin_shared is not None and not is_pd:
            # batcher-backed engine: shared claim — concurrent direct
            # requests land in the SAME continuous batch and share decode
            # rounds (PD stages keep the exclusive claim: they manage
            # engine slots out-of-band)
            if not begin_shared():
                self.stats["rejected"] += 1
                return None, None, None, web.json_response(
                    {"detail": f"worker {self.worker.state.value}"},
                    status=503,
                )
            end = self.worker.end_serving
        else:
            # atomically claim the worker (IDLE→BUSY): a second direct
            # request, or the queue poll loop, sees BUSY and backs off.
            # 503 → client falls back to the control-plane queue
            # (reference direct_server.py:79-85).
            if not self.worker.try_begin_job():
                self.stats["rejected"] += 1
                return None, None, None, web.json_response(
                    {"detail": f"worker {self.worker.state.value}"},
                    status=503,
                )
            end = self.worker.end_job
        self.stats["requests"] += 1

        def release(started: float) -> None:
            note = getattr(self.worker, "note_job_done", None)
            if note is not None:
                note(started)
            end()

        return engine, body, release, None

    def _fault_tag(self) -> str:
        """Per-worker context for the chaos seams: rules can target ONE
        replica of a fleet (``match={"worker": "w1"}``) instead of every
        engine in the process. Workers/shims opt in by setting
        ``fault_tag``; untagged workers match the empty string."""
        return str(getattr(self.worker, "fault_tag", "") or "")

    def _record_sample(self, latency_ms: Optional[float] = None,
                       error: bool = False) -> None:
        """Accumulate a health-telemetry observation for the next
        heartbeat. The sample buffer is bounded: if the heartbeat loop
        stalls, old samples drop rather than the buffer growing forever
        (the freshest window is what health scoring wants anyway)."""
        with self._stats_lock:
            if latency_ms is not None:
                self._recent_ms.append(float(latency_ms))
                if len(self._recent_ms) > 512:
                    del self._recent_ms[:-256]
            if error:
                self._new_errors += 1

    def wire_stats(self) -> Dict[str, Any]:
        """Heartbeat ``engine_stats["direct"]`` channel: drains the
        since-last-beat latency samples / served-5xx count (deltas), plus
        the CUMULATIVE hedge-cancel counter the plane delta-anchors into
        ``hedges_total{outcome="cancelled"}``."""
        with self._stats_lock:
            recent = self._recent_ms
            self._recent_ms = []
            errors = self._new_errors
            self._new_errors = 0
        return {"recent_ms": recent, "new_errors": errors,
                "hedge_cancels": int(self.stats["hedge_cancels"])}

    async def _inference(self, request: web.Request) -> web.Response:
        t0 = time.time()   # BEFORE the fault seam: injected gray delay is
        # real service time and must land in the health latency samples
        reject = _faults.http_reject("worker.direct.request",
                                     worker=self._fault_tag())
        if reject == 0:
            # chaos seam: the worker "dies" on this request — hard-close
            # so the client sees a crashed process, not a clean error
            with contextlib.suppress(Exception):
                request.transport.close()
            raise ConnectionResetError("fault injected: request cut")
        if reject is not None:
            # gray flaky seam: the process is healthy, the answer is a 5xx
            self.stats["rejected"] += 1
            self._record_sample(error=True)
            return web.json_response(
                {"detail": "fault injected: flaky reply"}, status=reject
            )
        engine, body, release, err = await self._parse_and_admit(request)
        if err is not None:
            return err
        # hedged dispatch: a client that raced this request against another
        # replica registers a cancel key — the losing leg is aborted at the
        # next step boundary via POST /inference/cancel instead of burning
        # decode rounds to the end. The key is client-supplied but the
        # EVENT is server-minted (``_cancel_evt`` rides the reserved
        # underscore namespace _parse_and_admit strips from clients).
        params = body.get("params") or {}
        hedge_key = None
        if isinstance(params, dict):
            # the event slot is server-owned: a wire-supplied value would
            # reach the batcher's cancel hook as a non-Event and crash it
            params.pop("_cancel_evt", None)
            if params.get("hedge_key"):
                hedge_key = str(params.pop("hedge_key"))
                evt = threading.Event()
                params["_cancel_evt"] = evt
                self._cancels[hedge_key] = evt
        started = time.time()
        loop = asyncio.get_running_loop()
        try:
            result = await loop.run_in_executor(
                None, engine.inference, params
            )
        except Exception as exc:  # noqa: BLE001 - surface as a job error
            self._record_sample(error=True)
            return web.json_response({"detail": str(exc)}, status=500)
        finally:
            release(started)
            if hedge_key is not None:
                self._cancels.pop(hedge_key, None)
        self._record_sample(latency_ms=(time.time() - t0) * 1000.0)
        return web.json_response({"result": result})

    async def _inference_cancel(self, request: web.Request) -> web.Response:
        """Hedge-loser abort: flips the cancel event registered under the
        caller's ``hedge_key``, so the batcher releases the slot at the
        next step boundary. Idempotent; an unknown key (request already
        finished, or never started here) is a no-op 200 so racers never
        error out while tidying up."""
        try:
            body = await request.json()
        except ValueError:
            return web.json_response({"detail": "invalid JSON"}, status=400)
        key = str((body or {}).get("hedge_key") or "")
        evt = self._cancels.get(key) if key else None
        if evt is not None and not evt.is_set():
            evt.set()
            self.stats["hedge_cancels"] += 1
            return web.json_response({"cancelled": True})
        return web.json_response({"cancelled": False})

    async def _inference_stream(self, request: web.Request
                                ) -> web.StreamResponse:
        """SSE token streaming (reference SGLang SSE path,
        llm_sglang.py:358-416): each chunk is one ``data:`` event; the final
        event carries done/finish_reason/usage.

        Crash-safe streams: every event is stamped with the engine's
        monotonic token ``offset`` (mirrored into the SSE ``id:`` field —
        the Last-Event-ID idiom), and a ``resume`` body
        (``{"stream_id", "offset"}``) adopts the stream's control-plane
        checkpoint — possibly left by a DIFFERENT, now-dead worker — and
        splices the continuation at the client's offset: no token re-sent,
        none skipped."""
        import json

        engine, body, release, err = await self._parse_and_admit(
            request, require_stream=True
        )
        if err is not None:
            return err
        started = time.time()
        params = dict(body.get("params") or {})
        resume = body.get("resume") if isinstance(body.get("resume"),
                                                  dict) else None
        stream_id = str(
            (resume or {}).get("stream_id") or body.get("stream_id")
            or uuid.uuid4().hex
        )
        if getattr(engine, "supports_failover", False):
            ctx: Dict[str, Any] = {"key": stream_id, "kind": "stream",
                                   "epoch": 0}
            if resume is not None:
                adopt = getattr(self.worker, "adopt_stream_checkpoint", None)
                adoption = None
                adopt_failed = adopt is None
                if adopt is not None:
                    loop = asyncio.get_running_loop()
                    try:
                        adoption = await loop.run_in_executor(
                            None, adopt, stream_id
                        )
                    except Exception:  # noqa: BLE001 — plane unreachable
                        adopt_failed = True
                if adoption is None:
                    release(started)
                    if adopt_failed:
                        # transient: the control plane was unreachable,
                        # NOT proof that no checkpoint exists — a 503
                        # keeps the client's resume budget alive (409
                        # would terminally fail a resumable stream)
                        return web.json_response(
                            {"detail": "checkpoint adoption failed "
                                       "(control plane unreachable)"},
                            status=503,
                        )
                    # no checkpoint to resume from: the client decides
                    # (fresh queued run only if it consumed nothing yet)
                    return web.json_response(
                        {"detail": f"no checkpoint for stream {stream_id}"},
                        status=409,
                    )
                ctx["checkpoint"] = adoption.get("checkpoint")
                ctx["epoch"] = int(adoption.get("epoch") or 0)
                ctx["offset"] = int(resume.get("offset") or 0)
                ctx["text_offset"] = int(resume.get("text_offset") or 0)
            params["_failover_ctx"] = ctx
        elif resume is not None:
            release(started)
            return web.json_response(
                {"detail": "engine does not support stream resume"},
                status=409,
            )
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "X-Accel-Buffering": "no",
            }
        )
        await resp.prepare(request)
        agen = engine.stream_inference(params)
        try:
            async for chunk in agen:
                if _faults.stream_cut("worker.direct.stream",
                                      stream_id=stream_id,
                                      worker=self._fault_tag()):
                    # chaos seam: the worker "dies" mid-stream — hard-close
                    # the socket so the client sees an abrupt drop, exactly
                    # like a crashed process
                    with contextlib.suppress(Exception):
                        request.transport.close()
                    raise ConnectionResetError("fault injected: stream cut")
                evt = b""
                if chunk.get("offset") is not None:
                    evt += f"id: {chunk['offset']}\n".encode()
                evt += f"data: {json.dumps(chunk)}\n\n".encode()
                await resp.write(evt)
        except ConnectionResetError:
            pass  # client went away mid-stream; aclose() below aborts the run
        finally:
            # closing the generator signals the pump thread to abort and
            # WAITS for it — the engine is quiet before the claim releases,
            # so the next request can never drive the engine concurrently
            await agen.aclose()
            release(started)
        with contextlib.suppress(ConnectionResetError):
            await resp.write_eof()
        return resp

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/status", self._status)
        app.router.add_post("/inference", self._inference)
        app.router.add_post("/inference/cancel", self._inference_cancel)
        app.router.add_post("/inference/stream", self._inference_stream)
        return app

    def start(self) -> None:
        """Run in a background thread with a private event loop (the worker's
        main loop is a plain thread, reference main.py:386)."""

        def _run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            runner = web.AppRunner(self.make_app())
            loop.run_until_complete(runner.setup())
            self._runner = runner
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            self._started.set()
            loop.run_forever()
            loop.run_until_complete(runner.cleanup())
            loop.close()

        self._thread = threading.Thread(
            target=_run, name="direct-server", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10.0):
            raise RuntimeError("direct server failed to start")

    def stop(self) -> None:
        if self._loop is not None and not self._loop.is_closed():
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
