"""Stable machine fingerprint for worker identity.

Behavioral parity with the reference's ``worker/machine_id.py``: combine
hardware identifiers (MAC :56, /etc/machine-id :65, accelerator identity
:119) into a stable worker id, persisted so re-registrations keep the same
identity (:140-178). TPU delta: the accelerator component is the TPU chip
topology (kind + chip count) from jax instead of nvidia-smi GPU UUIDs.
"""

from __future__ import annotations

import hashlib
import json
import os
import uuid
from pathlib import Path
from typing import Dict, Optional

from distributed_gpu_inference_tpu.runtime.io_guard import atomic_write_text

DEFAULT_STATE_DIR = "~/.dgi_tpu"


def _mac_address() -> str:
    return f"{uuid.getnode():012x}"


def _machine_id() -> str:
    for path in ("/etc/machine-id", "/var/lib/dbus/machine-id"):
        try:
            text = Path(path).read_text().strip()
            if text:
                return text
        except OSError:
            continue
    return ""


def _tpu_identity() -> str:
    """Accelerator component: TPU platform + device kinds (no nvidia-smi)."""
    try:
        import jax

        devs = jax.devices()
        kinds = sorted({d.device_kind for d in devs})
        return f"{jax.default_backend()}:{','.join(kinds)}:{len(devs)}"
    except Exception:  # noqa: BLE001 — fingerprint must work without jax/TPU
        return "cpu-only"


class MachineFingerprint:
    """Computes and persists a stable fingerprint."""

    def __init__(self, state_dir: str = DEFAULT_STATE_DIR) -> None:
        self._dir = Path(os.path.expanduser(state_dir))
        self._file = self._dir / "machine_fingerprint.json"

    def components(self) -> Dict[str, str]:
        return {
            "mac": _mac_address(),
            "machine_id": _machine_id(),
            "accelerator": _tpu_identity(),
        }

    def compute(self) -> str:
        blob = json.dumps(self.components(), sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:32]

    def load(self) -> Optional[str]:
        try:
            data = json.loads(self._file.read_text())
            return data.get("fingerprint") or None
        except (OSError, ValueError):
            return None

    def save(self, fingerprint: str) -> None:
        self._dir.mkdir(parents=True, exist_ok=True)
        payload = {"fingerprint": fingerprint, "components": self.components()}
        # atomic temp+fsync+rename: a crash mid-save must leave the OLD
        # fingerprint readable — a torn file would mint a new identity and
        # orphan this worker's server-side state (round 19)
        atomic_write_text(self._file, json.dumps(payload, indent=2))

    def get_or_create(self) -> str:
        """Persisted fingerprint wins (stable across hardware tweaks)."""
        existing = self.load()
        if existing:
            return existing
        fp = self.compute()
        try:
            self.save(fp)
        except OSError:  # read-only fs: still return a usable id
            pass
        return fp
