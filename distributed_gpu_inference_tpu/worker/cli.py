"""Worker CLI: setup wizard + start/status/set/show commands.

Behavioral parity with the reference's ``worker/cli.py`` (877 LoC):
- Interactive setup wizard — server/region/accelerator probe/task types/
  load control/direct endpoint (:298-651) — writing ``config.yaml``.
- ``start`` boots the worker (:706), ``status`` shows local + server state
  (:736), ``set k.v value`` does dotted config updates (:790).

TPU re-design: the accelerator probe reads ``jax.devices()``
(:class:`worker.main.probe_topology`) instead of nvidia-smi (:77), and
there is no CUDA-version → torch-index-url dance (:110-133) — jax is baked
into the image/venv by the launcher.

Every prompt has a default so the wizard is scriptable:
``yes "" | tpu-worker setup`` produces a valid config (hermetic tests drive
it with a ``input_fn``).
"""

from __future__ import annotations

import argparse
import builtins
import json
import sys
from pathlib import Path
from typing import Any, Callable, List, Optional

from ..utils.config import (
    WorkerConfig,
    load_worker_config,
    save_worker_config,
    set_dotted,
)

DEFAULT_CONFIG_PATH = "config.yaml"
REGIONS = ("us-west", "us-east", "eu-west", "eu-central", "asia-east",
           "asia-southeast")
TASK_TYPES = ("llm", "embedding", "image_gen", "vision", "whisper")


class ConfigWizard:
    """Interactive setup (reference ConfigWizard:298). ``input_fn``/``print_fn``
    are injectable for tests."""

    def __init__(self, input_fn: Optional[Callable[[str], str]] = None,
                 print_fn: Callable[[str], None] = print) -> None:
        # resolve builtins.input lazily so monkeypatched/test inputs work
        self._input = input_fn or (lambda prompt: builtins.input(prompt))
        self._print = print_fn

    def _ask(self, prompt: str, default: str) -> str:
        try:
            raw = self._input(f"{prompt} [{default}]: ").strip()
        except (EOFError, StopIteration):
            raw = ""
        return raw or default

    def _ask_bool(self, prompt: str, default: bool) -> bool:
        raw = self._ask(prompt + " (y/n)", "y" if default else "n").lower()
        return raw in ("y", "yes", "true", "1")

    def _ask_number(self, prompt: str, default, cast):
        """Re-prompt on a bad numeric answer instead of crashing the whole
        wizard (a typo must never discard every prior answer)."""
        for _ in range(3):
            raw = self._ask(prompt, str(default))
            try:
                return cast(raw)
            except ValueError:
                self._print(f"  not a valid number: {raw!r}")
        self._print(f"  using default {default}")
        return cast(str(default))

    def run(self, base: Optional[WorkerConfig] = None) -> WorkerConfig:
        from .main import probe_topology, probe_tpu_runtime

        cfg = base or WorkerConfig()
        self._print("== TPU worker setup ==")

        runtime = probe_tpu_runtime()
        if runtime["libtpu"] or runtime["accel_devices"]:
            self._print(
                "tpu runtime: libtpu="
                + ("found" if runtime["libtpu"] else "MISSING")
                + (f", devices={len(runtime['accel_devices'])}"
                   if runtime["accel_devices"] else "")
                + (f", type={runtime['accelerator_type']}"
                   if runtime["accelerator_type"] else "")
            )
        topo = probe_topology()
        self._print(
            f"detected accelerator: {topo.chip_type} x{topo.num_chips} "
            f"({topo.hbm_gb_per_chip:.0f} GB HBM/chip, mesh "
            f"{'x'.join(map(str, topo.mesh_shape))}, "
            f"{topo.peak_bf16_tflops:.0f} bf16 TFLOP/s/chip)"
        )

        cfg.name = self._ask("worker name", cfg.name)
        cfg.server.url = self._ask("control-plane URL", cfg.server.url)
        region = self._ask(
            f"region {list(REGIONS)}", cfg.region
        )
        cfg.region = region

        types = self._ask(
            f"task types (comma-sep of {list(TASK_TYPES)})",
            ",".join(cfg.task_types),
        )
        cfg.task_types = [t.strip() for t in types.split(",") if t.strip()]

        # load control (reference wizard load-control section)
        if self._ask_bool("configure load control", False):
            lc = cfg.load_control
            lc.acceptance_rate = self._ask_number(
                "acceptance rate 0..1", lc.acceptance_rate, float
            )
            lc.max_jobs_per_hour = self._ask_number(
                "max jobs/hour (0 = unlimited)", lc.max_jobs_per_hour, int
            )
            lc.cooldown_seconds = self._ask_number(
                "cooldown seconds between jobs", lc.cooldown_seconds, float
            )
            hours = self._ask("working hours start-end (e.g. 9-17, empty=all)",
                              "")
            if hours and "-" in hours:
                a, _, b = hours.partition("-")
                try:
                    lc.working_hours = (int(a), int(b))
                except ValueError:
                    self._print(f"  ignoring invalid hours: {hours!r}")

        # direct endpoint (reference wizard direct section)
        if self._ask_bool("enable direct inference endpoint", False):
            cfg.direct.enabled = True
            cfg.direct.port = self._ask_number(
                "direct port", cfg.direct.port, int
            )
            cfg.direct.public_url = self._ask(
                "public URL clients reach this worker at",
                cfg.direct.public_url or f"http://localhost:{cfg.direct.port}",
            ) or None
        return cfg


# ---------------------------------------------------------------------------
# commands
# ---------------------------------------------------------------------------


def cmd_setup(args: argparse.Namespace) -> int:
    base = None
    path = Path(args.config)
    if path.exists():
        base = load_worker_config(path)
    cfg = ConfigWizard().run(base)
    save_worker_config(cfg, path)
    print(f"wrote {path}")
    return 0


def cmd_start(args: argparse.Namespace) -> int:
    import logging

    from .main import Worker

    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    cfg = load_worker_config(args.config, missing_ok=True)
    path = Path(args.config)

    def persist(creds):
        cfg.server.worker_id = creds["worker_id"]
        cfg.server.auth_token = creds["auth_token"]
        cfg.server.refresh_token = creds["refresh_token"]
        cfg.server.signing_secret = creds["signing_secret"]
        save_worker_config(cfg, path)

    Worker(cfg, on_credentials=persist).start()
    return 0


def cmd_status(args: argparse.Namespace) -> int:
    cfg = load_worker_config(args.config, missing_ok=True)
    out = {
        "config": str(Path(args.config).resolve()),
        "name": cfg.name,
        "region": cfg.region,
        "task_types": cfg.task_types,
        "server_url": cfg.server.url,
        "registered": bool(cfg.server.worker_id),
        "worker_id": cfg.server.worker_id,
        "direct_enabled": cfg.direct.enabled,
    }
    if cfg.server.worker_id and not args.local:
        try:
            import httpx

            headers = {}
            if cfg.server.api_key:
                headers["X-API-Key"] = cfg.server.api_key
            if cfg.server.auth_token:
                headers["Authorization"] = f"Bearer {cfg.server.auth_token}"
            resp = httpx.get(
                f"{cfg.server.url.rstrip('/')}/api/v1/workers/"
                f"{cfg.server.worker_id}",
                headers=headers,
                timeout=5.0,
            )
            if resp.status_code == 200:
                remote = resp.json()
                out["server_status"] = remote.get("status")
                out["reliability_score"] = remote.get("reliability_score")
                out["last_heartbeat"] = remote.get("last_heartbeat")
            else:
                out["server_status"] = f"HTTP {resp.status_code}"
        except Exception as exc:  # noqa: BLE001 - status must never crash
            out["server_status"] = f"unreachable: {exc}"
    print(json.dumps(out, indent=2))
    return 0


def cmd_set(args: argparse.Namespace) -> int:
    cfg = load_worker_config(args.config, missing_ok=True)
    value: Any = args.value
    # parse JSON-ish scalars so `set load_control.acceptance_rate 0.5` works
    try:
        value = json.loads(args.value)
    except ValueError:
        pass
    try:
        cfg = set_dotted(cfg, args.key, value)
    except KeyError:
        print(f"error: unknown config key {args.key!r}", file=sys.stderr)
        return 1
    except Exception as exc:  # pydantic ValidationError etc.
        print(f"error: invalid value for {args.key!r}: {exc}",
              file=sys.stderr)
        return 1
    save_worker_config(cfg, args.config)
    print(f"{args.key} = {value!r}")
    return 0


def cmd_show(args: argparse.Namespace) -> int:
    cfg = load_worker_config(args.config, missing_ok=True)
    data = cfg.model_dump(mode="json")
    # never print secrets
    for k in ("auth_token", "refresh_token", "signing_secret", "api_key"):
        if data.get("server", {}).get(k):
            data["server"][k] = "***"
    print(json.dumps(data, indent=2))
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="tpu-worker",
        description="TPU inference worker (reference: gpu-worker CLI)",
    )
    ap.add_argument("--config", default=DEFAULT_CONFIG_PATH)
    sub = ap.add_subparsers(dest="command", required=True)

    sub.add_parser("setup", help="interactive configuration wizard")
    p_start = sub.add_parser("start", help="run the worker")
    p_start.add_argument("--log-level", default="INFO")
    p_status = sub.add_parser("status", help="local + server-side status")
    p_status.add_argument("--local", action="store_true",
                          help="skip the server round trip")
    p_set = sub.add_parser("set", help="dotted config update, e.g. "
                           "load_control.acceptance_rate 0.5")
    p_set.add_argument("key")
    p_set.add_argument("value")
    sub.add_parser("show", help="print config (secrets masked)")
    return ap


_COMMANDS = {
    "setup": cmd_setup,
    "start": cmd_start,
    "status": cmd_status,
    "set": cmd_set,
    "show": cmd_show,
}


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return _COMMANDS[args.command](args)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
