"""HTTP client from worker to control plane.

Behavioral parity with the reference's ``worker/api_client.py``:
- Retry with exponential backoff, but never on 4xx (:71-99, :87).
- HMAC-SHA256 request signing over METHOD:PATH:BODY_HASH:TS (:52-69) using
  the signing secret issued at registration.
- 204 from next-job means "no job" (:161); token refresh flow (:263).

Transport is httpx (sync — the worker's poll loop is a plain thread like the
reference's). The signing canonicalization matches
``server.security.RequestSigner`` so the server can verify.
"""

from __future__ import annotations

import json
import random
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import httpx

from ..server.security import RequestSigner
from ..testing import faults as _faults
from ..utils.backoff import full_jitter_delay


class APIError(Exception):
    def __init__(self, status: int, detail: str = "") -> None:
        super().__init__(f"HTTP {status}: {detail}")
        self.status = status
        self.detail = detail


class APIClient:
    """Control-plane HTTP client with plane-failover.

    ``base_url`` accepts a single URL (the historical single-plane
    contract, unchanged) or a LIST of plane endpoints. With a list, a
    transport failure or 5xx on the active plane rotates to the next
    health-probed peer and the request is retried there WITHOUT burning a
    backoff attempt — the rotation sticks, so every later heartbeat /
    poll / completion / checkpoint / adoption targets the surviving plane.
    Duplicate-delivery idempotency on the server (terminal completes
    answer ``{"ok": true, "duplicate": true}``; checkpoint upserts are
    epoch-fenced) is what makes the cross-plane retry safe.
    """

    def __init__(
        self,
        base_url: Union[str, Sequence[str]],
        worker_id: Optional[str] = None,
        auth_token: Optional[str] = None,
        refresh_token: Optional[str] = None,
        signing_secret: Optional[str] = None,
        max_retries: int = 3,
        backoff_s: float = 0.5,
        retry_budget_s: float = 15.0,
        timeout_s: float = 30.0,
        transport: Optional[httpx.BaseTransport] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        urls = [base_url] if isinstance(base_url, str) else list(base_url)
        if not urls:
            raise ValueError("APIClient needs at least one plane endpoint")
        self.endpoints: List[str] = [u.rstrip("/") for u in urls]
        self._active = 0
        self.worker_id = worker_id
        self.auth_token = auth_token
        self.refresh_token = refresh_token
        self.signing_secret = signing_secret
        self._max_retries = max_retries
        self._backoff_s = backoff_s
        self._retry_budget_s = retry_budget_s
        # full-jitter source; injectable so tests can pin the schedule
        self._rng = rng if rng is not None else random.Random()
        self._signer = RequestSigner()
        self._timeout_s = timeout_s
        self._clients = [
            httpx.Client(base_url=u, timeout=timeout_s, transport=transport)
            for u in self.endpoints
        ]
        # observability: how often this worker changed planes (the chaos
        # suite asserts failovers actually happened under plane kills)
        self.plane_failovers = 0

    @property
    def base_url(self) -> str:
        """The ACTIVE plane endpoint (single-plane: the only one)."""
        return self.endpoints[self._active]

    @property
    def _client(self) -> httpx.Client:
        return self._clients[self._active]

    def close(self) -> None:
        for c in self._clients:
            c.close()

    # -- plane failover ------------------------------------------------------

    def _probe_plane(self, index: int) -> bool:
        """GET /health on a candidate plane, through the same chaos seam as
        real requests: a partitioned plane is alive but unreachable FROM
        THIS WORKER, and the probe must see what the worker sees."""
        try:
            resp = _faults.wrap_http(
                "worker.api.request",
                lambda: self._clients[index].get("/health", timeout=2.0),
                method="GET", path="/health",
                worker=str(getattr(self, "fault_tag", "") or ""),
                # destination endpoint: plane-targeted chaos rules
                # (plane_partition / plane_slow) match on it
                server=self.endpoints[index],
            )
            return resp.status_code == 200
        except Exception:  # noqa: BLE001 — any failure means unhealthy
            return False

    def _failover_plane(self) -> bool:
        """Rotate to the next healthy plane endpoint (sticky — later
        requests start there). Prefers a probe-healthy peer; falls back to
        plain round-robin when nothing probes healthy right now (the
        request-level retry ladder keeps rotating). Returns False on a
        single-endpoint client."""
        if len(self.endpoints) <= 1:
            return False
        for step in range(1, len(self.endpoints)):
            cand = (self._active + step) % len(self.endpoints)
            if self._probe_plane(cand):
                self._active = cand
                self.plane_failovers += 1
                return True
        self._active = (self._active + 1) % len(self.endpoints)
        self.plane_failovers += 1
        return True

    # -- low-level ----------------------------------------------------------

    def _headers(self, method: str, path: str, body: bytes) -> Dict[str, str]:
        headers = {"Content-Type": "application/json"}
        if self.auth_token:
            headers["Authorization"] = f"Bearer {self.auth_token}"
        if self.signing_secret:
            headers.update(
                self._signer.sign(self.signing_secret, method, path, body)
            )
        return headers

    def _backoff(self, attempt: int, remaining_s: float) -> Optional[float]:
        """Sleep one full-jitter backoff step (``utils.backoff``); returns
        the slept seconds, or None when the retry budget is exhausted
        (caller stops retrying)."""
        delay = full_jitter_delay(
            self._backoff_s, attempt, self._rng, remaining_s
        )
        if delay is None:
            return None
        time.sleep(delay)
        return delay

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None,
                 retries: Optional[int] = None) -> httpx.Response:
        body = json.dumps(payload).encode() if payload is not None else b""
        attempts = (self._max_retries if retries is None else retries) + 1
        budget = self._retry_budget_s
        last_exc: Optional[Exception] = None
        # plane failover: a transport failure / 5xx rotates to a peer plane
        # and retries THERE without consuming a backoff attempt — bounded
        # to one full lap of the endpoint list per request, so a dead
        # cohort still exhausts in finite time. Even a retries=0 call
        # (next-job poll, stream checkpoint) gets its lap: the rotation is
        # sticky, so the NEXT call starts on the surviving plane.
        rotations = 0
        max_rotations = len(self.endpoints) - 1
        attempt = 0
        while attempt < attempts:
            try:
                resp = _faults.wrap_http(
                    "worker.api.request",
                    lambda: self._client.request(
                        method, path, content=body or None,
                        headers=self._headers(method, path, body),
                    ),
                    method=method, path=path,
                    # per-replica chaos targeting (fleet harness sets
                    # fault_tag): a bidirectional partition must cut ONE
                    # worker's control-plane traffic, not the process's
                    worker=str(getattr(self, "fault_tag", "") or ""),
                    # destination endpoint: plane-targeted chaos rules
                    # (plane_partition / plane_slow) match on it
                    server=self.base_url,
                )
            except httpx.TransportError as exc:
                last_exc = exc
                if rotations < max_rotations and self._failover_plane():
                    rotations += 1
                    continue
                attempt += 1
                if attempt >= attempts:
                    break
                slept = self._backoff(attempt - 1, budget)
                if slept is None:
                    break
                budget -= slept
                continue
            if resp.status_code >= 500:
                last_exc = APIError(resp.status_code, resp.text[:200])
                if rotations < max_rotations and self._failover_plane():
                    rotations += 1
                    continue
                attempt += 1
                if attempt >= attempts:
                    raise last_exc
                slept = self._backoff(attempt - 1, budget)
                if slept is None:
                    raise last_exc
                budget -= slept
                continue
            if 400 <= resp.status_code < 500:  # never retried (:87)
                detail = ""
                try:
                    detail = resp.json().get("detail", "")
                except ValueError:
                    pass
                raise APIError(resp.status_code, detail)
            return resp
        raise APIError(599, f"transport failed: {last_exc}")

    # -- registration / auth --------------------------------------------------

    def register(self, info: Dict[str, Any]) -> Dict[str, Any]:
        if self.worker_id:
            info = {**info, "worker_id": self.worker_id}
        resp = self._request("POST", "/api/v1/workers/register", info)
        data = resp.json()
        self.worker_id = data["worker_id"]
        self.auth_token = data["auth_token"]
        self.refresh_token = data["refresh_token"]
        self.signing_secret = data["signing_secret"]
        return data

    def verify_credentials(self) -> bool:
        if not (self.worker_id and self.auth_token):
            return False
        try:
            self._request(
                "POST", f"/api/v1/workers/{self.worker_id}/verify", {}
            )
            return True
        except APIError:
            return False

    def refresh_credentials(self) -> Dict[str, Any]:
        resp = self._request(
            "POST",
            f"/api/v1/workers/{self.worker_id}/refresh-token",
            {"refresh_token": self.refresh_token},
        )
        data = resp.json()
        self.auth_token = data["auth_token"]
        self.refresh_token = data["refresh_token"]
        self.signing_secret = data["signing_secret"]
        return data

    # -- lifecycle -------------------------------------------------------------

    def heartbeat(self, status: str = "idle",
                  config_version: int = 0,
                  **extra: Any) -> Dict[str, Any]:
        resp = self._request(
            "POST",
            f"/api/v1/workers/{self.worker_id}/heartbeat",
            {"status": status, "config_version": config_version, **extra},
        )
        return resp.json()

    def fetch_next_job(self) -> Optional[Dict[str, Any]]:
        resp = self._request(
            "GET", f"/api/v1/workers/{self.worker_id}/next-job", retries=0
        )
        if resp.status_code == 204:
            return None
        return resp.json()["job"]

    def complete_job(self, job_id: str, success: bool,
                     result: Optional[Dict[str, Any]] = None,
                     error: Optional[str] = None,
                     assignment_epoch: Optional[int] = None
                     ) -> Dict[str, Any]:
        payload: Dict[str, Any] = {
            "success": success, "result": result, "error": error,
        }
        if assignment_epoch is not None:
            # zombie fence: the server rejects a completion whose epoch no
            # longer matches the job's current assignment (requeued or
            # reclaimed since) with a 409 instead of applying it
            payload["assignment_epoch"] = int(assignment_epoch)
        resp = self._request(
            "POST",
            f"/api/v1/workers/{self.worker_id}/jobs/{job_id}/complete",
            payload,
        )
        return resp.json()

    # -- crash-safe generation (checkpoints + stream failover) ---------------

    def checkpoint_job(self, job_id: str, assignment_epoch: int,
                       state: Optional[Dict[str, Any]],
                       migrate: bool = False) -> Dict[str, Any]:
        """Push a generation checkpoint for a RUNNING job; ``migrate=True``
        additionally requeues it (graceful drain) without burning a retry."""
        resp = self._request(
            "POST",
            f"/api/v1/workers/{self.worker_id}/jobs/{job_id}/checkpoint",
            {"assignment_epoch": int(assignment_epoch), "state": state,
             "migrate": bool(migrate)},
        )
        return resp.json()

    def checkpoint_stream(self, stream_id: str, epoch: int,
                          state: Optional[Dict[str, Any]],
                          done: bool = False) -> Dict[str, Any]:
        """Push (or, with ``done=True``, retire) a direct stream's
        checkpoint — the per-token cadence between heartbeats."""
        resp = self._request(
            "POST",
            f"/api/v1/workers/{self.worker_id}/streams/{stream_id}"
            "/checkpoint",
            {"epoch": int(epoch), "state": state, "done": bool(done)},
            retries=0,
        )
        return resp.json()

    def adopt_stream(self, stream_id: str) -> Dict[str, Any]:
        """Adopt a dropped stream's checkpoint (epoch fences out the
        previous owner); raises APIError(404) when none exists."""
        resp = self._request(
            "POST",
            f"/api/v1/workers/{self.worker_id}/streams/{stream_id}/adopt",
            {},
        )
        return resp.json()

    def release_job(self, job_id: str) -> None:
        """Decline a claimed job without failing it (client-side load
        control); the server requeues it for other workers."""
        self._request(
            "POST",
            f"/api/v1/workers/{self.worker_id}/jobs/{job_id}/release",
            {},
        )

    def going_offline(self) -> None:
        self._request(
            "POST", f"/api/v1/workers/{self.worker_id}/going-offline", {}
        )

    def offline(self) -> List[str]:
        resp = self._request(
            "POST", f"/api/v1/workers/{self.worker_id}/offline", {}
        )
        return resp.json().get("requeued_jobs", [])

    def fetch_remote_config(self) -> Dict[str, Any]:
        resp = self._request(
            "GET", f"/api/v1/workers/{self.worker_id}/config"
        )
        return resp.json()
