"""Worker runtime: registration, poll loop, engines, direct serving.

TPU-native re-design of the reference's ``worker/`` layer: the process model
(register → heartbeat thread + poll loop → engine dispatch → graceful drain)
matches ``worker/main.py``, but engines run jitted JAX graphs on TPU chips
instead of wrapping vLLM/SGLang subprocesses.
"""
