"""Client SDK for the TPU inference platform.

Parity surface: reference ``sdk/python/inference_client.py`` (C37).
"""

from .client import (
    InferenceClient,
    InferenceClientError,
    NoWorkersAvailable,
    chat,
    embed,
    generate_image,
)

__all__ = [
    "InferenceClient",
    "InferenceClientError",
    "NoWorkersAvailable",
    "chat",
    "embed",
    "generate_image",
]
