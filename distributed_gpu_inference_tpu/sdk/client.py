"""Python client SDK: multi-server fallback, retries, sync/async jobs,
direct-to-worker mode.

Behavioral parity with the reference's ``sdk/python/inference_client.py``:

- Multi-server fallback + retry ladder: 503 → try the next server, 4xx →
  raise immediately, transport errors/5xx → exponential backoff then next
  server (:58-100).
- ``chat`` / ``generate_image`` with sync (long-poll ``/jobs/sync``) or
  async (create → poll) execution (:104-221).
- Job lifecycle: create / get / wait / cancel (:225-280).
- Direct mode: nearest-worker discovery via ``/api/v1/jobs/direct/nearest``
  with a 60 s cache (:284-306), then POST to the worker's ``/inference``
  (:308-329); on any direct failure, falls back to the queued path.
- Module-level one-shot helpers (:380-399).
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional, Sequence

import httpx

from ..testing import faults as _faults
from ..utils.backoff import full_jitter_delay

DIRECT_CACHE_TTL_S = 60.0  # reference inference_client.py:284-306


class InferenceClientError(Exception):
    def __init__(self, status: int, detail: str = "",
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(f"HTTP {status}: {detail}")
        self.status = status
        self.detail = detail
        # server-provided backpressure hint (429/503 Retry-After header or
        # the machine-readable retry_after_s body field) — callers that
        # schedule their own retries should wait at least this long
        self.retry_after_s = retry_after_s


class NoWorkersAvailable(InferenceClientError):
    """Every configured server answered 503 (no capacity)."""

    def __init__(self, detail: str = "no workers available",
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(503, detail, retry_after_s=retry_after_s)


def _retry_after_of(resp: httpx.Response) -> Optional[float]:
    """Parse the server's retry hint: machine-readable ``retry_after_s`` in
    the JSON body (one contract for 429 backpressure AND 503 capacity
    errors), falling back to the standard Retry-After header."""
    try:
        val = resp.json().get("retry_after_s")
        if val is not None:
            return max(0.0, float(val))
    except (ValueError, AttributeError, TypeError):
        pass
    hdr = resp.headers.get("Retry-After")
    if hdr:
        try:
            return max(0.0, float(hdr))
        except ValueError:
            pass
    return None


class InferenceClient:
    def __init__(
        self,
        server_url: str | Sequence[str] = "http://127.0.0.1:8000",
        api_key: Optional[str] = None,
        timeout_s: float = 120.0,
        max_retries: int = 2,
        backoff_s: float = 0.5,
        transport: Optional[httpx.BaseTransport] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.servers = (
            [server_url] if isinstance(server_url, str) else list(server_url)
        )
        self.servers = [s.rstrip("/") for s in self.servers]
        self.api_key = api_key
        self._max_retries = max_retries
        self._backoff_s = backoff_s
        # full-jitter source; injectable so tests can pin the schedule
        self._rng = rng if rng is not None else random.Random()
        self._client = httpx.Client(timeout=timeout_s, transport=transport)
        self._direct_cache: Optional[Dict[str, Any]] = None
        self._direct_cache_at = 0.0

    def close(self) -> None:
        self._client.close()

    def __enter__(self) -> "InferenceClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- transport with server fallback (reference :58-100) -----------------

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.api_key:
            h["X-API-Key"] = self.api_key
        return h

    def _sleep_backoff(self, attempt: int, floor_s: float = 0.0) -> None:
        """Full-jitter exponential backoff (``utils.backoff``); bounded by
        the attempt count, de-synchronized across a fleet of clients.
        ``floor_s``: a server-provided Retry-After hint — honored as a
        minimum wait with the jitter ADDED on top, so a saturated server's
        clients neither return early nor stampede back in lockstep."""
        time.sleep(
            floor_s + full_jitter_delay(self._backoff_s, attempt, self._rng)
        )

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None,
                 params: Optional[Dict[str, str]] = None,
                 timeout: Optional[float] = None,
                 idempotent: bool = True) -> httpx.Response:
        """``idempotent=False`` marks calls whose SERVER-SIDE effect may have
        happened even when the response is lost (POST /jobs, /jobs/sync): they
        are sent exactly once — no transport retry, no 5xx retry, no
        next-server failover — because a blind re-POST would create or
        execute the job again."""
        last: Optional[Exception] = None
        saw_503 = False
        err_429: Optional[InferenceClientError] = None
        last_retry_after: Optional[float] = None
        for server in self.servers:
            for attempt in range(self._max_retries + 1):
                try:
                    resp = _faults.wrap_http(
                        "sdk.client.request",
                        lambda srv=server: self._client.request(
                            method, f"{srv}{path}", json=payload,
                            params=params, headers=self._headers(),
                            **({"timeout": timeout}
                               if timeout is not None else {}),
                        ),
                        method=method, path=path,
                    )
                except httpx.TransportError as exc:
                    last = exc
                    if not idempotent:
                        raise InferenceClientError(
                            599, f"transport failed: {exc}"
                        ) from exc
                    if attempt < self._max_retries:
                        self._sleep_backoff(attempt)
                    continue
                if resp.status_code == 503:
                    saw_503 = True
                    last_retry_after = _retry_after_of(resp)
                    break  # capacity problem: next server, don't retry here
                if resp.status_code == 429:
                    # queue backpressure: the job was NOT created, so a
                    # retry is safe even for non-idempotent POSTs. Honor
                    # Retry-After as the backoff floor with full jitter on
                    # top (no fleet-wide stampede when the hint expires);
                    # once this server's retries are spent, fail over to
                    # the next configured server exactly like the 503 path.
                    retry_after = _retry_after_of(resp) or 1.0
                    last_retry_after = retry_after
                    if attempt < self._max_retries:
                        self._sleep_backoff(attempt, floor_s=retry_after)
                        continue
                    err_429 = InferenceClientError(
                        429, "server backpressure: queue saturated",
                        retry_after_s=retry_after,
                    )
                    last = err_429
                    break
                if 400 <= resp.status_code < 500:
                    detail = ""
                    try:
                        detail = resp.json().get("detail", "")
                    except ValueError:
                        pass
                    raise InferenceClientError(resp.status_code, detail)
                if resp.status_code >= 500:
                    last = InferenceClientError(
                        resp.status_code, resp.text[:200]
                    )
                    if not idempotent:  # the job may have run: don't re-run it
                        raise last
                    if attempt < self._max_retries:
                        self._sleep_backoff(attempt)
                    continue
                return resp
            if not idempotent and not (saw_503 or err_429 is not None):
                break  # no cross-server failover for effectful calls
                #       (503/429 mean the job was never created — safe)
        if saw_503:
            raise NoWorkersAvailable(retry_after_s=last_retry_after)
        if err_429 is not None:
            raise err_429  # every server backpressured: surface the hint
        raise InferenceClientError(599, f"all servers failed: {last}")

    # -- job lifecycle (reference :225-280) ----------------------------------

    def create_job(self, job_type: str, params: Dict[str, Any],
                   priority: int = 0,
                   preferred_region: Optional[str] = None,
                   **extra: Any) -> str:
        body: Dict[str, Any] = {
            "type": job_type, "params": params, "priority": priority, **extra,
        }
        if preferred_region:
            body["preferred_region"] = preferred_region
        resp = self._request("POST", "/api/v1/jobs", body, idempotent=False)
        return resp.json()["job_id"]

    def get_job(self, job_id: str) -> Dict[str, Any]:
        return self._request("GET", f"/api/v1/jobs/{job_id}").json()

    def wait_for_job(self, job_id: str, timeout_s: float = 300.0,
                     poll_s: float = 0.5) -> Dict[str, Any]:
        deadline = time.time() + timeout_s
        while True:
            try:
                job = self.get_job(job_id)
            except InferenceClientError as exc:
                # GET /jobs/{id} is idempotent: a transient blip (transport
                # failure = 599, or a 5xx the retry ladder exhausted on)
                # must not abort a long wait — keep polling until the
                # deadline. 4xx are real answers and surface immediately.
                if exc.status < 500:
                    raise
                if time.time() >= deadline:
                    raise TimeoutError(
                        f"job {job_id}: server unreachable at deadline "
                        f"({exc})"
                    ) from exc
                time.sleep(poll_s * self._rng.uniform(0.5, 1.0))
                continue
            if job["status"] in ("completed", "failed", "cancelled"):
                return job
            if time.time() >= deadline:
                raise TimeoutError(
                    f"job {job_id} still {job['status']} after {timeout_s}s"
                )
            time.sleep(poll_s)

    def cancel_job(self, job_id: str) -> None:
        self._request("DELETE", f"/api/v1/jobs/{job_id}")

    def queue_stats(self) -> Dict[str, Any]:
        return self._request("GET", "/api/v1/jobs/stats/queue").json()

    def _run_job(self, job_type: str, params: Dict[str, Any], sync: bool,
                 timeout_s: float, **extra: Any) -> Dict[str, Any]:
        if sync:
            # read timeout must outlive the server's long-poll window, and a
            # timeout must NOT be retried (the job may still complete)
            resp = self._request(
                "POST", "/api/v1/jobs/sync",
                {"type": job_type, "params": params,
                 "timeout_seconds": timeout_s, **extra},
                timeout=timeout_s + 15.0,
                idempotent=False,
            )
            data = resp.json()
            if data.get("status") != "completed":
                raise InferenceClientError(
                    500, data.get("error") or f"job {data.get('status')}"
                )
            return data["result"]
        job_id = self.create_job(job_type, params, **extra)
        job = self.wait_for_job(job_id, timeout_s=timeout_s)
        if job["status"] != "completed":
            raise InferenceClientError(
                500, job.get("error") or f"job {job['status']}"
            )
        return job["result"]

    # -- task helpers (reference :104-221) -----------------------------------

    def chat(
        self,
        messages: Optional[List[Dict[str, str]]] = None,
        prompt: Optional[str] = None,
        model: Optional[str] = None,
        sync: bool = True,
        use_direct: bool = False,
        timeout_s: float = 120.0,
        **gen_params: Any,
    ) -> Dict[str, Any]:
        params: Dict[str, Any] = dict(gen_params)
        if messages is not None:
            params["messages"] = messages
        if prompt is not None:
            params["prompt"] = prompt
        if model is not None:
            params["model"] = model
        if use_direct:
            result = self._try_direct("llm", params)
            if result is not None:
                return result
        return self._run_job("llm", params, sync=sync, timeout_s=timeout_s)

    def generate_image(self, prompt: str, sync: bool = True,
                       timeout_s: float = 300.0,
                       **gen_params: Any) -> Dict[str, Any]:
        params = {"prompt": prompt, **gen_params}
        return self._run_job(
            "image_gen", params, sync=sync, timeout_s=timeout_s
        )

    def embed(self, texts: Sequence[str], sync: bool = True,
              timeout_s: float = 60.0, **params: Any) -> Dict[str, Any]:
        return self._run_job(
            "embedding", {"texts": list(texts), **params},
            sync=sync, timeout_s=timeout_s,
        )

    def transcribe(self, audio_b64: str, sync: bool = True,
                   timeout_s: float = 300.0, **params: Any) -> Dict[str, Any]:
        return self._run_job(
            "whisper", {"audio": audio_b64, **params},
            sync=sync, timeout_s=timeout_s,
        )

    def stream_chat(
        self,
        messages: Optional[List[Dict[str, str]]] = None,
        prompt: Optional[str] = None,
        model: Optional[str] = None,
        timeout_s: float = 300.0,
        **gen_params: Any,
    ):
        """Token streaming via the nearest direct worker's SSE endpoint.

        Yields ``{"text_delta", "token_ids"}`` chunks then a final
        ``{"done": True, ...}``. When no direct worker is available (or the
        stream fails before the first chunk), falls back to one queued
        round trip yielded as a single chunk + done event.
        """
        import json as _json

        params: Dict[str, Any] = dict(gen_params)
        if messages is not None:
            params["messages"] = messages
        if prompt is not None:
            params["prompt"] = prompt
        if model is not None:
            params["model"] = model

        worker = self._get_nearest_worker()
        if worker is not None:
            url = f"{worker['direct_url'].rstrip('/')}/inference/stream"
            yielded = False
            try:
                with self._client.stream(
                    "POST", url, json={"type": "llm", "params": params},
                    headers=self._headers(), timeout=timeout_s,
                ) as resp:
                    if resp.status_code == 200:
                        for line in resp.iter_lines():
                            if not line.startswith("data: "):
                                continue
                            chunk = _json.loads(line[len("data: "):])
                            if "error" in chunk:
                                raise InferenceClientError(
                                    500, chunk["error"]
                                )
                            yielded = True
                            yield chunk
                        return
                    self._direct_cache = None  # busy: rediscover later
            except httpx.TransportError as exc:
                self._direct_cache = None
                if yielded:
                    # chunks already reached the consumer: a queued re-run
                    # would duplicate text AND execute the prompt twice
                    raise InferenceClientError(
                        599, f"stream dropped mid-generation: {exc}"
                    ) from exc
        # fallback: queued path, emitted as one chunk (stream contract kept)
        result = self._run_job("llm", params, sync=True, timeout_s=timeout_s)
        yield {"text_delta": result.get("text", ""), "token_ids": []}
        yield {"done": True,
               "finish_reason": result.get("finish_reason", "stop"),
               "usage": result.get("usage", {})}

    # -- direct mode (reference :284-329) ------------------------------------

    def _get_nearest_worker(self) -> Optional[Dict[str, Any]]:
        now = time.time()
        if self._direct_cache is not None and \
                now - self._direct_cache_at < DIRECT_CACHE_TTL_S:
            return self._direct_cache
        try:
            resp = self._request("GET", "/api/v1/jobs/direct/nearest")
        except InferenceClientError:
            return None
        self._direct_cache = resp.json()
        self._direct_cache_at = now
        return self._direct_cache

    def _try_direct(self, job_type: str,
                    params: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """POST straight to the nearest worker; any failure returns None so
        the caller falls back to the queued path (reference :308-329)."""
        worker = self._get_nearest_worker()
        if worker is None:
            return None
        try:
            resp = self._client.post(
                f"{worker['direct_url'].rstrip('/')}/inference",
                json={"type": job_type, "params": params},
                headers=self._headers(),
            )
        except httpx.TransportError:
            self._direct_cache = None
            return None
        if resp.status_code != 200:
            self._direct_cache = None  # busy/draining: rediscover next time
            return None
        return resp.json()["result"]


# ---------------------------------------------------------------------------
# Module-level one-shots (reference :380-399)
# ---------------------------------------------------------------------------


def chat(messages=None, prompt=None, server_url="http://127.0.0.1:8000",
         **kw) -> Dict[str, Any]:
    with InferenceClient(server_url) as c:
        return c.chat(messages=messages, prompt=prompt, **kw)


def generate_image(prompt: str, server_url="http://127.0.0.1:8000",
                   **kw) -> Dict[str, Any]:
    with InferenceClient(server_url) as c:
        return c.generate_image(prompt, **kw)


def embed(texts: Sequence[str], server_url="http://127.0.0.1:8000",
          **kw) -> Dict[str, Any]:
    with InferenceClient(server_url) as c:
        return c.embed(texts, **kw)
