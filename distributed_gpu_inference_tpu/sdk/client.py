"""Python client SDK: multi-server fallback, retries, sync/async jobs,
direct-to-worker mode.

Behavioral parity with the reference's ``sdk/python/inference_client.py``:

- Multi-server fallback + retry ladder: 503 → try the next server, 4xx →
  raise immediately, transport errors/5xx → exponential backoff then next
  server (:58-100).
- ``chat`` / ``generate_image`` with sync (long-poll ``/jobs/sync``) or
  async (create → poll) execution (:104-221).
- Job lifecycle: create / get / wait / cancel (:225-280).
- Direct mode: nearest-worker discovery via ``/api/v1/jobs/direct/nearest``
  with a 60 s cache (:284-306), then POST to the worker's ``/inference``
  (:308-329); on any direct failure, falls back to the queued path.
- Module-level one-shot helpers (:380-399).
"""

from __future__ import annotations

import concurrent.futures
import random
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

import httpx

from ..testing import faults as _faults
from ..utils.backoff import full_jitter_delay
from ..utils.prefixes import (
    canonical_prompt_text,
    fingerprints_for_params,
    prefix_fingerprints,
)

DIRECT_CACHE_TTL_S = 60.0  # reference inference_client.py:284-306
# sticky session→worker routing cache: kept SHORT (same staleness budget
# as the generic direct cache) because a sticky hit skips the server's
# load-spillover ranking — the pin must expire before a saturated worker
# can accumulate conversations the fleet should absorb
SESSION_CACHE_TTL_S = 60.0
_SESSION_CACHE_MAX = 1024


class InferenceClientError(Exception):
    def __init__(self, status: int, detail: str = "",
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(f"HTTP {status}: {detail}")
        self.status = status
        self.detail = detail
        # server-provided backpressure hint (429/503 Retry-After header or
        # the machine-readable retry_after_s body field) — callers that
        # schedule their own retries should wait at least this long
        self.retry_after_s = retry_after_s


class NoWorkersAvailable(InferenceClientError):
    """Every configured server answered 503 (no capacity)."""

    def __init__(self, detail: str = "no workers available",
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(503, detail, retry_after_s=retry_after_s)


def _retry_after_of(resp: httpx.Response) -> Optional[float]:
    """Parse the server's retry hint: machine-readable ``retry_after_s`` in
    the JSON body (one contract for 429 backpressure AND 503 capacity
    errors), falling back to the standard Retry-After header."""
    try:
        val = resp.json().get("retry_after_s")
        if val is not None:
            return max(0.0, float(val))
    except (ValueError, AttributeError, TypeError):
        pass
    hdr = resp.headers.get("Retry-After")
    if hdr:
        try:
            return max(0.0, float(hdr))
        except ValueError:
            pass
    return None


class InferenceClient:
    def __init__(
        self,
        server_url: str | Sequence[str] = "http://127.0.0.1:8000",
        api_key: Optional[str] = None,
        timeout_s: float = 120.0,
        max_retries: int = 2,
        backoff_s: float = 0.5,
        transport: Optional[httpx.BaseTransport] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.servers = (
            [server_url] if isinstance(server_url, str) else list(server_url)
        )
        self.servers = [s.rstrip("/") for s in self.servers]
        self.api_key = api_key
        self._max_retries = max_retries
        self._backoff_s = backoff_s
        # full-jitter source; injectable so tests can pin the schedule
        self._rng = rng if rng is not None else random.Random()
        self._client = httpx.Client(timeout=timeout_s, transport=transport)
        self._direct_cache: Optional[Dict[str, Any]] = None
        self._direct_cache_at = 0.0
        # cache-aware routing: session → (worker, ts) sticky cache. A
        # conversation keeps landing on the worker already holding its
        # KV prefix without re-asking the control plane every turn; any
        # failure drops the entry and rediscovers (affinity, never a pin).
        self._session_workers: Dict[str, tuple] = {}

    def close(self) -> None:
        self._client.close()

    def __enter__(self) -> "InferenceClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- transport with server fallback (reference :58-100) -----------------

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.api_key:
            h["X-API-Key"] = self.api_key
        return h

    def _sleep_backoff(self, attempt: int, floor_s: float = 0.0) -> None:
        """Full-jitter exponential backoff (``utils.backoff``); bounded by
        the attempt count, de-synchronized across a fleet of clients.
        ``floor_s``: a server-provided Retry-After hint — honored as a
        minimum wait with the jitter ADDED on top, so a saturated server's
        clients neither return early nor stampede back in lockstep."""
        time.sleep(
            floor_s + full_jitter_delay(self._backoff_s, attempt, self._rng)
        )

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None,
                 params: Optional[Dict[str, str]] = None,
                 timeout: Optional[float] = None,
                 idempotent: bool = True) -> httpx.Response:
        """``idempotent=False`` marks calls whose SERVER-SIDE effect may have
        happened even when the response is lost (POST /jobs, /jobs/sync): they
        are sent exactly once — no transport retry, no 5xx retry, no
        next-server failover — because a blind re-POST would create or
        execute the job again."""
        last: Optional[Exception] = None
        saw_503 = False
        saw_conn_fail = False
        err_429: Optional[InferenceClientError] = None
        last_retry_after: Optional[float] = None
        for server in self.servers:
            for attempt in range(self._max_retries + 1):
                try:
                    resp = _faults.wrap_http(
                        "sdk.client.request",
                        lambda srv=server: self._client.request(
                            method, f"{srv}{path}", json=payload,
                            params=params, headers=self._headers(),
                            **({"timeout": timeout}
                               if timeout is not None else {}),
                        ),
                        method=method, path=path,
                        # destination endpoint: plane-targeted chaos rules
                        # (plane_partition / plane_slow) match on it
                        server=server,
                    )
                except httpx.TransportError as exc:
                    last = exc
                    if not idempotent:
                        if isinstance(exc, httpx.ConnectError):
                            # plane-connection loss BEFORE the request was
                            # ever sent: the job was definitively NOT
                            # created, so the next plane endpoint may
                            # safely take the submission — this is the one
                            # transport failure where failing over an
                            # effectful POST cannot double-execute it
                            saw_conn_fail = True
                            break
                        raise InferenceClientError(
                            599, f"transport failed: {exc}"
                        ) from exc
                    if attempt < self._max_retries:
                        self._sleep_backoff(attempt)
                    continue
                if resp.status_code == 503:
                    saw_503 = True
                    last_retry_after = _retry_after_of(resp)
                    break  # capacity problem: next server, don't retry here
                if resp.status_code == 429:
                    # queue backpressure: the job was NOT created, so a
                    # retry is safe even for non-idempotent POSTs. Honor
                    # Retry-After as the backoff floor with full jitter on
                    # top (no fleet-wide stampede when the hint expires);
                    # once this server's retries are spent, fail over to
                    # the next configured server exactly like the 503 path.
                    retry_after = _retry_after_of(resp) or 1.0
                    last_retry_after = retry_after
                    if attempt < self._max_retries:
                        self._sleep_backoff(attempt, floor_s=retry_after)
                        continue
                    err_429 = InferenceClientError(
                        429, "server backpressure: queue saturated",
                        retry_after_s=retry_after,
                    )
                    last = err_429
                    break
                if 400 <= resp.status_code < 500:
                    detail = ""
                    try:
                        detail = resp.json().get("detail", "")
                    except ValueError:
                        pass
                    raise InferenceClientError(resp.status_code, detail)
                if resp.status_code >= 500:
                    last = InferenceClientError(
                        resp.status_code, resp.text[:200]
                    )
                    if not idempotent:  # the job may have run: don't re-run it
                        raise last
                    if attempt < self._max_retries:
                        self._sleep_backoff(attempt)
                    continue
                return resp
            if not idempotent and not (
                saw_503 or err_429 is not None or saw_conn_fail
            ):
                break  # no cross-server failover for effectful calls
                #       (503/429/connect-refused mean the job was never
                #       created — safe)
        if saw_503:
            raise NoWorkersAvailable(retry_after_s=last_retry_after)
        if err_429 is not None:
            raise err_429  # every server backpressured: surface the hint
        raise InferenceClientError(599, f"all servers failed: {last}")

    # -- job lifecycle (reference :225-280) ----------------------------------

    def create_job(self, job_type: str, params: Dict[str, Any],
                   priority: int = 0,
                   preferred_region: Optional[str] = None,
                   **extra: Any) -> str:
        body: Dict[str, Any] = {
            "type": job_type, "params": params, "priority": priority, **extra,
        }
        if preferred_region:
            body["preferred_region"] = preferred_region
        resp = self._request("POST", "/api/v1/jobs", body, idempotent=False)
        return resp.json()["job_id"]

    def get_job(self, job_id: str) -> Dict[str, Any]:
        return self._request("GET", f"/api/v1/jobs/{job_id}").json()

    def wait_for_job(self, job_id: str, timeout_s: float = 300.0,
                     poll_s: float = 0.5) -> Dict[str, Any]:
        deadline = time.time() + timeout_s
        while True:
            try:
                job = self.get_job(job_id)
            except InferenceClientError as exc:
                # GET /jobs/{id} is idempotent: a transient blip (transport
                # failure = 599, or a 5xx the retry ladder exhausted on)
                # must not abort a long wait — keep polling until the
                # deadline. 4xx are real answers and surface immediately.
                if exc.status < 500:
                    raise
                if time.time() >= deadline:
                    raise TimeoutError(
                        f"job {job_id}: server unreachable at deadline "
                        f"({exc})"
                    ) from exc
                time.sleep(poll_s * self._rng.uniform(0.5, 1.0))
                continue
            if job["status"] in ("completed", "failed", "cancelled"):
                return job
            if time.time() >= deadline:
                raise TimeoutError(
                    f"job {job_id} still {job['status']} after {timeout_s}s"
                )
            time.sleep(poll_s)

    def cancel_job(self, job_id: str) -> None:
        self._request("DELETE", f"/api/v1/jobs/{job_id}")

    def queue_stats(self) -> Dict[str, Any]:
        return self._request("GET", "/api/v1/jobs/stats/queue").json()

    def get_request_timeline(self, job_or_trace_id: str) -> Dict[str, Any]:
        """Merged flight-recorder timeline for a job (PD stage children
        resolve to the parent's trace) or a raw trace id: causally-ordered
        events + derived per-phase durations. 404s (as
        InferenceClientError) when nothing was recorded — e.g. the request
        carried no ``trace_id``."""
        return self._request(
            "GET", f"/api/v1/debug/requests/{job_or_trace_id}/timeline"
        ).json()

    def _run_job(self, job_type: str, params: Dict[str, Any], sync: bool,
                 timeout_s: float, **extra: Any) -> Dict[str, Any]:
        if sync:
            # read timeout must outlive the server's long-poll window, and a
            # timeout must NOT be retried (the job may still complete)
            resp = self._request(
                "POST", "/api/v1/jobs/sync",
                {"type": job_type, "params": params,
                 "timeout_seconds": timeout_s, **extra},
                timeout=timeout_s + 15.0,
                idempotent=False,
            )
            data = resp.json()
            if data.get("status") != "completed":
                raise InferenceClientError(
                    500, data.get("error") or f"job {data.get('status')}"
                )
            return data["result"]
        job_id = self.create_job(job_type, params, **extra)
        job = self.wait_for_job(job_id, timeout_s=timeout_s)
        if job["status"] != "completed":
            raise InferenceClientError(
                500, job.get("error") or f"job {job['status']}"
            )
        return job["result"]

    # -- task helpers (reference :104-221) -----------------------------------

    @staticmethod
    def _routing_fps(params: Dict[str, Any],
                     prefix_hint: Optional[str]) -> List[str]:
        """Boundary fingerprints for cache-aware routing: the explicit
        ``prefix_hint`` (e.g. a shared system prompt) when given, else the
        request's own prompt/messages — same canonicalization and hash as
        the control plane and the workers (``utils/prefixes.py``)."""
        if prefix_hint is not None:
            if not prefix_hint:
                return []
            if params.get("messages"):
                # workers fingerprint the CANONICAL message text
                # ("role\x1fcontent\x1e..."), so a raw-text hint would
                # never match — wrap it as the leading system message it
                # names, whose canonical form IS a prefix of the
                # request's canonical text
                return prefix_fingerprints(canonical_prompt_text(
                    [{"role": "system", "content": prefix_hint}]
                ))
            return prefix_fingerprints(canonical_prompt_text(prefix_hint))
        # no hint: same messages-over-prompt precedence as the server's
        # fallback computation — ONE implementation, so client- and
        # server-side fingerprints of a request can never drift
        return fingerprints_for_params(params)

    def chat(
        self,
        messages: Optional[List[Dict[str, str]]] = None,
        prompt: Optional[str] = None,
        model: Optional[str] = None,
        sync: bool = True,
        use_direct: bool = False,
        timeout_s: float = 120.0,
        priority: int = 0,
        session: Optional[str] = None,
        prefix_hint: Optional[str] = None,
        trace_id: Optional[str] = None,
        **gen_params: Any,
    ) -> Dict[str, Any]:
        """``priority``: scheduling priority — orders the control-plane
        queue AND the worker batcher's admission heap (higher admits
        first; KV-pressure victims are picked lowest-priority-first).

        Cache-aware routing: ``session`` makes direct mode sticky — every
        call with the same session id prefers the worker that served the
        last one (whose radix cache holds the conversation's KV), falling
        back to rediscovery on any failure. ``prefix_hint`` names the
        shared prefix (a system prompt, a RAG document header) to
        fingerprint for affinity routing; without it the prompt/messages
        fingerprint themselves. Both are advisory — results are identical
        wherever the request lands."""
        params: Dict[str, Any] = dict(gen_params)
        if messages is not None:
            params["messages"] = messages
        if prompt is not None:
            params["prompt"] = prompt
        if model is not None:
            params["model"] = model
        if priority:
            params["priority"] = int(priority)
        if trace_id:
            # flight recorder: ride the request end to end — fetch the
            # merged timeline later via get_request_timeline()
            params["trace_id"] = str(trace_id)
        fps = self._routing_fps(params, prefix_hint)
        if use_direct:
            result = self._try_direct("llm", params, prefix_fps=fps,
                                      session=session)
            if result is not None:
                return result
        return self._run_job("llm", params, sync=sync, timeout_s=timeout_s,
                             **({"priority": int(priority)} if priority
                                else {}),
                             **({"prefix_fps": fps} if fps else {}))

    def generate_image(self, prompt: str, sync: bool = True,
                       timeout_s: float = 300.0,
                       **gen_params: Any) -> Dict[str, Any]:
        params = {"prompt": prompt, **gen_params}
        return self._run_job(
            "image_gen", params, sync=sync, timeout_s=timeout_s
        )

    def embed(self, texts: Sequence[str], sync: bool = True,
              timeout_s: float = 60.0, **params: Any) -> Dict[str, Any]:
        return self._run_job(
            "embedding", {"texts": list(texts), **params},
            sync=sync, timeout_s=timeout_s,
        )

    def transcribe(self, audio_b64: str, sync: bool = True,
                   timeout_s: float = 300.0, **params: Any) -> Dict[str, Any]:
        return self._run_job(
            "whisper", {"audio": audio_b64, **params},
            sync=sync, timeout_s=timeout_s,
        )

    def stream_chat(
        self,
        messages: Optional[List[Dict[str, str]]] = None,
        prompt: Optional[str] = None,
        model: Optional[str] = None,
        timeout_s: float = 300.0,
        max_stream_resumes: int = 3,
        priority: int = 0,
        session: Optional[str] = None,
        prefix_hint: Optional[str] = None,
        trace_id: Optional[str] = None,
        **gen_params: Any,
    ):
        """Token streaming via the nearest direct worker's SSE endpoint.

        Yields ``{"text_delta", "token_ids"}`` chunks then a final
        ``{"done": True, ...}``. When no direct worker is available (or the
        stream fails before the first chunk), falls back to one queued
        round trip yielded as a single chunk + done event. The queued
        fallback NEVER fires after a chunk was consumed — a re-run would
        duplicate the delivered prefix AND execute the prompt twice.

        Exactly-once resumable streams: offset-aware workers stamp every
        event with a monotonic token ``offset``. When such a stream drops
        mid-generation, the client reconnects — to the same worker or,
        excluding the one that just died, a failover peer — with a
        ``Last-Event-ID``-style ``resume {stream_id, offset}`` body. The
        worker adopts the generation's control-plane checkpoint and splices
        the continuation at the offset, so the consumer sees the exact
        token sequence an undropped stream would have produced: no gap, no
        duplicate. Streams from legacy (offset-less) workers keep the old
        contract: a mid-generation drop raises."""
        import json as _json
        import uuid as _uuid

        params: Dict[str, Any] = dict(gen_params)
        if messages is not None:
            params["messages"] = messages
        if prompt is not None:
            params["prompt"] = prompt
        if model is not None:
            params["model"] = model
        if priority:
            # reaches the worker batcher's admission heap: a high-priority
            # stream admits ahead of waiting work on a saturated worker
            params["priority"] = int(priority)
        if trace_id:
            # flight recorder: the stream's final done chunk carries the
            # worker-side timeline; the heartbeat channel ships it to the
            # plane's merged store too
            params["trace_id"] = str(trace_id)

        stream_id = _uuid.uuid4().hex
        offset = 0            # token offset of the last consumed event
        text_len = 0          # characters consumed (holdback flushes can
        #                       advance text without advancing the token
        #                       offset — the resume must splice BOTH)
        yielded = False       # any chunk reached the consumer
        offset_aware = False  # the worker stamps offsets → resumable
        resumes = 0
        failed_workers: List[str] = []
        last_err: Any = None

        fps = self._routing_fps(params, prefix_hint)
        plane_retries = 0
        while True:
            resuming = yielded
            try:
                worker = self._get_nearest_worker(
                    exclude=failed_workers or None,
                    prefix_fps=fps, session=session,
                    raise_plane_errors=resuming,
                )
            except InferenceClientError as exc:
                # plane-connection loss during failover rediscovery: every
                # plane endpoint failed to ANSWER (this is not a worker
                # dying — the checkpoint is still adoptable once any plane
                # comes back). Retry discovery on its own bounded budget,
                # WITHOUT burning max_stream_resumes and WITHOUT
                # blacklisting the worker that was serving us.
                plane_retries += 1
                if plane_retries > self._max_retries + 1:
                    raise InferenceClientError(
                        599, "stream dropped mid-generation and no control "
                             f"plane reachable for failover: {exc}"
                    ) from exc
                self._sleep_backoff(plane_retries - 1)
                continue
            plane_retries = 0
            if worker is None:
                if resuming:
                    raise InferenceClientError(
                        599, "stream dropped mid-generation and no "
                             f"failover worker available: {last_err}"
                    )
                break  # nothing consumed: queued fallback is safe
            url = f"{worker['direct_url'].rstrip('/')}/inference/stream"
            body: Dict[str, Any] = {
                "type": "llm", "params": params, "stream_id": stream_id,
            }
            if resuming:
                body["resume"] = {"stream_id": stream_id, "offset": offset,
                                  "text_offset": text_len}
            dropped = False
            # a worker that DIED on us is excluded from rediscovery; one
            # that merely answered busy/5xx stays eligible (it frees up)
            blacklist = False
            retry_floor = 0.0
            try:
                with self._client.stream(
                    "POST", url, json=body,
                    headers=self._headers(), timeout=timeout_s,
                ) as resp:
                    if resp.status_code != 200:
                        self._direct_cache = None
                        if not resuming:
                            break  # busy/declined: queued fallback
                        if resp.status_code == 409:
                            # no checkpoint exists: the delivered prefix
                            # cannot be disowned and a re-run would
                            # double-generate it — surface the drop
                            raise InferenceClientError(
                                599, "stream dropped mid-generation: no "
                                     "checkpoint to resume from"
                            )
                        dropped = True
                        last_err = f"HTTP {resp.status_code}"
                        try:
                            retry_floor = float(
                                resp.headers.get("Retry-After") or 0.5
                            )
                        except ValueError:
                            retry_floor = 0.5
                    else:
                        for line in resp.iter_lines():
                            if not line.startswith("data: "):
                                continue
                            chunk = _json.loads(line[len("data: "):])
                            if "error" in chunk:
                                raise InferenceClientError(
                                    500, chunk["error"]
                                )
                            off = chunk.get("offset")
                            if off is not None:
                                offset_aware = True
                                # belt-and-braces dedupe: the worker
                                # splices, but a replayed event must never
                                # re-deliver consumed tokens. Same-offset
                                # chunks WITHOUT token ids are legitimate
                                # (the final holdback flush emits text
                                # only, at an unchanged token offset) and
                                # must pass.
                                if not chunk.get("done") and yielded and (
                                    int(off) < offset
                                    or (int(off) == offset
                                        and chunk.get("token_ids"))
                                ):
                                    continue
                                offset = max(offset, int(off))
                            elif resuming:
                                # a resume answered by an offset-less
                                # (legacy or fresh-run) worker cannot be
                                # spliced safely — refuse the duplicate
                                raise InferenceClientError(
                                    599, "stream dropped mid-generation: "
                                         "resume target is not offset-"
                                         "aware"
                                )
                            yielded = True
                            text_len += len(chunk.get("text_delta") or "")
                            yield chunk
                            if chunk.get("done"):
                                return
                        # 200 stream ended with no done event: the
                        # connection died mid-body (worker crash)
                        dropped = True
                        blacklist = True
                        last_err = "stream ended before done event"
            except httpx.TransportError as exc:
                self._direct_cache = None
                dropped = True
                blacklist = True
                last_err = exc
            if not dropped:
                break  # non-200 first attempt fell through: queued path
            if not yielded:
                break  # nothing consumed: queued fallback is safe
            if not offset_aware:
                # legacy worker: no offsets, no safe splice
                raise InferenceClientError(
                    599, f"stream dropped mid-generation: {last_err}"
                )
            resumes += 1
            if resumes > max_stream_resumes:
                raise InferenceClientError(
                    599, f"stream dropped mid-generation: resume budget "
                         f"exhausted after {max_stream_resumes} attempts "
                         f"({last_err})"
                )
            if blacklist:
                wid = worker.get("worker_id")
                if wid and wid not in failed_workers:
                    failed_workers.append(wid)
            self._direct_cache = None
            self._drop_session_worker(session)
            # jittered backoff between resume attempts (Retry-After as the
            # floor on a busy answer) — no zero-delay stampede at the very
            # worker fleet the first failure just destabilized
            self._sleep_backoff(resumes - 1, floor_s=retry_floor)
        # fallback: queued path, emitted as one chunk (stream contract kept)
        result = self._run_job("llm", params, sync=True, timeout_s=timeout_s)
        yield {"text_delta": result.get("text", ""), "token_ids": []}
        yield {"done": True,
               "finish_reason": result.get("finish_reason", "stop"),
               "usage": result.get("usage", {})}

    # -- direct mode (reference :284-329) ------------------------------------

    def _get_nearest_worker(
        self, exclude: Optional[Sequence[str]] = None,
        prefix_fps: Optional[Sequence[str]] = None,
        session: Optional[str] = None,
        trace_id: Optional[str] = None,
        raise_plane_errors: bool = False,
        hedge: bool = False,
    ) -> Optional[Dict[str, Any]]:
        now = time.time()
        if session and not exclude and not hedge:
            cached = self._session_workers.get(session)
            if cached is not None and now - cached[1] < SESSION_CACHE_TTL_S:
                return cached[0]
        if not exclude and not prefix_fps and not hedge \
                and self._direct_cache is not None \
                and now - self._direct_cache_at < DIRECT_CACHE_TTL_S:
            return self._direct_cache
        query: Dict[str, str] = {}
        if hedge:
            # hedged dispatch: ask the plane for a second-ranked backup
            # worker + the p95-derived hedge delay alongside the primary.
            # Hedged discoveries bypass the caches above — the backup
            # choice and delay are per-request-fresh by design.
            query["hedge"] = "1"
        if exclude:
            # exclude: workers the caller just watched fail — a failover
            # reconnect must not land on the corpse
            query["exclude"] = ",".join(exclude)
        if prefix_fps:
            # cache-aware routing: the control plane ranks direct workers
            # by advertised prefix affinity (load-spillover-scaled)
            query["prefix_fps"] = ",".join(prefix_fps)
        if trace_id:
            # flight recorder: the plane notes its route decision on the
            # request's timeline (direct requests never pass complete_job)
            query["trace_id"] = str(trace_id)
        try:
            resp = self._request(
                "GET", "/api/v1/jobs/direct/nearest",
                params=query or None,
            )
        except NoWorkersAvailable:
            # a plane ANSWERED and said the fleet has no eligible worker —
            # that is a definitive routing result, never plane loss
            return None
        except InferenceClientError as exc:
            if raise_plane_errors and exc.status >= 500:
                # the discovery failed because no control plane answered
                # (transport = 599, or retry-exhausted 5xx) — NOT because
                # the fleet has no worker. Callers holding a resumable
                # stream need the distinction: plane loss is retryable
                # without spending worker-failover budget.
                raise
            return None
        worker = resp.json()
        if session:
            if len(self._session_workers) >= _SESSION_CACHE_MAX:
                # evict expired entries first, oldest-inserted as fallback
                cutoff = now - SESSION_CACHE_TTL_S
                for k in [k for k, (_, ts) in self._session_workers.items()
                          if ts < cutoff]:
                    del self._session_workers[k]
                while len(self._session_workers) >= _SESSION_CACHE_MAX:
                    del self._session_workers[
                        next(iter(self._session_workers))
                    ]
            # pop-then-insert: a refresh must move the session to the
            # recent end, or capacity eviction would drop the most ACTIVE
            # session just because it was inserted first
            self._session_workers.pop(session, None)
            self._session_workers[session] = (worker, now)
        if "hedge" in worker:
            # a hedge hint is per-request-fresh (backup pick + delay are
            # derived from live health state) — never cache it
            return worker
        if not prefix_fps or "prefix_affinity" not in worker:
            # the generic cache stays affinity-free: a fingerprinted pick
            # for one conversation must not leak to unrelated requests.
            # An answer WITHOUT a prefix_affinity field was not affinity-
            # ranked (routing disabled server-side, or no summaries) — it
            # is safe to cache, restoring the one-discovery-per-60s
            # behavior when the operator turns routing off.
            self._direct_cache = worker
            self._direct_cache_at = now
        return worker

    def _drop_session_worker(self, session: Optional[str]) -> None:
        if session:
            self._session_workers.pop(session, None)

    def _try_direct(self, job_type: str, params: Dict[str, Any],
                    prefix_fps: Optional[Sequence[str]] = None,
                    session: Optional[str] = None
                    ) -> Optional[Dict[str, Any]]:
        """POST straight to the nearest worker; any failure returns None so
        the caller falls back to the queued path (reference :308-329).

        Hedged dispatch (gray-failure round): DEADLINE-carrying requests
        ask discovery for a backup worker + a p95-derived hedge delay. If
        the primary has not answered within the delay, the same request
        fires at the backup and the first finisher wins — the loser is
        cancelled at its next step boundary via ``/inference/cancel``.
        Deadline-less requests keep the single-POST path bit-for-bit."""
        want_hedge = params.get("deadline_s") is not None
        worker = self._get_nearest_worker(prefix_fps=prefix_fps,
                                          session=session,
                                          trace_id=params.get("trace_id"),
                                          hedge=want_hedge)
        if worker is None:
            return None
        hint = worker.get("hedge") if want_hedge else None
        if isinstance(hint, dict) and hint.get("direct_url"):
            return self._race_hedged(job_type, params, worker, hint,
                                     session)
        try:
            resp = self._client.post(
                f"{worker['direct_url'].rstrip('/')}/inference",
                json={"type": job_type, "params": params},
                headers=self._headers(),
            )
        except httpx.TransportError:
            self._direct_cache = None
            self._drop_session_worker(session)
            return None
        if resp.status_code != 200:
            self._direct_cache = None  # busy/draining: rediscover next time
            self._drop_session_worker(session)
            return None
        return resp.json()["result"]

    def _post_direct_leg(self, direct_url: str, job_type: str,
                         params: Dict[str, Any],
                         hedge_key: str) -> Optional[Dict[str, Any]]:
        """One leg of a hedged race: the request carries its cancel key so
        the losing leg can be aborted server-side. Any failure (transport,
        busy 503, flaky 5xx) returns None — the OTHER leg is the retry."""
        try:
            resp = self._client.post(
                f"{direct_url.rstrip('/')}/inference",
                json={"type": job_type,
                      "params": {**params, "hedge_key": hedge_key}},
                headers=self._headers(),
            )
        except httpx.TransportError:
            return None
        if resp.status_code != 200:
            return None
        try:
            return resp.json()["result"]
        except (ValueError, KeyError):
            return None

    def _cancel_hedge_leg(self, direct_url: str, hedge_key: str) -> None:
        """Best-effort loser abort: idempotent server-side, and a miss
        (request already finished) costs nothing but the wasted decode."""
        try:
            self._client.post(
                f"{direct_url.rstrip('/')}/inference/cancel",
                json={"hedge_key": hedge_key},
                headers=self._headers(), timeout=5.0,
            )
        except Exception:  # noqa: BLE001 — the winner's result stands
            pass

    def _race_hedged(self, job_type: str, params: Dict[str, Any],
                     primary: Dict[str, Any], hint: Dict[str, Any],
                     session: Optional[str]) -> Optional[Dict[str, Any]]:
        """Primary fires immediately; the backup fires after the plane's
        hedge delay unless the primary already answered. First non-error
        answer wins and cancels the other leg. Both-legs-failed falls back
        to the queued path (None), same as the unhedged single POST."""
        legs = {
            "primary": (str(primary["direct_url"]), uuid.uuid4().hex),
            "hedge": (str(hint["direct_url"]), uuid.uuid4().hex),
        }
        delay_s = max(0.0, float(hint.get("delay_ms") or 0.0)) / 1000.0
        ex = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="hedge"
        )
        futures: Dict[Any, str] = {}
        try:
            url, key = legs["primary"]
            pfut = ex.submit(self._post_direct_leg, url, job_type, params,
                             key)
            futures[pfut] = "primary"
            done, _ = concurrent.futures.wait([pfut], timeout=delay_s)
            if pfut in done:
                futures.pop(pfut, None)
                r = pfut.result()
                if r is not None:
                    return r   # primary beat the hedge delay: no hedge
                # primary failed fast: the backup leg IS the retry
            # primary slow (race it) or failed: fire the hedge leg
            url, key = legs["hedge"]
            hfut = ex.submit(self._post_direct_leg, url, job_type,
                             params, key)
            futures[hfut] = "hedge"
            result: Optional[Dict[str, Any]] = None
            while futures:
                done, _ = concurrent.futures.wait(
                    list(futures),
                    return_when=concurrent.futures.FIRST_COMPLETED,
                )
                for f in done:
                    futures.pop(f, None)
                    r = f.result()
                    if r is not None and result is None:
                        result = r
                        for lf, name in list(futures.items()):
                            lurl, lkey = legs[name]
                            self._cancel_hedge_leg(lurl, lkey)
                if result is not None:
                    return result
            # both legs failed: rediscover next time, queued fallback now
            self._direct_cache = None
            self._drop_session_worker(session)
            return None
        finally:
            ex.shutdown(wait=False)


# ---------------------------------------------------------------------------
# Module-level one-shots (reference :380-399)
# ---------------------------------------------------------------------------


def chat(messages=None, prompt=None, server_url="http://127.0.0.1:8000",
         **kw) -> Dict[str, Any]:
    with InferenceClient(server_url) as c:
        return c.chat(messages=messages, prompt=prompt, **kw)


def generate_image(prompt: str, server_url="http://127.0.0.1:8000",
                   **kw) -> Dict[str, Any]:
    with InferenceClient(server_url) as c:
        return c.generate_image(prompt, **kw)


def embed(texts: Sequence[str], server_url="http://127.0.0.1:8000",
          **kw) -> Dict[str, Any]:
    with InferenceClient(server_url) as c:
        return c.embed(texts, **kw)
