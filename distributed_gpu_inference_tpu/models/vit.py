"""ViT image encoder for the vision-language engine.

TPU-native counterpart of the reference's GLM-4V-style VLM backbone
(``worker/engines/vision.py`` loads a HF vision-language checkpoint): here
the VLM is composed first-party — this patch-transformer encodes the image
into ``num_prefix`` soft tokens projected into the Llama decoder's hidden
space, which enter the decoder as a hidden-state prefix through
``llama.forward_hidden_chunk`` (no tokenizer involvement, one jitted graph).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

from distributed_gpu_inference_tpu.models.encoder_common import (
    fan_in_init,
    init_encoder_layers,
    layer_norm,
    patchify,
    run_encoder,
)

Params = Dict[str, Any]


@dataclass(frozen=True)
class ViTConfig:
    name: str = "tiny-vit"
    image_size: int = 32
    channels: int = 3
    patch_size: int = 4
    hidden_size: int = 128
    num_layers: int = 4
    num_heads: int = 4
    out_dim: int = 64            # llama hidden size to project into
    num_prefix: int = 8          # soft tokens handed to the decoder

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels


VIT_REGISTRY: Dict[str, ViTConfig] = {
    "tiny-vit": ViTConfig(),
    "small-vit": ViTConfig(
        name="small-vit", image_size=224, patch_size=16, hidden_size=384,
        num_layers=12, num_heads=6, out_dim=2048, num_prefix=64,
    ),
}


def get_vit_config(name: str) -> ViTConfig:
    if name not in VIT_REGISTRY:
        raise KeyError(f"unknown vit model {name!r}")
    return VIT_REGISTRY[name]


def init_params(cfg: ViTConfig, key: jax.Array, dtype=jnp.float32) -> Params:
    h = cfg.hidden_size
    ks = jax.random.split(key, 5)
    return {
        "patch_proj": fan_in_init(ks[0], (cfg.patch_dim, h), cfg.patch_dim,
                                  dtype),
        "pos_emb": fan_in_init(ks[1], (cfg.num_patches, h), h, dtype),
        "query_emb": fan_in_init(ks[2], (cfg.num_prefix, h), h, dtype),
        "layers": init_encoder_layers(ks[3], cfg.num_layers, h, dtype=dtype),
        "out_norm": jnp.ones((h,), dtype),
        "out_proj": fan_in_init(ks[4], (h, cfg.out_dim), h, dtype),
    }


def encode_image(cfg: ViTConfig, params: Params,
                 images: jax.Array) -> jax.Array:
    """[B, H, W, C] in [0,1] → [B, num_prefix, out_dim] decoder prefix."""
    b = images.shape[0]
    x = patchify(images, cfg.patch_size)
    x = x @ params["patch_proj"] + params["pos_emb"][None]
    if "patch_bias" in params:      # HF ViT imports carry a conv bias
        x = x + params["patch_bias"]
    # perceiver-style: prepend learned queries; after the encoder, only the
    # query positions feed the decoder (fixed prefix length, static shapes)
    q = jnp.broadcast_to(
        params["query_emb"][None], (b,) + params["query_emb"].shape
    )
    x = jnp.concatenate([q, x], axis=1)
    x = run_encoder(x, params["layers"], cfg.num_heads)
    return layer_norm(
        x[:, : cfg.num_prefix], params["out_norm"],
        params.get("out_norm_b"),
    ) @ params["out_proj"]
