"""Compact DiT (diffusion transformer) for text-to-image generation.

TPU-native replacement for the reference's diffusers-pipeline engine
(``worker/engines/image_gen.py`` — StableDiffusionPipeline wrapper): instead
of wrapping a framework, the denoiser is a first-party patch-transformer
(DiT-style, AdaLN-zero conditioning) whose entire DDIM sampling loop runs as
ONE jitted ``lax.fori_loop`` on device — no per-step host round trips, MXU
matmuls throughout, static shapes.

Pixel-space for small geometries (tests/CI); the architecture is
latent-ready (patchify stride = any factor of image_size).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from distributed_gpu_inference_tpu.models.encoder_common import (
    fan_in_init,
    layer_norm,
    mha,
    patchify as _patchify_img,
    unpatchify as _unpatchify_img,
)

Params = Dict[str, Any]


@dataclass(frozen=True)
class DiffusionConfig:
    name: str = "tiny-diffusion"
    image_size: int = 32
    channels: int = 3
    patch_size: int = 4
    hidden_size: int = 128
    num_layers: int = 4
    num_heads: int = 4
    text_vocab: int = 260            # byte tokenizer vocab
    max_text_len: int = 64
    timesteps: int = 1000

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels


DIFFUSION_REGISTRY: Dict[str, DiffusionConfig] = {
    "tiny-diffusion": DiffusionConfig(),
    "small-diffusion": DiffusionConfig(
        name="small-diffusion", image_size=64, patch_size=4,
        hidden_size=384, num_layers=8, num_heads=6,
    ),
}


def get_diffusion_config(name: str) -> DiffusionConfig:
    if name not in DIFFUSION_REGISTRY:
        raise KeyError(
            f"unknown diffusion model {name!r}; known: "
            f"{sorted(DIFFUSION_REGISTRY)}"
        )
    return DIFFUSION_REGISTRY[name]


def init_params(cfg: DiffusionConfig, key: jax.Array,
                dtype=jnp.float32) -> Params:
    h, p = cfg.hidden_size, cfg.patch_dim
    ks = jax.random.split(key, 12)

    def _w(k, shape, fan_in):
        return fan_in_init(k, shape, fan_in, dtype)

    L = cfg.num_layers
    return {
        "patch_proj": _w(ks[0], (p, h), p),
        "pos_emb": _w(ks[1], (cfg.num_patches, h), h),
        "text_emb": _w(ks[2], (cfg.text_vocab, h), h),
        "time_mlp1": _w(ks[3], (h, h * 2), h),
        "time_mlp2": _w(ks[4], (h * 2, h), h * 2),
        "layers": {
            "norm_scale": jnp.ones((L, h), dtype),
            "ada": _w(ks[5], (L, h, h * 6), h),
            "wqkv": _w(ks[6], (L, h, h * 3), h),
            "wo": _w(ks[7], (L, h, h), h),
            "w1": _w(ks[8], (L, h, h * 4), h),
            "w2": _w(ks[9], (L, h * 4, h), h * 4),
        },
        "out_norm": jnp.ones((h,), dtype),
        "out_proj": _w(ks[10], (h, p), h),
    }


def _timestep_embedding(t: jax.Array, dim: int) -> jax.Array:
    """Sinusoidal embedding of diffusion time [B] → [B, dim]."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0) * jnp.arange(half) / half)
    args = t[:, None].astype(jnp.float32) * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def patchify(cfg: DiffusionConfig, img: jax.Array) -> jax.Array:
    """[B, H, W, C] → [B, N, patch_dim]."""
    return _patchify_img(img, cfg.patch_size)


def unpatchify(cfg: DiffusionConfig, x: jax.Array) -> jax.Array:
    return _unpatchify_img(x, cfg.image_size, cfg.patch_size, cfg.channels)


def encode_text(cfg: DiffusionConfig, params: Params,
                token_ids: jax.Array) -> jax.Array:
    """Mean-pooled text embedding [B, T] → [B, H] (pad id 0 masked)."""
    emb = jnp.take(params["text_emb"], token_ids, axis=0)
    mask = (token_ids > 0).astype(emb.dtype)[..., None]
    denom = jnp.maximum(mask.sum(axis=1), 1.0)
    return (emb * mask).sum(axis=1) / denom


def denoise(cfg: DiffusionConfig, params: Params, x_t: jax.Array,
            t: jax.Array, text_cond: jax.Array) -> jax.Array:
    """Predict noise for x_t at time t. x_t [B,H,W,C], t [B], cond [B,Hd]."""
    h = cfg.hidden_size
    x = patchify(cfg, x_t) @ params["patch_proj"] + params["pos_emb"][None]
    temb = _timestep_embedding(t, h)
    c = jax.nn.silu(temb @ params["time_mlp1"]) @ params["time_mlp2"]
    c = c + text_cond                                   # [B, H]

    def block(x, lp):
        # AdaLN-zero: per-layer modulation from the conditioning vector
        mod = (c @ lp["ada"]).reshape(x.shape[0], 1, 6, h)
        (s1, b1, g1, s2, b2, g2) = [mod[:, :, i] for i in range(6)]
        y = layer_norm(x, lp["norm_scale"]) * (1 + s1) + b1
        y = mha(y, lp["wqkv"], lp["wo"], cfg.num_heads)
        x = x + g1 * y
        y = layer_norm(x, lp["norm_scale"]) * (1 + s2) + b2
        y = jax.nn.gelu(y @ lp["w1"]) @ lp["w2"]
        return x + g2 * y, None

    x, _ = lax.scan(block, x, params["layers"])
    x = layer_norm(x, params["out_norm"]) @ params["out_proj"]
    return unpatchify(cfg, x)


def ddim_sample(
    cfg: DiffusionConfig,
    params: Params,
    text_tokens: jax.Array,       # [B, T] int32, 0 = pad
    key: jax.Array,
    num_steps: int = 20,
    guidance_scale: jax.Array | float = 3.0,
) -> jax.Array:
    """Full DDIM sampler as one jitted fori_loop. Returns images in [0, 1].

    Classifier-free guidance batches the conditional and unconditional
    branches into one forward (2B batch) per step — one MXU pass, no
    host syncs until the final image.
    """
    b = text_tokens.shape[0]
    cond = encode_text(cfg, params, text_tokens)
    uncond = encode_text(
        cfg, params, jnp.zeros_like(text_tokens)
    )
    betas = jnp.linspace(1e-4, 0.02, cfg.timesteps)
    alphas_bar = jnp.cumprod(1.0 - betas)
    step_ts = jnp.linspace(cfg.timesteps - 1, 0, num_steps).astype(jnp.int32)

    x = jax.random.normal(
        key, (b, cfg.image_size, cfg.image_size, cfg.channels)
    )

    def body(i, x):
        t = step_ts[i]
        t_next = jnp.where(i + 1 < num_steps, step_ts[i + 1], 0)
        a_t = alphas_bar[t]
        a_next = jnp.where(
            i + 1 < num_steps, alphas_bar[t_next], jnp.float32(1.0)
        )
        tb = jnp.full((2 * b,), t, jnp.int32)
        eps = denoise(
            cfg, params,
            jnp.concatenate([x, x]), tb,
            jnp.concatenate([cond, uncond]),
        )
        eps_c, eps_u = eps[:b], eps[b:]
        eps_g = eps_u + guidance_scale * (eps_c - eps_u)
        x0 = (x - jnp.sqrt(1 - a_t) * eps_g) / jnp.sqrt(a_t)
        x0 = jnp.clip(x0, -1.5, 1.5)
        return jnp.sqrt(a_next) * x0 + jnp.sqrt(1 - a_next) * eps_g

    x = lax.fori_loop(0, num_steps, body, x)
    return jnp.clip(x * 0.5 + 0.5, 0.0, 1.0)


# guidance_scale is traced (plain arithmetic scalar): per-request values
# must NOT recompile the whole sampling loop
sample_jit = jax.jit(ddim_sample, static_argnames=("cfg", "num_steps"))
