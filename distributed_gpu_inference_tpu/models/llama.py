"""Llama-3-class decoder as pure functional JAX over a params pytree.

TPU-native core replacing the reference's engine-wrapped models
(``worker/engines/llm.py`` — HF Transformers generate; ``llm_vllm.py`` /
``llm_sglang.py`` — CUDA serving engines). Design properties:

- **One generic ``forward_chunk``** serves prefill (S = chunk), chunked/long
  prefill (S = chunk with prefix), and decode (S = 1): static shapes, no
  data-dependent Python control flow, jits once per (B, S) bucket.
- **Paged KV is the only cache layout.** K/V live in HBM pools
  ``[L, num_blocks, n_kv_heads, block_size, head_dim]`` addressed through
  per-sequence block tables — the first-party equivalent of vLLM's
  PagedAttention pools the reference delegates to (SURVEY §2.3), written
  via scatter inside the jitted graph.
- **Stacked layer params** (leading L axis) so layers run under ``lax.scan``
  (fast compiles at 80 layers) and shard/pipeline cleanly over a mesh axis.
- Attention math runs through ``ops.attention`` which picks the Pallas paged
  kernel on TPU and a gather-based XLA fallback elsewhere.

Weight-name parity with HF Llama checkpoints is handled in ``models/loader.py``.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from distributed_gpu_inference_tpu.models.configs import ModelConfig
from distributed_gpu_inference_tpu.ops.attention import paged_attention
from distributed_gpu_inference_tpu.ops.quantization import (
    matmul as qmm,
    matmul_stacked,
    split_stacked_quant,
)

Params = Dict[str, Any]
KVPools = Dict[str, jax.Array]  # {"k": [L,N,Hkv,Bk,D], "v": [L,N,Hkv,Bk,D]}


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype: Optional[jnp.dtype] = None
) -> Params:
    """Random-init params with the exact pytree layout the engine shards."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    h, d = cfg.hidden_size, cfg.head_dim
    nh, nkv, i = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size
    L, v = cfg.num_layers, cfg.vocab_size
    keys = jax.random.split(key, 9)

    def _w(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) * (fan_in**-0.5)).astype(
            dtype
        )

    # norm identity depends on the convention: plain RMSNorm scales by w
    # (identity = ones); Gemma's offset form scales by 1+w (identity = zeros)
    norm_init = jnp.zeros if cfg.norm_offset else jnp.ones
    layers: Dict[str, jax.Array] = {
        "attn_norm": norm_init((L, h), dtype),
        "wq": _w(keys[1], (L, h, nh * d), h),
        "wk": _w(keys[2], (L, h, nkv * d), h),
        "wv": _w(keys[3], (L, h, nkv * d), h),
        "wo": _w(keys[4], (L, nh * d, h), nh * d),
        "mlp_norm": norm_init((L, h), dtype),
    }
    if cfg.num_experts:  # Mixtral-style sparse MoE: stacked expert axis E
        E = cfg.num_experts
        ekeys = jax.random.split(keys[5], 3)
        layers["w_router"] = _w(keys[7], (L, h, E), h)
        layers["we_gate"] = _w(ekeys[0], (L, E, h, i), h)
        layers["we_up"] = _w(ekeys[1], (L, E, h, i), h)
        layers["we_down"] = _w(ekeys[2], (L, E, i, h), i)
    else:
        layers["w_gate"] = _w(keys[5], (L, h, i), h)
        layers["w_up"] = _w(keys[6], (L, h, i), h)
        layers["w_down"] = _w(keys[7], (L, i, h), i)
    params: Params = {
        "embedding": _w(keys[0], (v, h), h),
        "layers": layers,
        "final_norm": norm_init((h,), dtype),
    }
    if cfg.attention_bias:  # Qwen2-style QKV biases (random init ~ small)
        bkeys = jax.random.split(keys[1], 3)
        params["layers"]["bq"] = _w(bkeys[0], (L, nh * d), nh * d)
        params["layers"]["bk"] = _w(bkeys[1], (L, nkv * d), nkv * d)
        params["layers"]["bv"] = _w(bkeys[2], (L, nkv * d), nkv * d)
    if not cfg.tie_word_embeddings:
        # distinct key: an untied head must not be bit-identical to the
        # embedding, or head/embedding swap bugs become invisible to tests
        params["lm_head"] = _w(keys[8], (v, h), h)
    return params


def init_kv_pools(
    cfg: ModelConfig,
    num_blocks: int,
    block_size: int = 16,
    dtype: Optional[jnp.dtype] = None,
) -> KVPools:
    """Device-resident paged KV pools. Block 0 is reserved as the garbage/pad
    block — writes for padded tokens land there and reads mask it out.

    Layout ``[L, N, Hkv, Bk, D]`` (head-major pages, like vLLM's pools and
    the reference's CacheBlock [max_blocks, heads, block, head_dim],
    kv_cache.py:130-144): a (page, head) slice is a contiguous [Bk, D] tile,
    which the Pallas decode kernel DMAs without breaking TPU tiling.

    ``dtype=int8``: quantized pools — the dict additionally carries
    ``k_scale``/``v_scale`` ([L, N, Bk, D] bf16, lane-replicated): one
    scale per (page, token) shared across KV heads (real = int * scale;
    contract: ``ops.paged_attention_pallas._quantize_token_rows``)."""
    dtype = jnp.dtype(dtype or cfg.dtype)
    shape = (cfg.num_layers, num_blocks, cfg.num_kv_heads, block_size, cfg.head_dim)
    pools = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if dtype == jnp.int8:
        sshape = (cfg.num_layers, num_blocks, block_size, cfg.head_dim)
        pools["k_scale"] = jnp.zeros(sshape, jnp.bfloat16)
        pools["v_scale"] = jnp.zeros(sshape, jnp.bfloat16)
    return pools


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float, offset: bool = False
) -> jax.Array:
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    w = weight.astype(jnp.float32)
    if offset:  # Gemma stores zero-centered norm weights; scale is (1 + w)
        w = 1.0 + w
    return (x * w).astype(dt)


def _rope_angles(positions: jax.Array, head_dim: int, theta: float) -> Tuple[jax.Array, jax.Array]:
    """positions [..., S] → (cos, sin) each [..., S, head_dim//2], float32."""
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Half-split RoPE (HF Llama ``rotate_half`` convention).

    x: [B, S, H, D]; cos/sin: [B, S, D/2] broadcast over heads.
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[..., None, :]  # [B, S, 1, D/2]
    s = sin[..., None, :]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * c - xf2 * s, xf2 * c + xf1 * s], axis=-1
    ).astype(x.dtype)


def _page_scatter_indices(
    num_blocks: int, block_tables: jax.Array, positions: jax.Array,
    block_size: int,
) -> Tuple[jax.Array, jax.Array]:
    """(flat_phys, flat_slot) for scattering per-token rows into a paged
    pool — THE one copy of the OOB-drop index math, shared by the data and
    scale scatters so they can never desynchronize. Pad writes (position <
    0) map to the OUT-OF-RANGE block ``num_blocks`` and are dropped: -1
    would *wrap* to the last block under jax .at[] semantics (negative
    indices stay in-bounds)."""
    valid = positions >= 0
    safe_pos = jnp.where(valid, positions, 0)
    logical = safe_pos // block_size                       # [B, S]
    slot = safe_pos % block_size                           # [B, S]
    phys = jnp.take_along_axis(block_tables, logical, axis=1)  # [B, S]
    phys = jnp.where(valid, phys, num_blocks)
    return phys.reshape(-1), slot.reshape(-1)


def _write_kv_pages(
    pool: jax.Array,          # [N, Hkv, Bk, D] (single layer)
    new: jax.Array,           # [B, S, Hkv, D]
    block_tables: jax.Array,  # [B, M] int32 physical block ids
    positions: jax.Array,     # [B, S] int32 token positions (-1 = pad)
    block_size: int,
) -> jax.Array:
    """Scatter a chunk of new K or V rows into the paged pool.

    Padded slots (position < 0) scatter out-of-bounds and are dropped.
    """
    b, s = positions.shape
    flat_phys, flat_slot = _page_scatter_indices(
        pool.shape[0], block_tables, positions, block_size
    )
    # pool may store a narrower dtype than the activations (fp8 KV cache)
    flat_new = new.astype(pool.dtype).reshape(b * s, *new.shape[2:])  # [T,Hkv,D]
    # advanced indices (dims 0 and 2) separated by the head slice: result
    # dims order as [T, Hkv, D] — exactly flat_new's layout.
    # no unique_indices: padded rows all collapse to the same OOB index, and
    # promising uniqueness there would be undefined behavior
    return pool.at[flat_phys, :, flat_slot].set(flat_new, mode="drop")


def _write_scale_pages(
    pool: jax.Array,          # [N, Bk, D] bf16 scale pool (single layer)
    new: jax.Array,           # [B, S, D] per-token scale rows (lane-replicated)
    block_tables: jax.Array,  # [B, M]
    positions: jax.Array,     # [B, S] (-1 = pad)
    block_size: int,
) -> jax.Array:
    """Scatter int8-KV scale rows — shares :func:`_page_scatter_indices`
    with the data scatter (same OOB-drop semantics by construction)."""
    b, s = positions.shape
    flat_phys, flat_slot = _page_scatter_indices(
        pool.shape[0], block_tables, positions, block_size
    )
    flat_new = new.astype(pool.dtype).reshape(b * s, new.shape[-1])
    return pool.at[flat_phys, flat_slot].set(flat_new, mode="drop")


def _mlp(x: jax.Array, proj, activation: str = "silu") -> jax.Array:
    act = jax.nn.silu if activation == "silu" else functools.partial(
        jax.nn.gelu, approximate=True  # Gemma GeGLU (gelu_pytorch_tanh)
    )
    gate = act(proj(x, "w_gate"))
    return proj(gate * proj(x, "w_up"), "w_down").astype(x.dtype)


def _moe_mlp(
    x: jax.Array, lp: Dict[str, jax.Array], cfg: ModelConfig
) -> jax.Array:
    """Mixtral-style sparse MoE MLP, expert-parallel by sharding.

    Routing follows HF Mixtral: softmax over all router logits, keep top-k,
    renormalize. The combine is expressed as a dense einsum over the expert
    axis with top-k-masked weights — on a mesh where ``we_*`` shard their E
    axis over ``model``, each chip runs only its local experts for all
    tokens and XLA inserts the combine all-reduce: expert parallelism
    without hand-written all-to-all (the TPU answer to SURVEY §2.2's
    "EP: ABSENT"). Single-chip cost is E/k times the active-path FLOPs —
    acceptable at serving batch sizes; a ragged/blocked Pallas dispatch is
    the designated upgrade path.
    """
    act = jax.nn.silu if cfg.activation == "silu" else functools.partial(
        jax.nn.gelu, approximate=True
    )
    b, s, h = x.shape
    xf = x.reshape(b * s, h)                                   # [T, H]
    # router math in float32: top-k selection is precision-sensitive
    logits = (xf.astype(jnp.float32) @ lp["w_router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                    # [T, E]
    topv, topi = lax.top_k(probs, cfg.num_experts_per_tok)     # [T, k]
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    # scatter the renormalized top-k back to a dense [T, E] combine weight
    weights = jnp.zeros_like(probs).at[
        jnp.arange(xf.shape[0])[:, None], topi
    ].set(topv)                                                # [T, E]

    gate = act(jnp.einsum("th,ehi->tei", xf, _deq(lp["we_gate"], x.dtype)))
    up = jnp.einsum("th,ehi->tei", xf, _deq(lp["we_up"], x.dtype))
    per_expert = jnp.einsum(
        "tei,eih->teh", gate * up, _deq(lp["we_down"], x.dtype)
    )                                                          # [T, E, H]
    out = jnp.einsum(
        "te,teh->th", weights.astype(jnp.float32),
        per_expert.astype(jnp.float32),
    )
    return out.reshape(b, s, h).astype(x.dtype)


def _deq(w: Any, dtype) -> jax.Array:
    """Expert weights [E, in, out] (layer axis consumed by scan), possibly
    quantized: convert-on-read, shaped for the einsum contraction."""
    from distributed_gpu_inference_tpu.ops.quantization import (
        dequantize, is_quantized,
    )

    return dequantize(w, dtype) if is_quantized(w) else w


# ---------------------------------------------------------------------------
# Transformer forward over paged KV
# ---------------------------------------------------------------------------


def _use_fused_decode(
    cfg: ModelConfig, s: int, block_tables: jax.Array, block_size: int
) -> bool:
    """Trace-time choice of the fused Pallas write+attention decode path
    (same dispatch facts as ops.attention.resolve_impl)."""
    from distributed_gpu_inference_tpu.ops.attention import resolve_impl

    return s == 1 and resolve_impl(
        q_seq=s,
        head_dim=cfg.head_dim,
        padded_ctx=block_tables.shape[1] * block_size,
    ) == "pallas"


class ChunkOutput(NamedTuple):
    hidden: jax.Array       # [B, S, H] final-layer hidden states (pre-norm)
    kv: KVPools             # updated pools
    logits: Optional[jax.Array]  # [B, S, V] ([B, 1, V] if last_only; None if
                                 # with_logits=False — intermediate chunks)
    # [B, S, k*H] concat of the requested layers' post-layer hiddens
    # (collect_layers; EAGLE-3-style multi-layer draft features) — None
    # unless asked for: stacking every layer's hidden is layer-count x the
    # activation memory, so only small spec/distill shapes request it
    features: Optional[jax.Array] = None


def _layer_step(
    cfg: ModelConfig,
    block_size: int,
    carry: Tuple[jax.Array, jax.Array, jax.Array, jax.Array],
    lp: Dict[str, jax.Array],
    *,
    block_tables: jax.Array,
    write_positions: jax.Array,   # where this chunk's KV lands (-1 = drop)
    cos: jax.Array,
    sin: jax.Array,
    attn_fn,                      # (q, layer_k, layer_v) -> attention output
    fused_decode: bool = False,   # S=1 TPU path: one kernel writes + attends
    kv_lens: Optional[jax.Array] = None,  # required when fused_decode
    stacked: Optional[Dict[str, Any]] = None,  # quantized weights kept whole
    dense_attn_fn=None,           # (q, k, v dense chunk) → attn; see below
    emit_hidden: bool = False,    # scan-emit this layer's hidden (features)
) -> Tuple[Tuple[jax.Array, jax.Array, jax.Array, jax.Array], Optional[jax.Array]]:
    """One transformer layer over paged KV — shared by the causal decode path
    and the speculative tree-verify path (they differ only in the attention
    mask and in where KV rows are written).

    ``fused_decode`` routes the whole KV path through the Pallas fused
    write+attention kernel on the STACKED pools (ops/paged_attention_pallas).
    The alternative — XLA scatter into a dynamically-indexed layer slice —
    forced two pool-sized HBM copies per decode step at serving pool sizes
    (scatter-preferred vs kernel-required layout, plus custom-call operand
    materialization; round-2 profiling).

    ``stacked`` holds quantized matmul weights with their layer axis intact
    (``split_stacked_quant``): projections then run through the Pallas
    VMEM-dequant kernel addressed by ``layer_idx``, so no per-layer weight
    slice is ever materialized for the custom call.

    ``dense_attn_fn`` routes attention over this chunk's DENSE K/V instead
    of the paged pools — valid exactly when the chunk IS the whole context
    (a from-scratch prefill with no cached prefix). This is the
    sequence-parallel entry: the engine passes ring/Ulysses attention
    (``parallel/ring_attention.py``) here so a long prompt's attention
    spreads over the ``seq`` mesh axis while KV pages still land in the
    same paged pools decode reads (SURVEY §5.7)."""
    hidden, k_ent, v_ent, layer_idx = carry
    # int8-KV pools travel as (pool, scale_pool) tuples through the scan
    # carry; bf16 pools stay bare arrays (static structure, zero overhead)
    quant_kv = isinstance(k_ent, tuple)
    if quant_kv:
        k_pool, k_scale_pool = k_ent
        v_pool, v_scale_pool = v_ent
    else:
        k_pool, v_pool = k_ent, v_ent
        k_scale_pool = v_scale_pool = None
    b, s, _ = hidden.shape
    nh, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    def proj(x_, name):
        if stacked is not None and name in stacked:
            return matmul_stacked(x_, stacked[name], layer_idx)
        return qmm(x_, lp[name])

    x = rms_norm(hidden, lp["attn_norm"], cfg.rms_norm_eps, cfg.norm_offset)
    q = proj(x, "wq")
    k = proj(x, "wk")
    v = proj(x, "wv")
    if "bq" in lp:  # Qwen2-style attention biases (static at trace time)
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(b, s, nh, d)
    k = k.reshape(b, s, nkv, d)
    v = v.reshape(b, s, nkv, d)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if fused_decode:
        from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
            paged_decode_attention_fused,
        )

        if quant_kv:
            # the kernel quantizes the new rows in place (shared contract)
            attn, k_pool, v_pool, k_scale_pool, v_scale_pool = \
                paged_decode_attention_fused(
                    q, k.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
                    k_pool, v_pool, layer_idx, block_tables,
                    write_positions, kv_lens, block_size,
                    window=cfg.sliding_window,
                    k_scale=k_scale_pool, v_scale=v_scale_pool,
                )
        else:
            attn, k_pool, v_pool = paged_decode_attention_fused(
                q, k.astype(k_pool.dtype), v.astype(v_pool.dtype),
                k_pool, v_pool, layer_idx, block_tables,
                write_positions, kv_lens, block_size,
                window=cfg.sliding_window,
            )
    else:
        layer_k = lax.dynamic_index_in_dim(k_pool, layer_idx, 0, keepdims=False)
        layer_v = lax.dynamic_index_in_dim(v_pool, layer_idx, 0, keepdims=False)
        layer_ks = layer_vs = None
        if quant_kv:
            from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
                _quantize_token_rows,
            )

            # per-token quantize over (Hkv, D), scale rows lane-replicated
            k_q, k_s = _quantize_token_rows(k.astype(jnp.float32), (2, 3))
            v_q, v_s = _quantize_token_rows(v.astype(jnp.float32), (2, 3))
            layer_ks = lax.dynamic_index_in_dim(
                k_scale_pool, layer_idx, 0, keepdims=False)
            layer_vs = lax.dynamic_index_in_dim(
                v_scale_pool, layer_idx, 0, keepdims=False)
            layer_k = _write_kv_pages(
                layer_k, k_q, block_tables, write_positions, block_size)
            layer_v = _write_kv_pages(
                layer_v, v_q, block_tables, write_positions, block_size)
            layer_ks = _write_scale_pages(
                layer_ks, jnp.broadcast_to(k_s[:, :, 0, :], (b, s, d)),
                block_tables, write_positions, block_size)
            layer_vs = _write_scale_pages(
                layer_vs, jnp.broadcast_to(v_s[:, :, 0, :], (b, s, d)),
                block_tables, write_positions, block_size)
            k_scale_pool = lax.dynamic_update_index_in_dim(
                k_scale_pool, layer_ks, layer_idx, 0)
            v_scale_pool = lax.dynamic_update_index_in_dim(
                v_scale_pool, layer_vs, layer_idx, 0)
        else:
            layer_k = _write_kv_pages(layer_k, k, block_tables, write_positions, block_size)
            layer_v = _write_kv_pages(layer_v, v, block_tables, write_positions, block_size)
        k_pool = lax.dynamic_update_index_in_dim(k_pool, layer_k, layer_idx, 0)
        v_pool = lax.dynamic_update_index_in_dim(v_pool, layer_v, layer_idx, 0)
        if dense_attn_fn is not None:
            # pages written above for decode; attention itself runs over the
            # chunk's dense K/V (== whole context for a from-scratch prefill)
            if quant_kv:
                # int8 pools: attend over the quantize→dequantize roundtrip
                # of the chunk's K/V (THE shared dequant arithmetic) so a
                # dense seq-sharded prefill matches a single-chip engine's
                # paged-read prefill
                from distributed_gpu_inference_tpu.ops.attention import (
                    dequantize_kv,
                )

                attn = dense_attn_fn(
                    q, dequantize_kv(k_q, k_s), dequantize_kv(v_q, v_s)
                )
            else:
                attn = dense_attn_fn(q, k, v)
        elif quant_kv:
            attn = attn_fn(q, layer_k, layer_v, layer_ks, layer_vs)
        else:
            attn = attn_fn(q, layer_k, layer_v)

    hidden = hidden + proj(attn.reshape(b, s, nh * d), "wo").astype(hidden.dtype)
    mlp_in = rms_norm(hidden, lp["mlp_norm"], cfg.rms_norm_eps, cfg.norm_offset)
    if "w_router" in lp:
        hidden = hidden + _moe_mlp(mlp_in, lp, cfg)
    else:
        hidden = hidden + _mlp(mlp_in, proj, cfg.activation)
    k_out = (k_pool, k_scale_pool) if quant_kv else k_pool
    v_out = (v_pool, v_scale_pool) if quant_kv else v_pool
    return (hidden, k_out, v_out, layer_idx + 1), (
        hidden if emit_hidden else None
    )


def forward_chunk(
    cfg: ModelConfig,
    params: Params,
    token_ids: jax.Array,      # [B, S] int32 (pad = any id at position -1)
    positions: jax.Array,      # [B, S] int32, -1 marks padding
    kv: KVPools,
    block_tables: jax.Array,   # [B, M] int32 physical block ids
    kv_lens: jax.Array,        # [B] int32 total valid context AFTER this chunk
    *,
    block_size: int = 16,
    last_only: bool = True,
    with_logits: bool = True,
    dense_attn_fn=None,
    attn_override=None,   # (q, layer_k, layer_v, tables, positions,
                          # kv_lens, layer_ks, layer_vs) — replaces the
                          # paged-attention read (e.g. the seq-sharded-pool
                          # shard_map op); disables the fused Pallas path.
                          # layer_ks/layer_vs are the layer's scale-pool
                          # slices (int8 pools) or None
    collect_layers: Optional[Tuple[int, ...]] = None,
                          # also return ChunkOutput.features = concat of
                          # these layers' post-layer hiddens (EAGLE-3 draft
                          # features) — costs L x hidden activation memory,
                          # request only on small spec/distill shapes
    allow_fused: bool = True,
                          # gate for the fused Pallas decode path: an
                          # engine serving over a GSPMD mesh must pass
                          # False — a pallas_call has no partitioning
                          # rules, and the kernel's in-VMEM per-token
                          # quantize amax (int8 pools) would reduce over
                          # LOCAL heads only, breaking the all-reduce-max
                          # scale contract (parallel/sharding.py)
) -> ChunkOutput:
    """Run S tokens per sequence through all layers against the paged cache.

    Covers prefill (S = prompt chunk, positions start at the cached prefix
    length) and decode (S = 1) with one traced graph per (B, S).

    ``with_logits=False`` skips the LM-head projection entirely — an
    intermediate chunk of a long prefill only needs its KV side effects, and
    the head matmul reads the full [V, H] embedding from HBM (0.77 GB on
    Llama-3 vocab) for logits nobody consumes.
    """
    b, s = token_ids.shape
    hidden = embed_tokens(params, token_ids, cfg)

    safe_pos = jnp.maximum(positions, 0)
    cos, sin = _rope_angles(safe_pos, cfg.head_dim, cfg.rope_theta)

    quant_kv = "k_scale" in kv
    if attn_override is not None:
        # int8 pools: the override receives the layer's scale pools too —
        # the seq-sharded shard_map ops dequantize their local page shards
        # (scales ride the same block axis; parallel/ring_attention.py)
        def attn_fn(q, layer_k, layer_v, layer_ks=None, layer_vs=None):
            return attn_override(
                q, layer_k, layer_v, block_tables, positions, kv_lens,
                layer_ks, layer_vs,
            )
    else:
        def attn_fn(q, layer_k, layer_v, layer_ks=None, layer_vs=None):
            return paged_attention(
                q, layer_k, layer_v, block_tables, positions, kv_lens,
                block_size, window=cfg.sliding_window,
                k_scale=layer_ks, v_scale=layer_vs,
            )

    scanned, stacked = split_stacked_quant(params["layers"])
    step = functools.partial(
        _layer_step,
        cfg,
        block_size,
        block_tables=block_tables,
        write_positions=positions,
        cos=cos,
        sin=sin,
        attn_fn=attn_fn,
        fused_decode=(
            allow_fused
            and _use_fused_decode(cfg, s, block_tables, block_size)
            and dense_attn_fn is None
            and attn_override is None
        ),
        kv_lens=kv_lens,
        stacked=stacked,
        dense_attn_fn=dense_attn_fn,
        emit_hidden=collect_layers is not None,
    )
    k0 = (kv["k"], kv["k_scale"]) if quant_kv else kv["k"]
    v0 = (kv["v"], kv["v_scale"]) if quant_kv else kv["v"]
    (hidden, k_out, v_out, _), layer_hs = lax.scan(
        lambda c, lp: step(c, lp),
        (hidden, k0, v0, jnp.int32(0)),
        scanned,
    )
    new_kv = (
        {"k": k_out[0], "v": v_out[0],
         "k_scale": k_out[1], "v_scale": v_out[1]}
        if quant_kv else {"k": k_out, "v": v_out}
    )
    features = (
        jnp.concatenate([layer_hs[i] for i in collect_layers], axis=-1)
        if collect_layers is not None else None
    )

    if not with_logits:
        return ChunkOutput(
            hidden=hidden, kv=new_kv, logits=None,
            features=features,
        )
    if last_only:
        # last valid token per sequence = kv_lens - 1 mapped into the chunk:
        # chunk covers positions [kv_len - n_valid, kv_len); the last valid
        # chunk index is (number of valid positions in chunk) - 1.
        n_valid = jnp.sum((positions >= 0).astype(jnp.int32), axis=1)  # [B]
        last_idx = jnp.maximum(n_valid - 1, 0)
        logits_in = jnp.take_along_axis(
            hidden, last_idx[:, None, None].astype(jnp.int32), axis=1
        )  # [B, 1, H]
    else:
        logits_in = hidden
    logits = project_logits(cfg, params, logits_in)
    return ChunkOutput(hidden=hidden, kv=new_kv,
                       logits=logits, features=features)


def forward_tree_chunk(
    cfg: ModelConfig,
    params: Params,
    token_ids: jax.Array,       # [B, N] tree-node tokens
    rope_positions: jax.Array,  # [B, N] semantic positions (prefix + depth)
    cache_positions: jax.Array, # [B, N] KV slot positions (prefix + node idx)
    kv: KVPools,
    block_tables: jax.Array,    # [B, M]
    prefix_lens: jax.Array,     # [B] committed context before the tree
    tree_mask: jax.Array,       # [N, N] ancestor-visibility mask
    *,
    block_size: int = 16,
    collect_layers: Optional[Tuple[int, ...]] = None,
) -> ChunkOutput:
    """Target forward over a speculative token tree (the verify pass).

    RoPE uses semantic depth positions; KV pages are written at distinct
    node-indexed slots so sibling nodes don't collide. After acceptance the
    engine compacts the winning path's pages (see
    ``runtime/speculative.py``). Reference analogue:
    ``worker/engines/speculative.py:419-453`` _verify_candidates.

    Composes with sliding-window models (the tree-attention mask windows
    prefix AND within-chunk keys by semantic node position — round 8
    deleted the depth-vs-window guard) and with int8 KV pools: node KV
    quantizes through the shared per-token contract on write and the
    verify read dequantizes context-sized via ``ops.attention
    .dequantize_kv`` — the same arithmetic every other int8 reader uses,
    so tree verification over int8 pools is bit-identical to a
    dequantized oracle.
    """
    from distributed_gpu_inference_tpu.ops.attention import paged_tree_attention

    hidden = embed_tokens(params, token_ids, cfg)
    cos, sin = _rope_angles(
        jnp.maximum(rope_positions, 0), cfg.head_dim, cfg.rope_theta
    )

    def attn_fn(q, layer_k, layer_v, layer_ks=None, layer_vs=None):
        return paged_tree_attention(
            q, layer_k, layer_v, block_tables, prefix_lens, tree_mask,
            block_size, node_positions=rope_positions,
            window=cfg.sliding_window,
            k_scale=layer_ks, v_scale=layer_vs,
        )

    quant_kv = "k_scale" in kv
    scanned, stacked = split_stacked_quant(params["layers"])
    step = functools.partial(
        _layer_step,
        cfg,
        block_size,
        block_tables=block_tables,
        write_positions=cache_positions,
        cos=cos,
        sin=sin,
        attn_fn=attn_fn,
        stacked=stacked,
        emit_hidden=collect_layers is not None,
    )
    k0 = (kv["k"], kv["k_scale"]) if quant_kv else kv["k"]
    v0 = (kv["v"], kv["v_scale"]) if quant_kv else kv["v"]
    (hidden, k_out, v_out, _), layer_hs = lax.scan(
        lambda c, lp: step(c, lp), (hidden, k0, v0, jnp.int32(0)),
        scanned,
    )
    new_kv = (
        {"k": k_out[0], "v": v_out[0],
         "k_scale": k_out[1], "v_scale": v_out[1]}
        if quant_kv else {"k": k_out, "v": v_out}
    )
    features = (
        jnp.concatenate([layer_hs[i] for i in collect_layers], axis=-1)
        if collect_layers is not None else None
    )
    logits = project_logits(cfg, params, hidden)
    return ChunkOutput(hidden=hidden, kv=new_kv,
                       logits=logits, features=features)


def forward_hidden_chunk(
    cfg: ModelConfig,
    params: Params,
    hidden: jax.Array,
    positions: jax.Array,
    kv: KVPools,
    block_tables: jax.Array,
    kv_lens: jax.Array,
    *,
    block_size: int = 16,
    layer_offset: int = 0,
) -> Tuple[jax.Array, KVPools]:
    """Forward pre-embedded hidden states through this shard's layers.

    The pipeline-parallel entry point: a stage that owns layers [a, b) calls
    this on activations received from the previous stage (reference analogue:
    ``worker/distributed/model_shard.py:173-228`` ModelShard.forward).
    ``params['layers']`` holds only the owned layers; ``kv`` likewise.
    int8 KV pools are fenced (stage pools are bf16/f32 today; a bare-array
    scan carry would silently truncate rows into the int8 pool).
    """
    if "k_scale" in kv:
        raise NotImplementedError(
            "forward_hidden_chunk over int8 KV pools is not wired"
        )
    safe_pos = jnp.maximum(positions, 0)
    cos, sin = _rope_angles(safe_pos, cfg.head_dim, cfg.rope_theta)

    def attn_fn(q, layer_k, layer_v):
        return paged_attention(
            q, layer_k, layer_v, block_tables, positions, kv_lens, block_size,
            window=cfg.sliding_window,
        )

    scanned, stacked = split_stacked_quant(params["layers"])
    step = functools.partial(
        _layer_step,
        cfg,
        block_size,
        block_tables=block_tables,
        write_positions=positions,
        cos=cos,
        sin=sin,
        attn_fn=attn_fn,
        fused_decode=_use_fused_decode(
            cfg, hidden.shape[1], block_tables, block_size
        ),
        kv_lens=kv_lens,
        stacked=stacked,
    )
    (hidden, k_pool, v_pool, _), _ = lax.scan(
        lambda c, lp: step(c, lp),
        (hidden, kv["k"], kv["v"], jnp.int32(0)),
        scanned,
    )
    return hidden, {"k": k_pool, "v": v_pool}


def embed_tokens(
    params: Params, token_ids: jax.Array, cfg: ModelConfig
) -> jax.Array:
    """First pipeline stage: token embedding (reference model_shard.py:163-166).
    Gemma scales embeddings by sqrt(hidden_size) — cfg is REQUIRED so no call
    site can silently skip the scaling convention."""
    hidden = jnp.take(params["embedding"], token_ids, axis=0)
    if cfg.scale_embeddings:
        hidden = hidden * jnp.asarray(
            cfg.hidden_size**0.5, dtype=hidden.dtype
        )
    return hidden


def project_logits(cfg: ModelConfig, params: Params, hidden: jax.Array) -> jax.Array:
    """Last pipeline stage: final norm + LM head (reference model_shard.py:168-171,
    get_logits:230-246)."""
    normed = rms_norm(hidden, params["final_norm"], cfg.rms_norm_eps,
                      cfg.norm_offset)
    # NOT dict.get(k, default): the default would be evaluated eagerly and
    # KeyError on a last pipeline stage that carries lm_head but no embedding
    head = params["lm_head"] if "lm_head" in params else params["embedding"]
    logits = jnp.einsum(
        "bsh,vh->bsv", normed.astype(jnp.float32), head.astype(jnp.float32)
    )
    if cfg.final_logit_softcap is not None:  # Gemma-2 style soft capping
        cap = cfg.final_logit_softcap
        logits = cap * jnp.tanh(logits / cap)
    return logits
