"""Model geometry registry (Llama-3-class decoder-only transformers).

The reference selects models by HF name and lets vLLM/SGLang introspect the
config (``worker/engines/llm_vllm.py:42``); here geometry is explicit because
the shard planner, KV pool sizing, and mesh sharding rules all consume it
(reference analogue: ``worker/distributed/model_shard.py:273-311``
``analyze_model`` reconstructs exactly these numbers from an HF config).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    intermediate_size: int
    head_dim: Optional[int] = None           # default hidden_size // num_heads
    max_position_embeddings: int = 8192
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    attention_bias: bool = False             # Qwen2-style QKV biases
    sliding_window: Optional[int] = None     # Mistral-style windowed attention
    # Gemma-family knobs
    activation: str = "silu"                 # silu | gelu (GeGLU MLP)
    scale_embeddings: bool = False           # hidden *= sqrt(hidden_size)
    norm_offset: bool = False                # RMSNorm uses (1 + weight)
    final_logit_softcap: Optional[float] = None  # cap*tanh(logits/cap)
    # MoE (Mixtral-style sparse MLP); 0 experts = dense
    num_experts: int = 0
    num_experts_per_tok: int = 2
    dtype: str = "bfloat16"

    def __post_init__(self) -> None:
        if self.head_dim is None:
            object.__setattr__(self, "head_dim", self.hidden_size // self.num_heads)
        if self.num_heads % self.num_kv_heads != 0:
            raise ValueError("num_heads must be divisible by num_kv_heads (GQA)")
        if self.activation not in ("silu", "gelu"):
            raise ValueError(
                f"unknown activation {self.activation!r}; use 'silu' or 'gelu'"
            )
        if self.num_experts and self.num_experts_per_tok > self.num_experts:
            raise ValueError("num_experts_per_tok exceeds num_experts")

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    @property
    def num_params(self) -> int:
        """Approximate parameter count (embeddings + layers + head)."""
        h, i, v = self.hidden_size, self.intermediate_size, self.vocab_size
        d = self.head_dim
        attn = h * (self.num_heads * d) + 2 * h * (self.num_kv_heads * d) + (
            self.num_heads * d
        ) * h
        if self.num_experts:
            mlp = self.num_experts * 3 * h * i + h * self.num_experts
        else:
            mlp = 3 * h * i
        norms = 2 * h
        per_layer = attn + mlp + norms
        emb = v * h
        head = 0 if self.tie_word_embeddings else v * h
        return emb + self.num_layers * per_layer + head + h

    def param_bytes(self, dtype_bytes: int = 2) -> int:
        return self.num_params * dtype_bytes

    def kv_bytes_per_token(self, dtype_bytes: int = 2) -> int:
        return 2 * self.num_layers * self.num_kv_heads * self.head_dim * dtype_bytes

    def layer_param_bytes(self, dtype_bytes: int = 2) -> int:
        """Per-layer weight bytes — the shard planner's unit of placement."""
        h, i, d = self.hidden_size, self.intermediate_size, self.head_dim
        attn = h * (self.num_heads * d) + 2 * h * (self.num_kv_heads * d) + (
            self.num_heads * d
        ) * h
        if self.num_experts:
            mlp = self.num_experts * 3 * h * i + h * self.num_experts
        else:
            mlp = 3 * h * i
        return (attn + mlp + 2 * h) * dtype_bytes


def _llama(name: str, **kw) -> ModelConfig:
    return ModelConfig(name=name, **kw)


MODEL_REGISTRY: Dict[str, ModelConfig] = {
    # test-scale
    "llama3-tiny": _llama(
        "llama3-tiny", vocab_size=512, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, intermediate_size=128,
        max_position_embeddings=1024, rope_theta=10000.0,
    ),
    "llama3-mini": _llama(  # CI-scale but realistic ratios
        "llama3-mini", vocab_size=2048, hidden_size=256, num_layers=4,
        num_heads=8, num_kv_heads=4, intermediate_size=640,
        max_position_embeddings=2048,
    ),
    # Llama 3.2 1B geometry — fits single v5e chip in bf16 with room for KV
    "llama3-1b": _llama(
        "llama3-1b", vocab_size=128256, hidden_size=2048, num_layers=16,
        num_heads=32, num_kv_heads=8, intermediate_size=8192,
        head_dim=64, tie_word_embeddings=True,
        max_position_embeddings=131072,
    ),
    # llama3-1b body with a bench-sized vocab: the speculative harness must
    # TRAIN its target for real accept rates (benchmarks/speculative.py),
    # and f32 training with a 128k-vocab logits tensor kernel-faults the
    # tunneled chip (observed rounds 2-3, llama3-1b AND qwen2.5-0.5b).
    # Same per-token transformer compute as llama3-1b; only the LM head
    # shrinks. num_params ~1.0B.
    "llama3-1b-bench": _llama(
        "llama3-1b-bench", vocab_size=8192, hidden_size=2048, num_layers=16,
        num_heads=16, num_kv_heads=8, intermediate_size=8192,
        head_dim=128, tie_word_embeddings=True,
        max_position_embeddings=8192,
    ),
    # ~200M sibling: the largest scale the tunnel chip trains without
    # kernel-faulting (1B-bench, llama3-1b, and qwen2.5-0.5b all crash the
    # TPU worker process during f32 training) — the biggest TRAINED
    # speculative-decoding measurement point available in this environment
    "llama3-200m-bench": _llama(
        "llama3-200m-bench", vocab_size=8192, hidden_size=1024,
        num_layers=12, num_heads=8, num_kv_heads=4, intermediate_size=4096,
        head_dim=128, tie_word_embeddings=True,
        max_position_embeddings=8192,
    ),
    # untied sibling: round-3 probes showed EAGLE-head distillation
    # acceptance collapses on TIED-embedding targets specifically (the
    # draft must hit embedding rows rather than a trained discriminative
    # head) — this variant isolates the serving-stack speedup from that
    # draft-modeling limitation at 200M scale
    "llama3-200m-bench-untied": _llama(
        "llama3-200m-bench-untied", vocab_size=8192, hidden_size=1024,
        num_layers=12, num_heads=8, num_kv_heads=4, intermediate_size=4096,
        head_dim=128, tie_word_embeddings=False,
        max_position_embeddings=8192,
    ),
    # Llama 3.2 3B geometry
    "llama3-3b": _llama(
        "llama3-3b", vocab_size=128256, hidden_size=3072, num_layers=28,
        num_heads=24, num_kv_heads=8, intermediate_size=8192,
        head_dim=128, tie_word_embeddings=True,
        max_position_embeddings=131072,
    ),
    # Llama 3 8B geometry (BASELINE.json config 1-3)
    "llama3-8b": _llama(
        "llama3-8b", vocab_size=128256, hidden_size=4096, num_layers=32,
        num_heads=32, num_kv_heads=8, intermediate_size=14336,
        max_position_embeddings=8192,
    ),
    # Llama 3 70B geometry (BASELINE.json config 4-5)
    "llama3-70b": _llama(
        "llama3-70b", vocab_size=128256, hidden_size=8192, num_layers=80,
        num_heads=64, num_kv_heads=8, intermediate_size=28672,
        max_position_embeddings=8192,
    ),
    # 70B PIPELINE-SCHEDULE geometry for the 8-device virtual-mesh dryrun
    # (benchmarks/distributed.py --mode spmd, BENCH_NOTES_r04): true per-
    # layer width (hidden 8192, GQA 64/8, intermediate 28672 — the shapes
    # every ppermute hop and per-stage matmul see) with 8 layers (1 per
    # stage) and a cut vocab so the f32 host tree stays ~27 GB. The CHIP
    # slice measurement uses the full llama3-70b config with num_layers
    # overridden (benchmarks/pipeline_70b.py).
    "llama3-70b-micro": _llama(
        "llama3-70b-micro", vocab_size=2048, hidden_size=8192, num_layers=8,
        num_heads=64, num_kv_heads=8, intermediate_size=28672,
        max_position_embeddings=8192,
    ),
    # Qwen2.5 family (the reference's single-worker benchmark default is
    # Qwen2.5-7B, benchmarks/single_worker.py:446) — same decoder recipe
    # with QKV biases and 1e6 rope theta
    "qwen2.5-tiny": _llama(  # test-scale
        "qwen2.5-tiny", vocab_size=512, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, intermediate_size=128,
        max_position_embeddings=1024, rope_theta=10000.0,
        attention_bias=True, tie_word_embeddings=True,
    ),
    "qwen2.5-0.5b": _llama(
        "qwen2.5-0.5b", vocab_size=151936, hidden_size=896, num_layers=24,
        num_heads=14, num_kv_heads=2, intermediate_size=4864,
        max_position_embeddings=32768, rope_theta=1000000.0,
        rms_norm_eps=1e-6, attention_bias=True, tie_word_embeddings=True,
    ),
    "qwen2.5-7b": _llama(
        "qwen2.5-7b", vocab_size=152064, hidden_size=3584, num_layers=28,
        num_heads=28, num_kv_heads=4, intermediate_size=18944,
        max_position_embeddings=32768, rope_theta=1000000.0,
        rms_norm_eps=1e-6, attention_bias=True,
    ),
    # Mistral family — Llama decoder recipe + sliding-window attention.
    # The reference serves Mistral through vLLM/SGLang model auto-detection
    # (worker/engines/llm_vllm.py:42 introspects the HF config); here the
    # window is first-class in the paged attention mask (ops/attention.py).
    "mistral-tiny": _llama(  # test-scale; window smaller than the test
        "mistral-tiny", vocab_size=512, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, intermediate_size=128,
        max_position_embeddings=1024, rope_theta=10000.0,
        rms_norm_eps=1e-5, sliding_window=8,
    ),
    "mistral-7b": _llama(  # v0.1 geometry: 4096-token sliding window
        "mistral-7b", vocab_size=32000, hidden_size=4096, num_layers=32,
        num_heads=32, num_kv_heads=8, intermediate_size=14336,
        max_position_embeddings=32768, rope_theta=10000.0,
        rms_norm_eps=1e-5, sliding_window=4096,
    ),
    # Gemma family — GeGLU MLP, sqrt(H)-scaled embeddings, (1+w) RMSNorm,
    # tied embeddings, 256-dim heads. Served by the reference through
    # vLLM/SGLang auto-detection; first-class decoder variant here.
    "gemma-tiny": _llama(  # test-scale
        "gemma-tiny", vocab_size=512, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, intermediate_size=256,
        max_position_embeddings=1024, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, activation="gelu", scale_embeddings=True,
        norm_offset=True, final_logit_softcap=30.0,
    ),
    "gemma-2b": _llama(  # MQA: one KV head
        "gemma-2b", vocab_size=256000, hidden_size=2048, num_layers=18,
        num_heads=8, num_kv_heads=1, intermediate_size=16384, head_dim=256,
        max_position_embeddings=8192, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, activation="gelu", scale_embeddings=True,
        norm_offset=True,
    ),
    "gemma-7b": _llama(
        "gemma-7b", vocab_size=256000, hidden_size=3072, num_layers=28,
        num_heads=16, num_kv_heads=16, intermediate_size=24576, head_dim=256,
        max_position_embeddings=8192, rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=True, activation="gelu", scale_embeddings=True,
        norm_offset=True,
    ),
    # Mixtral family — sparse MoE MLP (top-2 of E experts). The reference's
    # scope lists EP as absent/optional (SURVEY §2.2); on TPU the expert
    # axis shards over the mesh's ``model`` axis, so this is the EP design
    # the reference never had.
    "mixtral-tiny": _llama(  # test-scale: 4 experts, top-2
        "mixtral-tiny", vocab_size=512, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, intermediate_size=128,
        max_position_embeddings=1024, rope_theta=10000.0,
        num_experts=4, num_experts_per_tok=2,
    ),
    "mixtral-8x7b": _llama(
        "mixtral-8x7b", vocab_size=32000, hidden_size=4096, num_layers=32,
        num_heads=32, num_kv_heads=8, intermediate_size=14336,
        max_position_embeddings=32768, rope_theta=1000000.0,
        rms_norm_eps=1e-5, num_experts=8, num_experts_per_tok=2,
    ),
}


def get_model_config(name: str, **overrides) -> ModelConfig:
    if name not in MODEL_REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; known: {sorted(MODEL_REGISTRY)}"
        )
    cfg = MODEL_REGISTRY[name]
    return replace(cfg, **overrides) if overrides else cfg
