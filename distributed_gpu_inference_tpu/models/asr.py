"""ASR model: log-mel frontend + conformer-lite encoder + CTC head.

TPU-native counterpart of the reference's whisper task type (job family in
``worker/engines/__init__.py``; the reference delegates to a backend). The
architecture here is encoder+CTC rather than Whisper's encoder-decoder:
fixed-length audio → fixed-shape mel → one jitted encoder pass → greedy CTC
collapse, which keeps the entire transcription path to a single device call
with static shapes (no autoregressive loop, no KV cache — the right
trade for TPU serving of short utterances).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from distributed_gpu_inference_tpu.models.encoder_common import (
    fan_in_init,
    init_encoder_layers,
    layer_norm,
    run_encoder,
)

Params = Dict[str, Any]

CTC_BLANK = 0


@dataclass(frozen=True)
class ASRConfig:
    name: str = "tiny-whisper"
    sample_rate: int = 16000
    n_fft: int = 400
    hop: int = 160
    n_mels: int = 40
    max_seconds: float = 4.0
    hidden_size: int = 96
    num_layers: int = 4
    num_heads: int = 4
    vocab_size: int = 260          # byte tokenizer vocab (blank = 0)
    conv_stride: int = 4           # time downsampling before the encoder

    @property
    def max_samples(self) -> int:
        return int(self.sample_rate * self.max_seconds)

    @property
    def num_frames(self) -> int:
        return self.max_samples // self.hop

    @property
    def enc_frames(self) -> int:
        return self.num_frames // self.conv_stride


ASR_REGISTRY: Dict[str, ASRConfig] = {
    "tiny-whisper": ASRConfig(),
    "small-whisper": ASRConfig(
        name="small-whisper", max_seconds=30.0, n_mels=80,
        hidden_size=384, num_layers=12, num_heads=6,
    ),
}


def get_asr_config(name: str) -> ASRConfig:
    if name not in ASR_REGISTRY:
        raise KeyError(f"unknown asr model {name!r}")
    return ASR_REGISTRY[name]


# ---------------------------------------------------------------------------
# mel frontend (host-side numpy: tiny cost, keeps the jitted graph static)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=8)
def _mel_filterbank(cfg: ASRConfig) -> np.ndarray:
    n_bins = cfg.n_fft // 2 + 1
    f_max = cfg.sample_rate / 2

    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    mels = np.linspace(0.0, hz_to_mel(f_max), cfg.n_mels + 2)
    freqs = mel_to_hz(mels)
    bins = np.floor((cfg.n_fft + 1) * freqs / cfg.sample_rate).astype(int)
    fb = np.zeros((cfg.n_mels, n_bins), np.float32)
    for m in range(1, cfg.n_mels + 1):
        lo, ctr, hi = bins[m - 1], bins[m], bins[m + 1]
        for k in range(lo, ctr):
            if ctr > lo:
                fb[m - 1, k] = (k - lo) / (ctr - lo)
        for k in range(ctr, hi):
            if hi > ctr:
                fb[m - 1, k] = (hi - k) / (hi - ctr)
    return fb


def log_mel(cfg: ASRConfig, audio: np.ndarray) -> np.ndarray:
    """[B, max_samples] f32 PCM in [-1,1] → [B, num_frames, n_mels]."""
    window = np.hanning(cfg.n_fft).astype(np.float32)
    padded = np.pad(audio, ((0, 0), (0, cfg.n_fft)))
    # zero-copy strided framing (no Python loop over frames)
    all_frames = np.lib.stride_tricks.sliding_window_view(
        padded, cfg.n_fft, axis=1
    )
    frames = all_frames[:, :: cfg.hop][:, : cfg.num_frames] * window
    spec = np.abs(np.fft.rfft(frames, axis=-1)) ** 2
    mel = spec @ _mel_filterbank(cfg).T
    return np.log10(np.maximum(mel, 1e-10)).astype(np.float32)


# ---------------------------------------------------------------------------
# encoder + CTC
# ---------------------------------------------------------------------------


def init_params(cfg: ASRConfig, key: jax.Array, dtype=jnp.float32) -> Params:
    h = cfg.hidden_size
    ks = jax.random.split(key, 4)
    in_dim = cfg.n_mels * cfg.conv_stride
    return {
        "in_proj": fan_in_init(ks[0], (in_dim, h), in_dim, dtype),
        "pos_emb": fan_in_init(ks[1], (cfg.enc_frames, h), h, dtype),
        "layers": init_encoder_layers(ks[2], cfg.num_layers, h, dtype=dtype),
        "out_norm": jnp.ones((h,), dtype),
        "ctc_head": fan_in_init(ks[3], (h, cfg.vocab_size), h, dtype),
    }


def encode(cfg: ASRConfig, params: Params, mel: jax.Array) -> jax.Array:
    """[B, num_frames, n_mels] → CTC logits [B, enc_frames, vocab]."""
    b = mel.shape[0]
    # stride-fold time downsampling (conv-free "conv subsampling")
    x = mel.reshape(b, cfg.enc_frames, cfg.n_mels * cfg.conv_stride)
    x = x @ params["in_proj"] + params["pos_emb"][None]
    x = run_encoder(x, params["layers"], cfg.num_heads)
    return layer_norm(x, params["out_norm"]) @ params["ctc_head"]


def ctc_greedy_decode(logits: np.ndarray) -> List[List[int]]:
    """Greedy CTC collapse: argmax per frame, merge repeats, drop blanks."""
    ids = np.argmax(logits, axis=-1)
    out: List[List[int]] = []
    for row in ids:
        seq: List[int] = []
        prev = -1
        for t in row:
            t = int(t)
            if t != prev and t != CTC_BLANK:
                seq.append(t)
            prev = t
        out.append(seq)
    return out
