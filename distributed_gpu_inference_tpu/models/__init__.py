"""Pure-JAX model families.

TPU-native replacement for the reference's engine-wrapped model zoo
(``worker/engines/llm.py`` HF Transformers, ``llm_vllm.py``, ``llm_sglang.py``):
instead of wrapping a framework, the decoder is implemented directly as
functional JAX over a params pytree so it jits, shards (pjit/GSPMD), and
pipelines over a mesh without translation layers.
"""

from distributed_gpu_inference_tpu.models.configs import (  # noqa: F401
    MODEL_REGISTRY,
    ModelConfig,
    get_model_config,
)
