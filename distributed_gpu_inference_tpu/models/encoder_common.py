"""Shared building blocks for the non-causal transformer encoders
(ViT, DiT, ASR): LayerNorm, fan-in init, bidirectional attention block,
patchify/unpatchify. One implementation — the three encoders must not
drift on eps/head-reshape details.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def fan_in_init(key: jax.Array, shape: Tuple[int, ...], fan_in: int,
                dtype=jnp.float32) -> jax.Array:
    return (jax.random.normal(key, shape, jnp.float32) * fan_in**-0.5
            ).astype(dtype)


def layer_norm(v: jax.Array, w: jax.Array, b: Optional[jax.Array] = None,
               eps: float = 1e-6) -> jax.Array:
    mean = v.mean(-1, keepdims=True)
    var = v.var(-1, keepdims=True)
    out = (v - mean) * lax.rsqrt(var + eps) * w
    return out + b if b is not None else out


def init_encoder_layers(key: jax.Array, num_layers: int, hidden: int,
                        mlp_ratio: int = 4, dtype=jnp.float32
                        ) -> Dict[str, jax.Array]:
    """Stacked (leading L axis) params for ``encoder_block`` under lax.scan."""
    ks = jax.random.split(key, 4)
    L, h = num_layers, hidden
    return {
        "norm1": jnp.ones((L, h), dtype),
        "wqkv": fan_in_init(ks[0], (L, h, h * 3), h, dtype),
        "wo": fan_in_init(ks[1], (L, h, h), h, dtype),
        "norm2": jnp.ones((L, h), dtype),
        "w1": fan_in_init(ks[2], (L, h, h * mlp_ratio), h, dtype),
        "w2": fan_in_init(ks[3], (L, h * mlp_ratio, h), h * mlp_ratio, dtype),
    }


def mha(x: jax.Array, wqkv: jax.Array, wo: jax.Array, num_heads: int,
        bqkv: Optional[jax.Array] = None,
        bo: Optional[jax.Array] = None) -> jax.Array:
    """Bidirectional multi-head self-attention over [B, N, H]."""
    b, n, h = x.shape
    hd = h // num_heads
    qkv = x @ wqkv
    if bqkv is not None:
        qkv = qkv + bqkv
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(z):
        return z.reshape(b, n, num_heads, hd).transpose(0, 2, 1, 3)

    attn = jax.nn.softmax(
        (heads(q) @ heads(k).transpose(0, 1, 3, 2)) / math.sqrt(hd), -1
    )
    out = (attn @ heads(v)).transpose(0, 2, 1, 3).reshape(b, n, h) @ wo
    return out + bo if bo is not None else out


def encoder_block(x: jax.Array, lp: Dict[str, jax.Array],
                  num_heads: int) -> jax.Array:
    """Pre-norm transformer encoder block (attention + GELU MLP).

    Bias keys (``bqkv``/``bo``/``b1``/``b2``/``norm1_b``/``norm2_b``) are
    OPTIONAL: first-party inits are bias-free (round-1 design), while
    imported HF ViT-class checkpoints carry all of them
    (``models/loader.py load_hf_vit``) — the pytree's key set is static
    per jit trace, so the branch costs nothing."""
    x = x + mha(
        layer_norm(x, lp["norm1"], lp.get("norm1_b")),
        lp["wqkv"], lp["wo"], num_heads,
        bqkv=lp.get("bqkv"), bo=lp.get("bo"),
    )
    y = layer_norm(x, lp["norm2"], lp.get("norm2_b"))
    y = jax.nn.gelu(y @ lp["w1"] + (lp["b1"] if "b1" in lp else 0.0))
    y = y @ lp["w2"] + (lp["b2"] if "b2" in lp else 0.0)
    return x + y


def run_encoder(x: jax.Array, layers: Dict[str, jax.Array],
                num_heads: int) -> jax.Array:
    """All encoder blocks under one lax.scan (stacked-L params)."""

    def step(h, lp):
        return encoder_block(h, lp, num_heads), None

    out, _ = lax.scan(step, x, layers)
    return out


def patchify(img: jax.Array, patch: int) -> jax.Array:
    """[B, S, S, C] → [B, (S/p)^2, p*p*C]."""
    b, s, _, c = img.shape
    g = s // patch
    x = img.reshape(b, g, patch, g, patch, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, patch * patch * c)


def unpatchify(x: jax.Array, image_size: int, patch: int,
               channels: int) -> jax.Array:
    b = x.shape[0]
    g = image_size // patch
    x = x.reshape(b, g, g, patch, patch, channels)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, image_size, image_size, channels
    )
