"""Weight loading: HF Llama safetensors → params pytree; orbax-native
checkpoints; (mesh resharding hooks live in ``parallel/sharding.py``).

Reference analogue: ``worker/engines/llm.py:33-36`` (AutoModelForCausalLM
device_map load) and ``worker/distributed/model_shard.py:61-160``
(layer-range partial loading) — re-designed: weights map straight into the
stacked-layer pytree (leading L axis) that ``lax.scan`` and GSPMD sharding
consume, and a pipeline stage can load only its layer range.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from distributed_gpu_inference_tpu.models.configs import ModelConfig
from distributed_gpu_inference_tpu.utils.data_structures import BlockRange

# HF parameter name → (our key, needs_transpose). Layer index is captured by
# the regex; our layout stacks layers on a leading axis.
_HF_LAYER_MAP = {
    "input_layernorm.weight": ("attn_norm", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    # Qwen2-style attention biases (absent in Llama checkpoints)
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.bias": ("bv", False),
    "post_attention_layernorm.weight": ("mlp_norm", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
}

_LAYER_RE = re.compile(r"^model\.layers\.(\d+)\.(.+)$")

# HF Mixtral MoE naming: block_sparse_moe.gate (router) and per-expert
# w1 (gate), w3 (up), w2 (down) projections
_MOE_GATE_KEY = "block_sparse_moe.gate.weight"
_MOE_EXPERT_RE = re.compile(
    r"^block_sparse_moe\.experts\.(\d+)\.(w[123])\.weight$"
)
_MOE_EXPERT_MAP = {"w1": "we_gate", "w3": "we_up", "w2": "we_down"}


def load_hf_llama(
    model_dir: str | Path,
    cfg: ModelConfig,
    dtype: Optional[Any] = None,
    layer_range: Optional[BlockRange] = None,
) -> Dict[str, Any]:
    """Load a HF Llama checkpoint directory (safetensors shards) into the
    stacked params pytree. ``layer_range`` loads only layers [start, end)
    (pipeline stages); embeddings / final norm / head are included only for
    the ranges that own them (first / last stage — reference
    model_shard.py:163-171)."""
    from safetensors import safe_open

    model_dir = Path(model_dir)
    dtype = jnp.dtype(dtype or cfg.dtype)
    rng = layer_range or BlockRange(0, cfg.num_layers)
    first_stage = rng.start == 0
    last_stage = rng.end == cfg.num_layers
    L = rng.num_layers

    files = sorted(model_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {model_dir}")

    layers: Dict[str, np.ndarray] = {}
    params: Dict[str, Any] = {"layers": {}}

    def _slot(our_key: str, shape: Tuple[int, ...]) -> np.ndarray:
        if our_key not in layers:
            layers[our_key] = np.zeros((L, *shape), dtype=dtype)
        return layers[our_key]

    for f in files:
        with safe_open(str(f), framework="np") as st:
            for name in st.keys():
                m = _LAYER_RE.match(name)
                if m:
                    li = int(m.group(1))
                    if li not in rng:
                        continue
                    sub = m.group(2)
                    em = _MOE_EXPERT_RE.match(sub)
                    if em:  # Mixtral expert: stack on [L, E, in, out]
                        ei = int(em.group(1))
                        our_key = _MOE_EXPERT_MAP[em.group(2)]
                        w = st.get_tensor(name).T  # HF stores [out, in]
                        buf = _slot(
                            our_key, (cfg.num_experts, *w.shape)
                        )
                        buf[li - rng.start, ei] = w.astype(dtype)
                        continue
                    if sub == _MOE_GATE_KEY:  # router [E, H] → [H, E]
                        w = st.get_tensor(name).T
                        _slot("w_router", w.shape)[li - rng.start] = (
                            w.astype(dtype)
                        )
                        continue
                    if sub not in _HF_LAYER_MAP:
                        continue
                    our_key, transpose = _HF_LAYER_MAP[sub]
                    w = st.get_tensor(name)
                    if transpose:
                        w = w.T
                    _slot(our_key, w.shape)[li - rng.start] = w.astype(dtype)
                elif name == "model.embed_tokens.weight" and first_stage:
                    params["embedding"] = jnp.asarray(st.get_tensor(name), dtype)
                elif name == "model.norm.weight" and last_stage:
                    params["final_norm"] = jnp.asarray(st.get_tensor(name), dtype)
                elif name == "lm_head.weight" and last_stage and \
                        not cfg.tie_word_embeddings:
                    params["lm_head"] = jnp.asarray(st.get_tensor(name), dtype)

    params["layers"] = {k: jnp.asarray(v) for k, v in layers.items()}
    if cfg.tie_word_embeddings and last_stage and not first_stage:
        # tied head on a non-first stage still needs the embedding matrix;
        # scan every shard — multi-file checkpoints store it anywhere
        for f in files:
            with safe_open(str(f), framework="np") as st:
                if "model.embed_tokens.weight" in st.keys():
                    params["embedding"] = jnp.asarray(
                        st.get_tensor("model.embed_tokens.weight"), dtype
                    )
                    break
    _validate(params, cfg, rng)
    return params


def _validate(params: Dict[str, Any], cfg: ModelConfig, rng: BlockRange) -> None:
    expected = set(_HF_LAYER_MAP[k][0] for k in _HF_LAYER_MAP)
    if not cfg.attention_bias:  # Llama-family checkpoints carry no biases
        expected -= {"bq", "bk", "bv"}
    if cfg.num_experts:  # Mixtral: sparse expert MLP instead of dense
        expected -= {"w_gate", "w_up", "w_down"}
        expected |= {"w_router", "we_gate", "we_up", "we_down"}
    got = set(params["layers"].keys())
    if got != expected:
        missing, extra = expected - got, got - expected
        parts = []
        if missing:
            parts.append(f"missing {sorted(missing)}")
        if extra:
            hint = (
                " (a biased checkpoint needs a config with "
                "attention_bias=True)"
                if extra <= {"bq", "bk", "bv"} else ""
            )
            parts.append(f"unexpected {sorted(extra)}{hint}")
        raise ValueError("checkpoint layer params: " + "; ".join(parts))
    L = rng.num_layers
    for k, v in params["layers"].items():
        if v.shape[0] != L:
            raise ValueError(f"{k}: expected {L} layers, got {v.shape[0]}")
    if rng.start == 0 and "embedding" not in params:
        raise ValueError("first stage missing embedding")
    if rng.end == cfg.num_layers and "final_norm" not in params:
        raise ValueError("last stage missing final_norm")


# ---------------------------------------------------------------------------
# Native checkpoints (orbax) — serving snapshots / resume (SURVEY §5.4 notes
# the reference has none; we add weight checkpointing as a first-class op)
# ---------------------------------------------------------------------------


def save_checkpoint(path: str | Path, params: Dict[str, Any],
                    cfg: Optional[ModelConfig] = None) -> None:
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path / "params", params)
    ckptr.wait_until_finished()
    if cfg is not None:
        from dataclasses import asdict

        # dump EVERY config field: a hand-kept list silently drops new
        # fields (attention_bias once went missing this way)
        (path / "model_config.json").write_text(json.dumps(asdict(cfg)))


def load_checkpoint(path: str | Path, template: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    ckptr = ocp.StandardCheckpointer()
    if template is not None:
        return ckptr.restore(path / "params", template)
    return ckptr.restore(path / "params")


def load_or_init_params(
    cfg: ModelConfig,
    checkpoint_path: Optional[str] = None,
    dtype: Optional[Any] = None,
    seed: int = 0,
) -> Dict[str, Any]:
    """One-stop weight source for engines: orbax checkpoint dir, HF
    safetensors dir, or random init (hermetic tests / benchmarks)."""
    import jax

    from distributed_gpu_inference_tpu.models import llama

    if checkpoint_path:
        p = Path(checkpoint_path)
        if (p / "config.json").exists() or list(p.glob("*.safetensors")):
            return load_hf_llama(p, cfg, dtype=dtype)
        return load_checkpoint(p)
    return llama.init_params(
        cfg, jax.random.PRNGKey(seed), jnp.dtype(dtype or cfg.dtype)
    )
