"""Weight loading: HF Llama safetensors → params pytree; orbax-native
checkpoints; (mesh resharding hooks live in ``parallel/sharding.py``).

Reference analogue: ``worker/engines/llm.py:33-36`` (AutoModelForCausalLM
device_map load) and ``worker/distributed/model_shard.py:61-160``
(layer-range partial loading) — re-designed: weights map straight into the
stacked-layer pytree (leading L axis) that ``lax.scan`` and GSPMD sharding
consume, and a pipeline stage can load only its layer range.
"""

from __future__ import annotations

import functools
import json
import re
import zlib
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from distributed_gpu_inference_tpu.models.configs import ModelConfig
from distributed_gpu_inference_tpu.utils.data_structures import BlockRange

# HF parameter name → (our key, needs_transpose). Layer index is captured by
# the regex; our layout stacks layers on a leading axis.
_HF_LAYER_MAP = {
    "input_layernorm.weight": ("attn_norm", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    # Qwen2-style attention biases (absent in Llama checkpoints)
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.bias": ("bv", False),
    "post_attention_layernorm.weight": ("mlp_norm", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
}

_LAYER_RE = re.compile(r"^model\.layers\.(\d+)\.(.+)$")

# HF Mixtral MoE naming: block_sparse_moe.gate (router) and per-expert
# w1 (gate), w3 (up), w2 (down) projections
_MOE_GATE_KEY = "block_sparse_moe.gate.weight"
_MOE_EXPERT_RE = re.compile(
    r"^block_sparse_moe\.experts\.(\d+)\.(w[123])\.weight$"
)
_MOE_EXPERT_MAP = {"w1": "we_gate", "w3": "we_up", "w2": "we_down"}


def load_hf_llama(
    model_dir: str | Path,
    cfg: ModelConfig,
    dtype: Optional[Any] = None,
    layer_range: Optional[BlockRange] = None,
) -> Dict[str, Any]:
    """Load a HF Llama checkpoint directory (safetensors shards) into the
    stacked params pytree. ``layer_range`` loads only layers [start, end)
    (pipeline stages); embeddings / final norm / head are included only for
    the ranges that own them (first / last stage — reference
    model_shard.py:163-171)."""
    from safetensors import safe_open

    model_dir = Path(model_dir)
    dtype = jnp.dtype(dtype or cfg.dtype)
    rng = layer_range or BlockRange(0, cfg.num_layers)
    first_stage = rng.start == 0
    last_stage = rng.end == cfg.num_layers
    L = rng.num_layers

    files = sorted(model_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {model_dir}")

    layers: Dict[str, np.ndarray] = {}
    params: Dict[str, Any] = {"layers": {}}

    def _slot(our_key: str, shape: Tuple[int, ...]) -> np.ndarray:
        if our_key not in layers:
            layers[our_key] = np.zeros((L, *shape), dtype=dtype)
        return layers[our_key]

    for f in files:
        with safe_open(str(f), framework="np") as st:
            for name in st.keys():
                m = _LAYER_RE.match(name)
                if m:
                    li = int(m.group(1))
                    if li not in rng:
                        continue
                    sub = m.group(2)
                    em = _MOE_EXPERT_RE.match(sub)
                    if em:  # Mixtral expert: stack on [L, E, in, out]
                        ei = int(em.group(1))
                        our_key = _MOE_EXPERT_MAP[em.group(2)]
                        w = st.get_tensor(name).T  # HF stores [out, in]
                        buf = _slot(
                            our_key, (cfg.num_experts, *w.shape)
                        )
                        buf[li - rng.start, ei] = w.astype(dtype)
                        continue
                    if sub == _MOE_GATE_KEY:  # router [E, H] → [H, E]
                        w = st.get_tensor(name).T
                        _slot("w_router", w.shape)[li - rng.start] = (
                            w.astype(dtype)
                        )
                        continue
                    if sub not in _HF_LAYER_MAP:
                        continue
                    our_key, transpose = _HF_LAYER_MAP[sub]
                    w = st.get_tensor(name)
                    if transpose:
                        w = w.T
                    _slot(our_key, w.shape)[li - rng.start] = w.astype(dtype)
                elif name == "model.embed_tokens.weight" and first_stage:
                    params["embedding"] = jnp.asarray(st.get_tensor(name), dtype)
                elif name == "model.norm.weight" and last_stage:
                    params["final_norm"] = jnp.asarray(st.get_tensor(name), dtype)
                elif name == "lm_head.weight" and last_stage and \
                        not cfg.tie_word_embeddings:
                    params["lm_head"] = jnp.asarray(st.get_tensor(name), dtype)

    params["layers"] = {k: jnp.asarray(v) for k, v in layers.items()}
    if cfg.tie_word_embeddings and last_stage and not first_stage:
        # tied head on a non-first stage still needs the embedding matrix;
        # scan every shard — multi-file checkpoints store it anywhere
        for f in files:
            with safe_open(str(f), framework="np") as st:
                if "model.embed_tokens.weight" in st.keys():
                    params["embedding"] = jnp.asarray(
                        st.get_tensor("model.embed_tokens.weight"), dtype
                    )
                    break
    _validate(params, cfg, rng)
    return params


def _validate(params: Dict[str, Any], cfg: ModelConfig, rng: BlockRange) -> None:
    expected = set(_HF_LAYER_MAP[k][0] for k in _HF_LAYER_MAP)
    if not cfg.attention_bias:  # Llama-family checkpoints carry no biases
        expected -= {"bq", "bk", "bv"}
    if cfg.num_experts:  # Mixtral: sparse expert MLP instead of dense
        expected -= {"w_gate", "w_up", "w_down"}
        expected |= {"w_router", "we_gate", "we_up", "we_down"}
    got = set(params["layers"].keys())
    if got != expected:
        missing, extra = expected - got, got - expected
        parts = []
        if missing:
            parts.append(f"missing {sorted(missing)}")
        if extra:
            hint = (
                " (a biased checkpoint needs a config with "
                "attention_bias=True)"
                if extra <= {"bq", "bk", "bv"} else ""
            )
            parts.append(f"unexpected {sorted(extra)}{hint}")
        raise ValueError("checkpoint layer params: " + "; ".join(parts))
    L = rng.num_layers
    for k, v in params["layers"].items():
        if v.shape[0] != L:
            raise ValueError(f"{k}: expected {L} layers, got {v.shape[0]}")
    if rng.start == 0 and "embedding" not in params:
        raise ValueError("first stage missing embedding")
    if rng.end == cfg.num_layers and "final_norm" not in params:
        raise ValueError("last stage missing final_norm")


def init_quantized_streamed(
    cfg: ModelConfig,
    mode: str,
    dtype: Optional[Any] = None,
    seed: int = 0,
) -> Dict[str, Any]:
    """Random-init a model DIRECTLY on device in quantized form, one layer
    slice at a time — the cold-start path for models whose full-precision
    tree exceeds device HBM (llama3-8b bf16 = 16.1 GB on a 16 GB v5e).

    Each quantized leaf is produced by ONE jitted ``lax.scan`` over the layer
    axis: the scan body generates a float32 layer slice on device, quantizes
    it (``ops.quantization.quantize_weight``), and the scan stacks the int8/
    fp8 outputs. Peak transient HBM = one f32 layer slice (~0.25 GB for 8B)
    on top of the growing quantized tree — no host-side init (minutes of
    single-core numpy for 8B) and no multi-GB host→device upload (~1 GB/s
    over a tunneled chip). Per distinct leaf shape there is one compile.

    The random stream is deterministic in ``seed`` but differs from
    ``llama.init_params`` (which draws each leaf in one full-shape call);
    random-init weights serve benchmarks/tests, not checkpoints, so only
    determinism matters, not cross-path equality.

    Reference analogue: none — its engines inherit load-time behavior from
    HF/vLLM (``worker/engines/llm.py:33-36``); cold-starting a quantized
    model that doesn't fit in fp16 is delegated to pre-quantized
    checkpoints there.
    """
    import jax
    from distributed_gpu_inference_tpu.models import llama
    from distributed_gpu_inference_tpu.ops.quantization import (
        QUANT_KEYS,
        quantize_weight,
    )

    dtype = jnp.dtype(dtype or cfg.dtype)
    h, d = cfg.hidden_size, cfg.head_dim
    nh, nkv, i = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size
    L, v = cfg.num_layers, cfg.vocab_size

    root = jax.random.PRNGKey(seed)

    @functools.lru_cache(maxsize=None)
    def _scan_fn(shape: Tuple[int, ...], fan_in: int):
        def gen(keys):
            def body(carry, k):
                w = jax.random.normal(k, shape, jnp.float32) * (fan_in**-0.5)
                q = quantize_weight(w, mode)
                return carry, (q["qw"], q["scale"])

            _, (qw, scale) = jax.lax.scan(body, 0, keys)
            return {"qw": qw, "scale": scale}

        return jax.jit(gen)

    def _name_key(name: str):
        # stable across processes (str hash() is salted per interpreter)
        return jax.random.fold_in(root, zlib.crc32(name.encode()) & 0x7FFFFFFF)

    def _q_leaf(name: str, shape: Tuple[int, ...], fan_in: int):
        keys = jax.random.split(_name_key(name), L)
        out = _scan_fn(shape, fan_in)(keys)
        jax.block_until_ready(out["qw"])  # bound transient f32 live range
        return out

    def _dense_leaf(name: str, shape: Tuple[int, ...], fan_in: int):
        k = _name_key(name)
        f = jax.jit(
            lambda k: (
                jax.random.normal(k, shape, jnp.float32) * (fan_in**-0.5)
            ).astype(dtype)
        )
        return f(k)

    norm_init = jnp.zeros if cfg.norm_offset else jnp.ones
    layers: Dict[str, Any] = {
        "attn_norm": norm_init((L, h), dtype),
        "mlp_norm": norm_init((L, h), dtype),
    }
    leaf_specs = {
        "wq": ((h, nh * d), h),
        "wk": ((h, nkv * d), h),
        "wv": ((h, nkv * d), h),
        "wo": ((nh * d, h), nh * d),
    }
    if cfg.num_experts:
        E = cfg.num_experts
        layers["w_router"] = _dense_leaf("w_router", (L, h, E), h)
        leaf_specs.update({
            "we_gate": ((E, h, i), h),
            "we_up": ((E, h, i), h),
            "we_down": ((E, i, h), i),
        })
    else:
        leaf_specs.update({
            "w_gate": ((h, i), h),
            "w_up": ((h, i), h),
            "w_down": ((i, h), i),
        })
    for name, (shape, fan_in) in leaf_specs.items():
        assert name in QUANT_KEYS
        layers[name] = _q_leaf(name, shape, fan_in)
    if cfg.attention_bias:
        layers["bq"] = _dense_leaf("bq", (L, nh * d), nh * d)
        layers["bk"] = _dense_leaf("bk", (L, nkv * d), nkv * d)
        layers["bv"] = _dense_leaf("bv", (L, nkv * d), nkv * d)

    params: Dict[str, Any] = {
        "embedding": _dense_leaf("embedding", (v, h), h),
        "layers": layers,
        "final_norm": norm_init((h,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = _dense_leaf("lm_head", (v, h), h)
    return params


# ---------------------------------------------------------------------------
# HF ViT-class checkpoints → vit.Params (VERDICT r4 #8: one non-Llama
# family with a real-checkpoint import path)
# ---------------------------------------------------------------------------

# HF ViT encoder-layer name → (our key, needs_transpose). q/k/v weights
# fuse into our wqkv separately below.
_HF_VIT_LAYER_MAP = {
    "layernorm_before.weight": ("norm1", False),
    "layernorm_before.bias": ("norm1_b", False),
    "attention.output.dense.weight": ("wo", True),
    "attention.output.dense.bias": ("bo", False),
    "layernorm_after.weight": ("norm2", False),
    "layernorm_after.bias": ("norm2_b", False),
    "intermediate.dense.weight": ("w1", True),
    "intermediate.dense.bias": ("b1", False),
    "output.dense.weight": ("w2", True),
    "output.dense.bias": ("b2", False),
}
_VIT_LAYER_RE = re.compile(r"^vit\.encoder\.layer\.(\d+)\.(.+)$")
_VIT_QKV_RE = re.compile(
    r"^attention\.attention\.(query|key|value)\.(weight|bias)$"
)


def load_hf_vit(model_dir: str | Path, cfg, dtype: Optional[Any] = None,
                head_seed: int = 0) -> Dict[str, Any]:
    """Load an HF ViT-class safetensors checkpoint (google/vit-* layout)
    into the :mod:`models.vit` params pytree.

    Faithful for everything the architectures share — both are PRE-norm
    encoders, so patch projection (the conv kernel reshaped to our matmul
    layout), position embeddings, every encoder layer incl. all biases,
    and the final layernorm import exactly. What does NOT come from the
    checkpoint, by design: the CLS token (our model pools through learned
    perceiver queries instead — its position-embedding slot is dropped)
    and the ``query_emb``/``out_proj`` resampler head, which is
    fresh-initialized from ``head_seed`` — the LLaVA-style projector that
    is always trained against the paired decoder (reference bar:
    /root/reference/worker/engines/vision.py:57-78 serves a pretrained
    VLM whose projector shipped with the checkpoint; ours is the part a
    deployment fine-tunes).
    """
    import jax

    from safetensors import safe_open

    from distributed_gpu_inference_tpu.models.encoder_common import (
        fan_in_init,
    )

    model_dir = Path(model_dir)
    dtype = jnp.dtype(dtype or "float32")
    L, h = cfg.num_layers, cfg.hidden_size
    files = sorted(model_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {model_dir}")

    layers: Dict[str, np.ndarray] = {}
    qkv_w = np.zeros((L, 3, h, h), np.float32)
    qkv_b = np.zeros((L, 3, h), np.float32)
    params: Dict[str, Any] = {}
    _QKV_IDX = {"query": 0, "key": 1, "value": 2}
    # every (key, layer) slot must be FILLED from the checkpoint: a missing
    # shard would otherwise leave zero placeholders (zero norms = silent
    # near-no-op blocks) — same contract as the Llama path's _validate
    filled: set = set()

    def _slot(our_key: str, shape: Tuple[int, ...]) -> np.ndarray:
        if our_key not in layers:
            layers[our_key] = np.zeros((L, *shape), dtype=dtype)
        return layers[our_key]

    for f in files:
        with safe_open(str(f), framework="np") as st:
            for name in st.keys():
                m = _VIT_LAYER_RE.match(name)
                if m:
                    li = int(m.group(1))
                    if li >= L:
                        raise ValueError(
                            f"checkpoint layer {li} exceeds config "
                            f"num_layers={L}"
                        )
                    sub = m.group(2)
                    qm = _VIT_QKV_RE.match(sub)
                    if qm:
                        idx = _QKV_IDX[qm.group(1)]
                        w = st.get_tensor(name)
                        if qm.group(2) == "weight":
                            qkv_w[li, idx] = w.T    # HF stores [out, in]
                        else:
                            qkv_b[li, idx] = w
                        filled.add((f"{qm.group(1)}.{qm.group(2)}", li))
                        continue
                    if sub not in _HF_VIT_LAYER_MAP:
                        continue
                    our_key, transpose = _HF_VIT_LAYER_MAP[sub]
                    w = st.get_tensor(name)
                    if transpose:
                        w = w.T
                    _slot(our_key, w.shape)[li] = w.astype(dtype)
                    filled.add((our_key, li))
                elif name == ("vit.embeddings.patch_embeddings."
                              "projection.weight"):
                    # conv kernel [H, C, P, P] → matmul over patchify's
                    # (row, col, channel) flattening → [P*P*C, H]
                    w = st.get_tensor(name).transpose(2, 3, 1, 0)
                    params["patch_proj"] = jnp.asarray(
                        w.reshape(-1, w.shape[-1]), dtype
                    )
                elif name == ("vit.embeddings.patch_embeddings."
                              "projection.bias"):
                    params["patch_bias"] = jnp.asarray(
                        st.get_tensor(name), dtype
                    )
                elif name == "vit.embeddings.position_embeddings":
                    # [1, 1+N, H]: slot 0 is the CLS position — dropped
                    # (we pool through perceiver queries, not CLS)
                    params["pos_emb"] = jnp.asarray(
                        st.get_tensor(name)[0, 1:], dtype
                    )
                elif name == "vit.layernorm.weight":
                    params["out_norm"] = jnp.asarray(
                        st.get_tensor(name), dtype
                    )
                elif name == "vit.layernorm.bias":
                    params["out_norm_b"] = jnp.asarray(
                        st.get_tensor(name), dtype
                    )

    # wqkv columns order (q | k | v) to match the encoder's split:
    # [L, 3, H_in, H_out] → [L, H_in, 3, H_out] → [L, H, 3H]
    layers["wqkv"] = qkv_w.transpose(0, 2, 1, 3).reshape(L, h, 3 * h)
    layers["bqkv"] = qkv_b.reshape(L, 3 * h)
    params["layers"] = {
        k: jnp.asarray(v, dtype) for k, v in layers.items()
    }

    missing = {"patch_proj", "pos_emb", "out_norm"} - set(params)
    if missing:
        raise ValueError(f"checkpoint is missing ViT tensors: {missing}")
    expected_keys = (
        {v[0] for v in _HF_VIT_LAYER_MAP.values()}
        | {f"{q}.{t}" for q in _QKV_IDX for t in ("weight", "bias")}
    )
    gaps = sorted(
        (k, li) for k in expected_keys for li in range(L)
        if (k, li) not in filled
    )
    if gaps:
        raise ValueError(
            f"checkpoint left {len(gaps)} encoder tensors unfilled "
            f"(missing shard / shallower model?): first few {gaps[:4]}"
        )
    if params["pos_emb"].shape[0] != cfg.num_patches:
        raise ValueError(
            f"position embeddings cover {params['pos_emb'].shape[0]} "
            f"patches, config expects {cfg.num_patches} "
            f"(image {cfg.image_size} / patch {cfg.patch_size})"
        )

    # resampler head: fresh init (trained against the paired decoder)
    ks = jax.random.split(jax.random.PRNGKey(head_seed), 2)
    params["query_emb"] = fan_in_init(ks[0], (cfg.num_prefix, h), h, dtype)
    params["out_proj"] = fan_in_init(ks[1], (h, cfg.out_dim), h, dtype)
    return params


# ---------------------------------------------------------------------------
# Native checkpoints (orbax) — serving snapshots / resume (SURVEY §5.4 notes
# the reference has none; we add weight checkpointing as a first-class op)
# ---------------------------------------------------------------------------


def save_checkpoint(path: str | Path, params: Dict[str, Any],
                    cfg: Optional[ModelConfig] = None) -> None:
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path / "params", params)
    ckptr.wait_until_finished()
    if cfg is not None:
        from dataclasses import asdict

        # dump EVERY config field: a hand-kept list silently drops new
        # fields (attention_bias once went missing this way)
        (path / "model_config.json").write_text(json.dumps(asdict(cfg)))


def load_checkpoint(path: str | Path, template: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    ckptr = ocp.StandardCheckpointer()
    if template is not None:
        return ckptr.restore(path / "params", template)
    return ckptr.restore(path / "params")


def load_or_init_params(
    cfg: ModelConfig,
    checkpoint_path: Optional[str] = None,
    dtype: Optional[Any] = None,
    seed: int = 0,
) -> Dict[str, Any]:
    """One-stop weight source for engines: orbax checkpoint dir, HF
    safetensors dir, or random init (hermetic tests / benchmarks)."""
    import jax

    from distributed_gpu_inference_tpu.models import llama

    if checkpoint_path:
        p = Path(checkpoint_path)
        if (p / "config.json").exists() or list(p.glob("*.safetensors")):
            return load_hf_llama(p, cfg, dtype=dtype)
        return load_checkpoint(p)
    return llama.init_params(
        cfg, jax.random.PRNGKey(seed), jnp.dtype(dtype or cfg.dtype)
    )
