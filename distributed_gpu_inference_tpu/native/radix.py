"""ctypes wrapper exposing the C++ radix index with the exact interface of
``runtime.kv_cache.RadixPrefixIndex`` (drop-in behind make_radix_index)."""

from __future__ import annotations

import ctypes
from array import array
from typing import List, Sequence

import numpy as np

from . import get_lib

_I32P = ctypes.POINTER(ctypes.c_int32)
_I64P = ctypes.POINTER(ctypes.c_int64)


def _as_i32(token_ids: Sequence[int]):
    """Cheapest bulk path to a C int32 buffer: zero-copy for numpy/array
    inputs, one C-level pass for Python lists."""
    if isinstance(token_ids, np.ndarray):
        a = np.ascontiguousarray(token_ids, dtype=np.int32)
        return a, a.ctypes.data_as(_I32P), a.size
    a = array("i", token_ids)
    ptr = (ctypes.c_int32 * len(a)).from_buffer(a)
    return a, ctypes.cast(ptr, _I32P), len(a)


class NativeRadixPrefixIndex:
    """C++-backed prefix index; see src/radix_index.cpp.

    Marshaling note: token/block sequences cross the boundary as numpy
    buffers (C-converted in bulk) — a per-element ctypes splat costs more
    than the whole C++ traversal saves. Callers that already hold numpy
    int32 arrays cross zero-copy (``wants_arrays`` advertises this).
    """

    wants_arrays = True

    def __init__(self, block_size: int) -> None:
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.block_size = block_size
        self._h = lib.radix_new(block_size)
        if not self._h:
            raise RuntimeError("radix_new failed")

    def __del__(self) -> None:  # pragma: no cover - interpreter teardown
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            try:
                lib.radix_destroy(h)
            except Exception:
                pass
            self._h = None

    def match_prefix(self, token_ids: Sequence[int]) -> List[int]:
        keep, ptr, n = _as_i32(token_ids)
        max_out = max(1, n // self.block_size)
        out = np.empty(max_out, dtype=np.int64)
        got = self._lib.radix_match(
            self._h, ptr, n, out.ctypes.data_as(_I64P), max_out,
        )
        del keep
        return out[:got].tolist()

    def insert(self, token_ids: Sequence[int], block_ids: Sequence[int]) -> int:
        keep, ptr, n = _as_i32(token_ids)
        blocks = np.ascontiguousarray(block_ids, dtype=np.int64)
        res = int(self._lib.radix_insert(
            self._h, ptr, n, blocks.ctypes.data_as(_I64P), blocks.size,
        ))
        del keep
        return res

    def contains_block(self, block_id: int) -> bool:
        return bool(self._lib.radix_contains(self._h, int(block_id)))

    def is_leaf(self, block_id: int) -> bool:
        return bool(self._lib.radix_is_leaf(self._h, int(block_id)))

    def remove_block(self, block_id: int) -> None:
        rc = self._lib.radix_remove(self._h, int(block_id))
        if rc == -1:
            raise ValueError(f"cannot evict interior radix block {block_id}")

    def __len__(self) -> int:
        return int(self._lib.radix_size(self._h))
