// Radix prefix index over full token blocks — C++ core.
//
// Native counterpart of runtime/kv_cache.py::RadixPrefixIndex (same
// semantics, exchangeable behind runtime.kv_cache.make_radix_index).
// The reference delegates this role to SGLang's RadixAttention C++/Triton
// internals (SURVEY §2.3); here it is first-party: the scheduler-path prefix
// probe runs at C++ speed while KV pages stay device-resident and are only
// referred to by integer block ids.
//
// Design for speed: traversal allocates NOTHING. A chunk is addressed by a
// precomputed FNV-1a hash over its raw int32 bytes; each node keeps its
// children in a flat vector of (hash, child*) scanned linearly (prefix trees
// branch rarely — shared system prompts diverge at one point), with a full
// memcmp of the stored edge on hash match. This beats a per-chunk
// std::vector key construction by an order of magnitude.
//
// C ABI (ctypes): every function is extern "C"; handles are opaque pointers.

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

inline uint64_t chunk_hash(const int32_t* p, int n) {
    uint64_t h = 1469598103934665603ULL;  // FNV offset basis
    const uint8_t* b = reinterpret_cast<const uint8_t*>(p);
    for (int i = 0; i < n * 4; ++i) {
        h ^= b[i];
        h *= 1099511628211ULL;  // FNV prime
    }
    return h;
}

struct Node {
    std::vector<std::pair<uint64_t, Node*>> children;
    Node* parent = nullptr;
    std::vector<int32_t> edge;   // owned copy of the chunk tokens
    uint64_t edge_hash = 0;
    int64_t block_id = -1;

    Node* find_child(uint64_t h, const int32_t* chunk, int bs) {
        for (auto& c : children) {
            if (c.first == h &&
                std::memcmp(c.second->edge.data(), chunk,
                            bs * sizeof(int32_t)) == 0) {
                return c.second;
            }
        }
        return nullptr;
    }
};

struct RadixIndex {
    int block_size;
    Node root;
    std::unordered_map<int64_t, Node*> by_block;

    explicit RadixIndex(int bs) : block_size(bs) {}

    ~RadixIndex() { destroy_children(&root); }

    void destroy_children(Node* n) {
        for (auto& kv : n->children) {
            destroy_children(kv.second);
            delete kv.second;
        }
        n->children.clear();
    }
};

}  // namespace

extern "C" {

void* radix_new(int block_size) {
    if (block_size <= 0) return nullptr;
    return new RadixIndex(block_size);
}

void radix_destroy(void* h) { delete static_cast<RadixIndex*>(h); }

// Longest cached full-block prefix: writes up to max_out physical block ids
// into out_blocks; returns the number matched.
int64_t radix_match(void* h, const int32_t* tokens, int64_t n_tokens,
                    int64_t* out_blocks, int64_t max_out) {
    auto* idx = static_cast<RadixIndex*>(h);
    const int bs = idx->block_size;
    const int64_t n_full = n_tokens / bs;
    Node* node = &idx->root;
    int64_t matched = 0;
    for (int64_t i = 0; i < n_full && matched < max_out; ++i) {
        const int32_t* chunk = tokens + i * bs;
        Node* child = node->find_child(chunk_hash(chunk, bs), chunk, bs);
        if (child == nullptr) break;
        out_blocks[matched++] = child->block_id;
        node = child;
    }
    return matched;
}

// Index blocks as the cache of the full token blocks; already-present prefix
// nodes are left untouched. Returns the number of newly indexed blocks.
int64_t radix_insert(void* h, const int32_t* tokens, int64_t n_tokens,
                     const int64_t* blocks, int64_t n_blocks) {
    auto* idx = static_cast<RadixIndex*>(h);
    const int bs = idx->block_size;
    int64_t n_full = n_tokens / bs;
    if (n_blocks < n_full) n_full = n_blocks;
    Node* node = &idx->root;
    int64_t added = 0;
    for (int64_t i = 0; i < n_full; ++i) {
        const int32_t* chunk = tokens + i * bs;
        const uint64_t hash = chunk_hash(chunk, bs);
        Node* child = node->find_child(hash, chunk, bs);
        if (child == nullptr) {
            child = new Node();
            child->parent = node;
            child->edge.assign(chunk, chunk + bs);
            child->edge_hash = hash;
            child->block_id = blocks[i];
            node->children.emplace_back(hash, child);
            idx->by_block[blocks[i]] = child;
            ++added;
        }
        node = child;
    }
    return added;
}

int radix_contains(void* h, int64_t block_id) {
    auto* idx = static_cast<RadixIndex*>(h);
    return idx->by_block.count(block_id) ? 1 : 0;
}

int radix_is_leaf(void* h, int64_t block_id) {
    auto* idx = static_cast<RadixIndex*>(h);
    auto it = idx->by_block.find(block_id);
    return (it != idx->by_block.end() && it->second->children.empty()) ? 1 : 0;
}

// 0 = removed, 1 = absent (no-op), -1 = interior (refused)
int radix_remove(void* h, int64_t block_id) {
    auto* idx = static_cast<RadixIndex*>(h);
    auto it = idx->by_block.find(block_id);
    if (it == idx->by_block.end()) return 1;
    Node* node = it->second;
    if (!node->children.empty()) return -1;
    idx->by_block.erase(it);
    auto& sibs = node->parent->children;
    for (size_t i = 0; i < sibs.size(); ++i) {
        if (sibs[i].second == node) {
            sibs[i] = sibs.back();
            sibs.pop_back();
            break;
        }
    }
    delete node;
    return 0;
}

int64_t radix_size(void* h) {
    return static_cast<int64_t>(static_cast<RadixIndex*>(h)->by_block.size());
}

}  // extern "C"
