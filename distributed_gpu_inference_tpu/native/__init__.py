"""First-party native (C++) runtime components with build-on-demand.

The reference ships zero first-party native code — its native surface lives
in vLLM/SGLang/grpcio (SURVEY §2.3). Here the performance-critical HOST-side
runtime pieces are first-party C++ compiled at first use with the system
toolchain and loaded over ctypes; every component has an exact-semantics
Python fallback, so the framework works (slower) without a compiler.

Components:
- ``radix_index.cpp`` — prefix-cache radix tree (scheduler hot path); Python
  fallback: ``runtime.kv_cache.RadixPrefixIndex``. Perf profile (1-core CI
  box): ~8-19x faster than the fallback when token ids arrive as numpy
  int32 arrays (zero-copy across the ABI), break-even on short Python lists
  where ``array('i', ...)`` conversion dominates — pass arrays on hot paths.

Set ``TPU_NATIVE=0`` to force the Python fallbacks.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from pathlib import Path
from typing import Optional

log = logging.getLogger("tpu_native")

_SRC_DIR = Path(__file__).parent / "src"
_BUILD_DIR = Path(
    os.environ.get("TPU_NATIVE_BUILD_DIR", Path(__file__).parent / "_build")
)

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False
_load_lock = threading.Lock()


def _compile(src: Path, out: Path) -> bool:
    tmp = None
    try:
        out.parent.mkdir(parents=True, exist_ok=True)
        # build to a temp name then atomic-rename: concurrent importers must
        # never dlopen a half-written .so
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(out.parent))
        os.close(fd)
        cmd = [
            os.environ.get("CXX", "g++"), "-O2", "-std=c++17", "-shared",
            "-fPIC", "-o", tmp, str(src),
        ]
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
        if proc.returncode != 0:
            log.warning("native build failed:\n%s", proc.stderr[-2000:])
            return False
        os.replace(tmp, out)
        tmp = None
        return True
    except (OSError, subprocess.TimeoutExpired) as exc:
        # read-only install dir, missing toolchain, … → Python fallback
        log.warning("native build unavailable: %s", exc)
        return False
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    with _load_lock:
        if _lib_tried:  # lost the race: winner already initialized
            return _lib
        try:
            lib = _load_locked()
        except Exception as exc:  # e.g. stale .so missing a symbol
            log.warning("native load failed (cached as unavailable): %s", exc)
            lib = None
        _lib = lib
        _lib_tried = True  # success OR failure is cached: probe runs once
        return _lib


def _load_locked() -> Optional[ctypes.CDLL]:
    if os.environ.get("TPU_NATIVE", "1") == "0":
        return None
    src = _SRC_DIR / "radix_index.cpp"
    out = _BUILD_DIR / "libtpu_native.so"
    # a prebuilt .so without sources (shipped wheel) must load as-is
    stale = src.exists() and (
        not out.exists() or out.stat().st_mtime < src.stat().st_mtime
    )
    if stale and not _compile(src, out):
        return None
    if not out.exists():
        return None
    try:
        lib = ctypes.CDLL(str(out))
    except OSError as exc:
        log.warning("could not load native library: %s", exc)
        return None
    # signatures
    lib.radix_new.argtypes = [ctypes.c_int]
    lib.radix_new.restype = ctypes.c_void_p
    lib.radix_destroy.argtypes = [ctypes.c_void_p]
    lib.radix_match.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    lib.radix_match.restype = ctypes.c_int64
    lib.radix_insert.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    lib.radix_insert.restype = ctypes.c_int64
    for name in ("radix_contains", "radix_is_leaf", "radix_remove"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        fn.restype = ctypes.c_int
    lib.radix_size.argtypes = [ctypes.c_void_p]
    lib.radix_size.restype = ctypes.c_int64
    return lib


def native_available() -> bool:
    try:
        return _load() is not None
    except Exception as exc:  # contract: boolean, never raises
        log.warning("native probe failed: %s", exc)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    return _load()
