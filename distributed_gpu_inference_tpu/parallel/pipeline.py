"""Pipeline parallelism: layer-range stages over the ``stage`` mesh axis.

TPU-native re-architecture of the reference's Petals-style pipeline
(``worker/distributed/model_shard.py`` layer-range shards +
``worker/distributed/session.py`` per-hop HTTP tensor shipping). There, every
token crosses N network boundaries as base64 JSON (SURVEY §3.3 calls it the
#1 throughput sin). Here a pipeline "hop" is a ``lax.ppermute`` of activations
over ICI inside ONE jitted graph: no serialization, no host round-trip.

Two layers of the design:

- **In-slice (this module)**: GPipe-style microbatch schedule expressed with
  ``shard_map`` over the ``stage`` axis + ``lax.scan`` over clock ticks; each
  stage owns a contiguous slice of the stacked layer params and its layers'
  paged-KV pools.
- **Cross-host (distributed/)**: the same stage partitioning driven by the
  shard planner below, with activations framed over DCN — the planner mirrors
  the reference's VRAM-proportional ``create_shard_plan``
  (``model_shard.py:313-369``).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_gpu_inference_tpu.models import llama
from distributed_gpu_inference_tpu.models.configs import ModelConfig
from distributed_gpu_inference_tpu.parallel.mesh import AXIS_STAGE

# ---------------------------------------------------------------------------
# Shard planning (layer ranges per stage)
# ---------------------------------------------------------------------------


def uniform_stages(num_layers: int, num_stages: int) -> List[Tuple[int, int]]:
    """Even split of [0, L) into stages (reference ``model_shard.py:372-394``)."""
    base, rem = divmod(num_layers, num_stages)
    plan, start = [], 0
    for s in range(num_stages):
        n = base + (1 if s < rem else 0)
        plan.append((start, start + n))
        start += n
    return plan


def create_shard_plan(
    cfg: ModelConfig,
    hbm_bytes: Sequence[int],
    kv_reserve_frac: float = 0.3,
) -> List[Tuple[int, int]]:
    """Layer ranges proportional to each stage's HBM minus a KV reserve.

    Mirrors the reference's VRAM-proportional planner
    (``worker/distributed/model_shard.py:313-369``): every stage gets at least
    one layer; capacity shortfalls raise rather than silently overcommit.
    """
    usable = [max(0.0, b * (1.0 - kv_reserve_frac)) for b in hbm_bytes]
    per_layer = cfg.layer_param_bytes(jnp.dtype(cfg.dtype).itemsize)
    cap = [int(u // per_layer) for u in usable]
    L, n = cfg.num_layers, len(hbm_bytes)
    if n > L:
        raise ValueError(f"{n} stages > {L} layers; every stage needs ≥1 layer")
    for s, c in enumerate(cap):
        if c < 1:
            raise ValueError(
                f"stage {s} fits 0 layers "
                f"(per-layer {per_layer / 1e6:.1f} MB > usable HBM)"
            )
    if sum(cap) < L:
        raise ValueError(
            f"stages fit {sum(cap)} layers < model's {L}; "
            f"add stages or HBM (per-layer {per_layer / 1e6:.1f} MB)"
        )
    total = sum(usable)
    raw = [u / total * L for u in usable]
    counts = [1] * n
    while sum(counts) < L:
        cands = [s for s in range(n) if counts[s] < cap[s]]
        s = max(cands, key=lambda j: raw[j] - counts[j])
        counts[s] += 1
    plan, start = [], 0
    for n in counts:
        plan.append((start, start + n))
        start += n
    return plan


def slice_stage_params(
    params: llama.Params, start: int, end: int, *, num_layers: int
) -> llama.Params:
    """Extract one stage's params for the cross-host pipeline: first stage
    keeps the embedding, last keeps final_norm + lm_head (reference
    ``model_shard.py:163-171``)."""
    out: llama.Params = {
        # tree.map: a layer value may be a quantized {"qw","scale"} sub-dict
        # whose leaves both carry the stacked L axis
        "layers": {
            k: jax.tree.map(lambda a: a[start:end], v)
            for k, v in params["layers"].items()
        }
    }
    if start == 0:
        out["embedding"] = params["embedding"]
    if end == num_layers:
        out["final_norm"] = params["final_norm"]
        if "lm_head" in params:
            out["lm_head"] = params["lm_head"]
        elif start != 0:  # tied embeddings: last stage still needs the table
            out["embedding"] = params["embedding"]
    return out


# ---------------------------------------------------------------------------
# In-slice SPMD pipeline (shard_map over the stage axis)
# ---------------------------------------------------------------------------


def stage_param_shardings(mesh: Mesh) -> Dict[str, Any]:
    """Shard the stacked L axis over ``stage``; everything else replicated.
    Composable with TP specs later (stage on L, model on width)."""
    lp = NamedSharding(mesh, P(AXIS_STAGE))

    def _l(*rest):
        return NamedSharding(mesh, P(AXIS_STAGE, *rest))

    return {
        "embedding": NamedSharding(mesh, P()),
        "layers": {
            "attn_norm": _l(None),
            "wq": _l(None, None),
            "wk": _l(None, None),
            "wv": _l(None, None),
            "wo": _l(None, None),
            "bq": _l(None),
            "bk": _l(None),
            "bv": _l(None),
            "mlp_norm": _l(None),
            "w_gate": _l(None, None),
            "w_up": _l(None, None),
            "w_down": _l(None, None),
            "w_router": _l(None, None),
            "we_gate": _l(None, None, None),
            "we_up": _l(None, None, None),
            "we_down": _l(None, None, None),
        },
        "final_norm": NamedSharding(mesh, P()),
        "lm_head": NamedSharding(mesh, P()),
    }


def shard_params_stages(params: llama.Params, mesh: Mesh) -> llama.Params:
    from distributed_gpu_inference_tpu.parallel.sharding import prune_rules

    return jax.device_put(
        params, prune_rules(stage_param_shardings(mesh), params)
    )


def stage_kv_sharding(mesh: Mesh) -> NamedSharding:
    """KV pools [L, N, Hkv, Bk, D]: the layer axis follows its stage."""
    return NamedSharding(mesh, P(AXIS_STAGE, None, None, None, None))


def shard_kv_stages(kv: llama.KVPools, mesh: Mesh) -> llama.KVPools:
    s = stage_kv_sharding(mesh)
    return {k: jax.device_put(v, s) for k, v in kv.items()}


def _pipeline_local(
    tokens: jax.Array,        # [n_micro, mb, S] int32
    positions: jax.Array,     # [n_micro, mb, S] int32, -1 = pad
    block_tables: jax.Array,  # [n_micro, mb, M] int32
    kv_lens: jax.Array,       # [n_micro, mb] int32
    params: llama.Params,     # stage-local: layers [L/n, ...], embed/head replicated
    kv: llama.KVPools,        # stage-local: [L/n, N, Hkv, Bk, D]
    *,
    cfg: ModelConfig,
    axis_name: str,
    n_stages: int,
    block_size: int,
) -> Tuple[jax.Array, llama.KVPools]:
    """Per-device pipeline body. Clock tick t: stage s works on microbatch
    t - s (the GPipe diagonal); activations ppermute forward each tick."""
    stage = lax.axis_index(axis_name)
    n_micro, mb, s_len = tokens.shape
    h = cfg.hidden_size
    total_ticks = n_micro + n_stages - 1
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        act, kv_k, kv_v, out_buf = carry
        my_mb = t - stage
        valid = (my_mb >= 0) & (my_mb < n_micro)
        mb_idx = jnp.clip(my_mb, 0, n_micro - 1)

        tok_t = jnp.take(tokens, mb_idx, axis=0)          # [mb, S]
        pos_t = jnp.take(positions, mb_idx, axis=0)
        tab_t = jnp.take(block_tables, mb_idx, axis=0)
        len_t = jnp.take(kv_lens, mb_idx, axis=0)

        # stage 0 ingests fresh embeddings; later stages consume the permuted
        # activations. Padded/invalid ticks write no KV (positions forced -1).
        inject = llama.embed_tokens(params, tok_t, cfg)
        act_in = jnp.where(stage == 0, inject, act)
        write_pos = jnp.where(valid, pos_t, -1)

        hidden, kv_out = llama.forward_hidden_chunk(
            cfg,
            params,
            act_in,
            write_pos,
            {"k": kv_k, "v": kv_v},
            tab_t,
            len_t,
            block_size=block_size,
        )

        # last stage emits last-valid-token logits for its microbatch
        n_valid = jnp.sum((pos_t >= 0).astype(jnp.int32), axis=1)
        last_idx = jnp.maximum(n_valid - 1, 0)
        h_last = jnp.take_along_axis(
            hidden, last_idx[:, None, None].astype(jnp.int32), axis=1
        )                                                  # [mb, 1, H]
        logits = llama.project_logits(cfg, params, h_last)[:, 0, :]
        store = valid & (stage == n_stages - 1)
        out_buf = jnp.where(
            store,
            out_buf.at[mb_idx].set(logits),
            out_buf,
        )

        act_next = lax.ppermute(hidden, axis_name, fwd_perm)
        return (act_next, kv_out["k"], kv_out["v"], out_buf), None

    # activation dtype follows the actual weights (callers may load params
    # in a dtype other than the config default, e.g. float32 on CPU)
    act0 = jnp.zeros((mb, s_len, h), params["embedding"].dtype)
    out0 = jnp.zeros((n_micro, mb, cfg.vocab_size), jnp.float32)
    (_, kv_k, kv_v, out_buf), _ = lax.scan(
        tick,
        (act0, kv["k"], kv["v"], out0),
        jnp.arange(total_ticks, dtype=jnp.int32),
    )
    # out_specs concatenate per-stage buffers on a fresh axis; only the last
    # stage's slice carries real logits — caller reads [-1].
    return out_buf[None], {"k": kv_k, "v": kv_v}


def pipelined_forward(
    cfg: ModelConfig,
    params: llama.Params,      # stage-sharded (shard_params_stages)
    tokens: jax.Array,         # [n_micro, mb, S]
    positions: jax.Array,      # [n_micro, mb, S]
    kv: llama.KVPools,         # stage-sharded on L
    block_tables: jax.Array,   # [n_micro, mb, M]
    kv_lens: jax.Array,        # [n_micro, mb]
    mesh: Mesh,
    *,
    block_size: int = 16,
) -> Tuple[jax.Array, llama.KVPools]:
    """Microbatched pipeline forward. → (logits [n_micro, mb, V], updated kv).

    One jitted graph; hops are ICI ppermutes. Works for prefill (S = chunk)
    and decode (S = 1) alike.
    """
    n_stages = dict(mesh.shape).get(AXIS_STAGE, 1)
    if cfg.num_layers % n_stages:
        raise ValueError(
            f"{cfg.num_layers} layers not divisible by {n_stages} stages; "
            "use the cross-host planner (create_shard_plan) for uneven splits"
        )
    stage_cfg = cfg  # scan runs over whatever L slice the leaves carry

    lspec = {k: P(AXIS_STAGE, *([None] * (v.ndim - 1)))
             for k, v in params["layers"].items()}
    pspec: Dict[str, Any] = {"layers": lspec}
    for name in ("embedding", "final_norm", "lm_head"):
        if name in params:
            pspec[name] = P()
    kv_spec = {"k": P(AXIS_STAGE), "v": P(AXIS_STAGE)}

    fn = jax.shard_map(
        functools.partial(
            _pipeline_local,
            cfg=stage_cfg,
            axis_name=AXIS_STAGE,
            n_stages=n_stages,
            block_size=block_size,
        ),
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), pspec, kv_spec),
        out_specs=(P(AXIS_STAGE), kv_spec),
        check_vma=False,
    )
    stacked, kv_out = fn(tokens, positions, block_tables, kv_lens, params, kv)
    return stacked[-1], kv_out
