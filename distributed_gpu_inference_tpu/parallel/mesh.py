"""Device mesh construction and axis conventions.

Axis names (fixed across the framework so sharding rules compose):

- ``data``  — request-level data parallelism (replica groups; the reference's
  "many independent workers" DP, SURVEY §2.2, made explicit)
- ``model`` — tensor parallelism over attention heads / MLP width (reference:
  passthrough ``tensor_parallel_size``, vLLM internals; here first-class)
- ``seq``   — sequence/context parallelism (ring attention; absent upstream)
- ``stage`` — pipeline stages (reference: worker-per-layer-range hops over
  HTTP; here a mesh axis with ppermute'd activations)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_STAGE = "stage"

ALL_AXES = (AXIS_DATA, AXIS_STAGE, AXIS_SEQ, AXIS_MODEL)


@dataclass(frozen=True)
class MeshPlan:
    """A named factorization of the device count into parallelism axes."""

    data: int = 1
    stage: int = 1
    seq: int = 1
    model: int = 1

    @property
    def num_devices(self) -> int:
        return self.data * self.stage * self.seq * self.model

    def axis_sizes(self) -> Dict[str, int]:
        return {
            AXIS_DATA: self.data,
            AXIS_STAGE: self.stage,
            AXIS_SEQ: self.seq,
            AXIS_MODEL: self.model,
        }

    def nontrivial_axes(self) -> Tuple[str, ...]:
        return tuple(a for a, s in self.axis_sizes().items() if s > 1)


def make_mesh(
    plan: Optional[MeshPlan] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    keep_trivial_axes: bool = True,
) -> Mesh:
    """Build a Mesh whose axis order is (data, stage, seq, model).

    The model axis is innermost so TP collectives ride the fastest ICI
    neighbors; stage is outer so pipeline transfers cross the slower links —
    matching the bandwidth hierarchy argument of the scaling playbook.
    """
    devices = list(devices if devices is not None else jax.devices())
    if plan is None:
        plan = MeshPlan(model=len(devices))
    if plan.num_devices != len(devices):
        raise ValueError(
            f"mesh plan {plan} needs {plan.num_devices} devices, got {len(devices)}"
        )
    shape = (plan.data, plan.stage, plan.seq, plan.model)
    names: Tuple[str, ...] = ALL_AXES
    if not keep_trivial_axes:
        keep = [i for i, s in enumerate(shape) if s > 1] or [3]
        shape = tuple(shape[i] for i in keep)
        names = tuple(names[i] for i in keep)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, names)


def infer_plan(
    num_devices: int,
    num_kv_heads: int,
    prefer: str = "model",
) -> MeshPlan:
    """Pick a default factorization: TP up to the KV-head count, remainder DP.

    (Sharding KV heads beyond ``num_kv_heads`` would need head replication —
    supported later; the planner stays conservative.)
    """
    model = int(np.gcd(num_devices, num_kv_heads)) if prefer == "model" else 1
    data = num_devices // model
    return MeshPlan(data=data, model=model)
