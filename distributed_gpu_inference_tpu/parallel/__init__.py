"""Mesh-based parallelism: TP/DP/SP/PP shardings over ICI collectives.

TPU-native replacement for the reference's distribution strategies
(SURVEY §2.2): tensor parallelism is first-class GSPMD sharding (the reference
only passes ``tensor_parallel_size`` through to vLLM), pipeline parallelism is
stages over a mesh axis with ``ppermute`` activation transfer (the reference
ships base64 JSON per hop), sequence parallelism is ring attention
(absent in the reference — green-field per SURVEY §5.7).
"""

from distributed_gpu_inference_tpu.parallel.mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SEQ,
    AXIS_STAGE,
    MeshPlan,
    make_mesh,
)
from distributed_gpu_inference_tpu.parallel.sharding import (  # noqa: F401
    batch_shardings,
    kv_sharding,
    param_shardings,
    shard_params,
)
from distributed_gpu_inference_tpu.parallel.pipeline import (  # noqa: F401
    create_shard_plan,
    pipelined_forward,
    shard_kv_stages,
    shard_params_stages,
    slice_stage_params,
    uniform_stages,
)
from distributed_gpu_inference_tpu.parallel.ring_attention import (  # noqa: F401
    ring_self_attention,
    seq_parallel_decode_attention,
)
