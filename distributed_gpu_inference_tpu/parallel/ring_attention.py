"""Sequence/context parallelism over the ``seq`` mesh axis: ring + Ulysses.

Green-field per SURVEY §5.7 — the reference has NO sequence scaling (its
layer-sharded pipeline scales model depth only; long inputs are delegated to
vLLM/SGLang chunked-prefill flags, ``worker/engines/llm_vllm.py:61``,
``llm_sglang.py:63``). Here long sequences are first-class: Q/K/V are sharded
over the ``seq`` axis, with two interchangeable communication strategies:

- **Ring** (:func:`ring_self_attention`) — KV shards rotate around the ring
  via ``lax.ppermute`` over ICI while each device accumulates blockwise
  attention with an online softmax (the Liu et al. recipe, expressed so XLA
  can overlap the permute with the matmul of the next round). No head-count
  constraint; n-1 KV-sized hops.
- **Ulysses** (:func:`ulysses_self_attention`) — two ``lax.all_to_all``
  exchanges swap the sequence shard for a head shard (DeepSpeed-Ulysses):
  each device runs plain full-sequence attention over its Nh/n heads.
  Communication is 2 activation-sized a2a instead of n-1 KV rotations —
  cheaper when n is large and heads are plentiful; requires
  ``num_kv_heads % n == 0``.

Plus :func:`seq_parallel_decode_attention` — decode-style: queries replicated
on the ring, context KV sharded; partial (max, sum, acc) merged with one
``pmax``/``psum`` instead of n ring hops.

All match the semantics of ``ops.attention.dense_causal_attention`` (the test
oracle): causal GQA with per-sequence valid ``lengths``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_gpu_inference_tpu.parallel.mesh import AXIS_DATA, AXIS_SEQ

_NEG_INF = -1e30


def _ring_attention_local(
    q: jax.Array,        # [B, Sq, Nh, D] — this device's query shard
    k: jax.Array,        # [B, Skv, Hkv, D] — this device's KV shard
    v: jax.Array,        # [B, Skv, Hkv, D]
    lengths: jax.Array,  # [B] global valid lengths (replicated)
    axis_name: str,
    axis_size: int,
) -> jax.Array:
    """Per-device body (runs under shard_map). → [B, Sq, Nh, D]."""
    idx = jax.lax.axis_index(axis_name)
    b, sq, nh, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    qpk = nh // hkv
    scale = d**-0.5

    qg = q.reshape(b, sq, hkv, qpk, d).astype(jnp.float32)
    q_pos = idx * sq + jnp.arange(sq, dtype=jnp.int32)          # [Sq] global
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def accumulate(r, k_c, v_c, m, l, acc):
        # after r forward rotations, this device holds the chunk produced by
        # ring neighbor (idx - r) mod n — that fixes the keys' global positions
        src = (idx - r) % axis_size
        k_pos = src * skv + jnp.arange(skv, dtype=jnp.int32)    # [Skv] global

        scores = (
            jnp.einsum("bsgqd,bjgd->bgqsj", qg, k_c.astype(jnp.float32))
            * scale
        )
        causal = q_pos[:, None] >= k_pos[None, :]               # [Sq, Skv]
        valid = k_pos[None, None, :] < lengths[:, None, None]   # [B, 1, Skv]
        mask = (causal[None] & valid)[:, None, None, :, :]      # [B,1,1,Sq,Skv]
        scores = jnp.where(mask, scores, _NEG_INF)

        m_new = jnp.maximum(m, scores.max(axis=-1))             # [B,g,q,Sq]
        p = jnp.exp(scores - m_new[..., None]) * mask           # masked → 0
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bgqsj,bjgd->bgqsd", p, v_c.astype(jnp.float32)
        )
        return m_new, l_new, acc_new

    def round_body(r, carry):
        k_c, v_c, m, l, acc = carry
        m, l, acc = accumulate(r, k_c, v_c, m, l, acc)
        k_n = jax.lax.ppermute(k_c, axis_name, perm)
        v_n = jax.lax.ppermute(v_c, axis_name, perm)
        return (k_n, v_n, m, l, acc)

    m0 = jnp.full((b, hkv, qpk, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, qpk, sq), jnp.float32)
    acc0 = jnp.zeros((b, hkv, qpk, sq, d), jnp.float32)
    # n-1 compute+rotate rounds, then a final compute with no rotation — the
    # last hop's output would be discarded, so don't pay for it on ICI
    k_c, v_c, m, l, acc = jax.lax.fori_loop(
        0, axis_size - 1, round_body, (k, v, m0, l0, acc0)
    )
    m, l, acc = accumulate(axis_size - 1, k_c, v_c, m, l, acc)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.where((l > 0)[..., None], out, 0.0)               # padded queries
    return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, nh, d).astype(q.dtype)


def ring_self_attention(
    q: jax.Array,        # [B, S, Nh, D] — S divisible by mesh seq size
    k: jax.Array,        # [B, S, Hkv, D]
    v: jax.Array,        # [B, S, Hkv, D]
    lengths: jax.Array,  # [B]
    mesh: Mesh,
    shard_batch: bool = False,
) -> jax.Array:
    """Causal GQA self-attention with Q/K/V sharded over the ``seq`` axis.

    Jit-compatible: call inside ``jit`` with the mesh in scope, or directly.
    ``shard_batch=True`` additionally shards B over ``data``.
    """
    n = dict(mesh.shape).get(AXIS_SEQ, 1)
    if q.shape[1] % n:
        raise ValueError(f"seq len {q.shape[1]} not divisible by seq axis {n}")
    dspec = AXIS_DATA if shard_batch else None
    qkv_spec = P(dspec, AXIS_SEQ, None, None)
    fn = jax.shard_map(
        functools.partial(
            _ring_attention_local, axis_name=AXIS_SEQ, axis_size=n
        ),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, P(dspec)),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, lengths)


def _ulysses_local(
    q: jax.Array,        # [B, S/n, Nh, D] — this device's sequence shard
    k: jax.Array,        # [B, S/n, Hkv, D]
    v: jax.Array,        # [B, S/n, Hkv, D]
    lengths: jax.Array,  # [B] global valid lengths (replicated)
    axis_name: str,
) -> jax.Array:
    """Per-device body (runs under shard_map). → [B, S/n, Nh, D].

    a2a #1 scatters heads / gathers sequence → full-sequence attention over
    the local head group; a2a #2 restores the sequence sharding. Contiguous
    head splits keep GQA intact: device p owns query heads
    [p·Nh/n, (p+1)·Nh/n) and exactly their KV heads [p·Hkv/n, (p+1)·Hkv/n)
    (head h reads kv head h // qpk, and Nh/n = qpk · Hkv/n).
    """
    from distributed_gpu_inference_tpu.ops.attention import (
        dense_causal_attention,
    )

    q_full = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                                tiled=True)   # [B, S, Nh/n, D]
    k_full = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                                tiled=True)   # [B, S, Hkv/n, D]
    v_full = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                                tiled=True)
    out = dense_causal_attention(q_full, k_full, v_full, lengths)
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)     # [B, S/n, Nh, D]


def ulysses_self_attention(
    q: jax.Array,        # [B, S, Nh, D] — S divisible by mesh seq size
    k: jax.Array,        # [B, S, Hkv, D]
    v: jax.Array,        # [B, S, Hkv, D]
    lengths: jax.Array,  # [B]
    mesh: Mesh,
    shard_batch: bool = False,
) -> jax.Array:
    """Causal GQA self-attention, seq-sharded, Ulysses a2a strategy.

    Same contract as :func:`ring_self_attention`; requires
    ``num_kv_heads % seq_axis == 0``.
    """
    n = dict(mesh.shape).get(AXIS_SEQ, 1)
    if q.shape[1] % n:
        raise ValueError(f"seq len {q.shape[1]} not divisible by seq axis {n}")
    if k.shape[2] % n:
        raise ValueError(
            f"ulysses needs num_kv_heads {k.shape[2]} divisible by the seq "
            f"axis {n} (use ring_self_attention otherwise)"
        )
    dspec = AXIS_DATA if shard_batch else None
    qkv_spec = P(dspec, AXIS_SEQ, None, None)
    fn = jax.shard_map(
        functools.partial(_ulysses_local, axis_name=AXIS_SEQ),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, P(dspec)),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, lengths)


def _decode_local(
    q: jax.Array,        # [B, 1, Nh, D] (replicated over ring)
    k: jax.Array,        # [B, Skv, Hkv, D] — this device's context shard
    v: jax.Array,
    lengths: jax.Array,  # [B] global context lengths
    axis_name: str,
) -> jax.Array:
    idx = jax.lax.axis_index(axis_name)
    b, sq, nh, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    qpk = nh // hkv
    qg = q.reshape(b, sq, hkv, qpk, d).astype(jnp.float32)
    k_pos = idx * skv + jnp.arange(skv, dtype=jnp.int32)

    scores = (
        jnp.einsum("bsgqd,bjgd->bgqsj", qg, k.astype(jnp.float32)) * d**-0.5
    )
    valid = (k_pos[None, :] < lengths[:, None])[:, None, None, None, :]
    scores = jnp.where(valid, scores, _NEG_INF)

    m_loc = scores.max(axis=-1)
    m = jax.lax.pmax(m_loc, axis_name)                          # global max
    p = jnp.exp(scores - m[..., None]) * valid
    l = jax.lax.psum(p.sum(axis=-1), axis_name)
    acc = jax.lax.psum(
        jnp.einsum("bgqsj,bjgd->bgqsd", p, v.astype(jnp.float32)), axis_name
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.where((l > 0)[..., None], out, 0.0)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, nh, d).astype(q.dtype)


def _shard_map_paged(local, mesh, base_specs, args, k_scale, v_scale):
    """Build + call the shard_map for a paged partial-softmax op, appending
    the int8 scale-pool operands (block-axis-sharded exactly like their
    data pools) when the pool is quantized — the ONE place the quant wiring
    for the seq-sharded ops lives."""
    if k_scale is not None:
        def body(*a):
            *base, ks_, vs_ = a
            return local(*base, k_scale=ks_, v_scale=vs_)

        in_specs = base_specs + (
            P(AXIS_SEQ, None, None), P(AXIS_SEQ, None, None),
        )
        args = args + (k_scale, v_scale)
    else:
        body, in_specs = local, base_specs
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(None, None, None, None),
        check_vma=False,
    )
    return fn(*args)


def _paged_decode_local(
    q: jax.Array,            # [B, 1, Nh, D] (replicated over the seq axis)
    k_shard: jax.Array,      # [Nloc, Hkv, Bk, D] — this device's pool shard
    v_shard: jax.Array,
    block_tables: jax.Array,  # [B, M] GLOBAL physical block ids (replicated)
    positions: jax.Array,    # [B] query positions (-1 = inactive)
    kv_lens: jax.Array,      # [B] global context lengths
    axis_name: str,
    block_size: int,
    k_scale: Optional[jax.Array] = None,  # [Nloc, Bk, D] bf16 — int8 pools
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-device body: attend over the LOCAL subset of each sequence's
    pages, then merge the partial (max, sum, acc) across the axis.

    ``k_scale``/``v_scale``: int8 pools' per-(page, token) scale shards —
    they ride the same block axis as their data pools, so dequantization is
    entirely local (same arithmetic as ``ops.attention._gather_ctx``: bf16
    cast then multiply, keeping numerics identical to the single-chip read).
    """
    idx = jax.lax.axis_index(axis_name)
    b, _, nh, d = q.shape
    nloc, hkv = k_shard.shape[0], k_shard.shape[1]
    qpk = nh // hkv
    m = block_tables.shape[1]
    j = m * block_size

    # global page id → local shard slot; out-of-shard pages gather slot 0
    # and are masked out of the softmax
    local = block_tables - idx * nloc                       # [B, M]
    in_shard = (local >= 0) & (local < nloc)
    safe = jnp.where(in_shard, local, 0)
    # [B, M, Hkv, Bk, D] → [B, J, Hkv, D] token-major context
    k_ctx = jnp.take(k_shard, safe, axis=0).transpose(0, 1, 3, 2, 4).reshape(
        b, j, hkv, d
    )
    v_ctx = jnp.take(v_shard, safe, axis=0).transpose(0, 1, 3, 2, 4).reshape(
        b, j, hkv, d
    )
    if k_scale is not None:
        from distributed_gpu_inference_tpu.ops.attention import dequantize_kv

        ks_ctx = jnp.take(k_scale, safe, axis=0).reshape(b, j, d)
        vs_ctx = jnp.take(v_scale, safe, axis=0).reshape(b, j, d)
        k_ctx = dequantize_kv(k_ctx, ks_ctx[:, :, None, :])
        v_ctx = dequantize_kv(v_ctx, vs_ctx[:, :, None, :])

    qg = q.reshape(b, 1, hkv, qpk, d).astype(jnp.float32)
    scores = jnp.einsum(
        "bsgqd,bjgd->bgqsj", qg, k_ctx.astype(jnp.float32)
    ) * (d**-0.5)                                           # [B,Hkv,qpk,1,J]

    key_pos = jnp.arange(j, dtype=jnp.int32)[None, :]       # [1, J]
    visible = (
        (key_pos < kv_lens[:, None])
        & (key_pos <= positions[:, None])
        & jnp.repeat(in_shard, block_size, axis=1)
    )                                                       # [B, J]
    mask = visible[:, None, None, None, :]
    scores = jnp.where(mask, scores, _NEG_INF)

    m_loc = scores.max(axis=-1)
    m_glob = jax.lax.pmax(m_loc, axis_name)
    p = jnp.exp(scores - m_glob[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jax.lax.psum(p.sum(axis=-1), axis_name)
    acc = jax.lax.psum(
        jnp.einsum("bgqsj,bjgd->bgqsd", p, v_ctx.astype(jnp.float32)),
        axis_name,
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.where((l > 0)[..., None], out, 0.0)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, 1, nh, d).astype(q.dtype)


def seq_parallel_paged_decode_attention(
    q: jax.Array,             # [B, 1, Nh, D]
    k_pool: jax.Array,        # [N, Hkv, Bk, D] — sharded over ``seq`` on N
    v_pool: jax.Array,
    block_tables: jax.Array,  # [B, M] int32 global block ids
    positions: jax.Array,     # [B, 1] int32 (-1 = inactive)
    kv_lens: jax.Array,       # [B]
    mesh: Mesh,
    block_size: int = 16,
    k_scale: Optional[jax.Array] = None,  # [N, Bk, D] — sharded like pools
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Decode attention over a PAGED pool whose block axis is sharded over
    the ``seq`` mesh axis — the memory-scaling completion of ring prefill
    (SURVEY §5.7): each device stores and reads only its block range, and
    one pmax + two psum merge the partial softmax ([B, Nh, D]-sized partials
    cross ICI; pages never move).

    Semantics match ``ops.attention.paged_attention_xla`` over the same pool
    (causal by ``positions``, bounded by ``kv_lens``, inactive rows zero),
    including int8 pools when ``k_scale``/``v_scale`` are given (scale
    shards ride the block axis; dequantization is local to each device).
    The pool's N must divide evenly by the seq axis.
    """
    n = dict(mesh.shape).get(AXIS_SEQ, 1)
    if k_pool.shape[0] % n:
        raise ValueError(
            f"pool blocks {k_pool.shape[0]} not divisible by seq axis {n}"
        )
    local = functools.partial(
        _paged_decode_local, axis_name=AXIS_SEQ, block_size=block_size
    )
    base_specs = (
        P(None, None, None, None),
        P(AXIS_SEQ, None, None, None),
        P(AXIS_SEQ, None, None, None),
        P(None, None),
        P(None),
        P(None),
    )
    args = (
        q, k_pool, v_pool, block_tables.astype(jnp.int32),
        positions[:, 0].astype(jnp.int32), kv_lens.astype(jnp.int32),
    )
    return _shard_map_paged(local, mesh, base_specs, args, k_scale, v_scale)


def _paged_chunk_local(
    q: jax.Array,            # [B, S, Nh, D] (replicated over the seq axis)
    k_shard: jax.Array,      # [Nloc, Hkv, Bk, D] — this device's pool shard
    v_shard: jax.Array,
    block_tables: jax.Array,  # [B, M] GLOBAL physical block ids (replicated)
    positions: jax.Array,    # [B, S] query positions (-1 = padding)
    kv_lens: jax.Array,      # [B] context lengths AFTER the chunk
    axis_name: str,
    block_size: int,
    pages_per_step: int,
    k_scale: Optional[jax.Array] = None,  # [Nloc, Bk, D] bf16 — int8 pools
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Chunk (q_len ≥ 1) attention over the LOCAL pool shard, flash-style:
    a ``lax.scan`` over page groups keeps the per-step score tile at
    [B, Nh, S, G·Bk] instead of materializing [S, whole-context] — the
    long-context case this op exists for. Partial (m, l, acc) merge across
    the axis afterwards, exactly like the decode op. int8 pools dequantize
    per page group inside the scan (``_gather_ctx`` arithmetic), so the
    dequantized tile never exceeds [B, G·Bk, Hkv, D]."""
    idx = jax.lax.axis_index(axis_name)
    b, s, nh, d = q.shape
    nloc, hkv = k_shard.shape[0], k_shard.shape[1]
    qpk = nh // hkv
    m_tab = block_tables.shape[1]
    g = min(pages_per_step, m_tab)
    n_steps = -(-m_tab // g)
    pad = n_steps * g - m_tab
    # pad the table with out-of-shard ids (masked): every scan step sees G
    tables_p = jnp.pad(block_tables, ((0, 0), (0, pad)),
                       constant_values=-1)

    local = tables_p - idx * nloc                            # [B, M']
    in_shard = (local >= 0) & (local < nloc) & (tables_p >= 0)
    safe = jnp.where(in_shard, local, 0)

    qg = q.reshape(b, s, hkv, qpk, d).astype(jnp.float32)
    scale = d**-0.5
    qpos = positions                                         # [B, S]

    def step(carry, grp):
        m_run, l_run, acc = carry
        ids, shard_ok, page0 = grp                           # [B,G],[B,G],[]
        # [B, G, Hkv, Bk, D] → [B, G*Bk, Hkv, D]
        k_ctx = jnp.take(k_shard, ids, axis=0).transpose(
            0, 1, 3, 2, 4
        ).reshape(b, g * block_size, hkv, d)
        v_ctx = jnp.take(v_shard, ids, axis=0).transpose(
            0, 1, 3, 2, 4
        ).reshape(b, g * block_size, hkv, d)
        if k_scale is not None:
            from distributed_gpu_inference_tpu.ops.attention import (
                dequantize_kv,
            )

            ks_ctx = jnp.take(k_scale, ids, axis=0).reshape(
                b, g * block_size, d)
            vs_ctx = jnp.take(v_scale, ids, axis=0).reshape(
                b, g * block_size, d)
            k_ctx = dequantize_kv(k_ctx, ks_ctx[:, :, None, :])
            v_ctx = dequantize_kv(v_ctx, vs_ctx[:, :, None, :])
        key_pos = (
            page0 * block_size
            + jnp.arange(g * block_size, dtype=jnp.int32)
        )[None, :]                                           # [1, G*Bk]
        scores = jnp.einsum(
            "bsgqd,bjgd->bgqsj", qg, k_ctx.astype(jnp.float32)
        ) * scale                                            # [B,Hkv,qpk,S,J]
        visible = (
            (key_pos < kv_lens[:, None])[:, None, :]         # [B, 1, J]
            & (key_pos[:, None, :] <= qpos[:, :, None])      # [B, S, J]
            & jnp.repeat(shard_ok, block_size, axis=1)[:, None, :]
        )                                                    # [B, S, J]
        mask = visible[:, None, None, :, :]
        scores = jnp.where(mask, scores, _NEG_INF)
        m_new = jnp.maximum(m_run, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_run - m_new)
        l_new = l_run * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bgqsj,bjgd->bgqsd", p, v_ctx.astype(jnp.float32)
        )
        return (m_new, l_new, acc_new), None

    ids_g = safe.reshape(b, n_steps, g).transpose(1, 0, 2)
    ok_g = in_shard.reshape(b, n_steps, g).transpose(1, 0, 2)
    page0_g = jnp.arange(n_steps, dtype=jnp.int32) * g
    m0 = jnp.full((b, hkv, qpk, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, qpk, s), jnp.float32)
    acc0 = jnp.zeros((b, hkv, qpk, s, d), jnp.float32)
    (m_loc, l_loc, acc_loc), _ = jax.lax.scan(
        step, (m0, l0, acc0), (ids_g, ok_g, page0_g)
    )
    # cross-device partial-softmax merge (same math as the decode op)
    m_glob = jax.lax.pmax(m_loc, axis_name)
    corr = jnp.exp(m_loc - m_glob)
    l = jax.lax.psum(l_loc * corr, axis_name)
    acc = jax.lax.psum(acc_loc * corr[..., None], axis_name)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.where((l > 0)[..., None], out, 0.0)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, s, nh, d).astype(q.dtype)


def seq_parallel_paged_chunk_attention(
    q: jax.Array,             # [B, S, Nh, D]
    k_pool: jax.Array,        # [N, Hkv, Bk, D] — sharded over ``seq`` on N
    v_pool: jax.Array,
    block_tables: jax.Array,  # [B, M] int32 global block ids
    positions: jax.Array,     # [B, S] int32 (-1 = padding)
    kv_lens: jax.Array,       # [B]
    mesh: Mesh,
    block_size: int = 16,
    pages_per_step: int = 16,
    k_scale: Optional[jax.Array] = None,  # [N, Bk, D] — sharded like pools
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Chunk attention (q_len ≥ 1) over a seq-sharded paged pool — what lets
    ``kv_seq_sharded`` engines serve PREFIX-CACHED and CHUNKED/continuation
    prompts (VERDICT r3 #6): the chunk's KV is already written to the
    (sharded) pool by the layer step, so one partial-softmax read over the
    block axis covers cached prefix + prior chunks + in-chunk causal keys.
    Generalizes :func:`seq_parallel_paged_decode_attention` (S = 1) with a
    flash-style page-group scan so long contexts never materialize
    [S, ctx] scores. ``k_scale``/``v_scale`` (int8 pools) shard with their
    data pools and dequantize locally."""
    n = dict(mesh.shape).get(AXIS_SEQ, 1)
    if k_pool.shape[0] % n:
        raise ValueError(
            f"pool blocks {k_pool.shape[0]} not divisible by seq axis {n}"
        )
    local = functools.partial(
        _paged_chunk_local, axis_name=AXIS_SEQ, block_size=block_size,
        pages_per_step=pages_per_step,
    )
    base_specs = (
        P(None, None, None, None),
        P(AXIS_SEQ, None, None, None),
        P(AXIS_SEQ, None, None, None),
        P(None, None),
        P(None, None),
        P(None),
    )
    args = (
        q, k_pool, v_pool, block_tables.astype(jnp.int32),
        positions.astype(jnp.int32), kv_lens.astype(jnp.int32),
    )
    return _shard_map_paged(local, mesh, base_specs, args, k_scale, v_scale)


def seq_parallel_decode_attention(
    q: jax.Array,        # [B, 1, Nh, D]
    k: jax.Array,        # [B, Sctx, Hkv, D] — full context, sharded by caller
    v: jax.Array,
    lengths: jax.Array,  # [B]
    mesh: Mesh,
) -> jax.Array:
    """Decode attention against seq-sharded context KV.

    One ``pmax`` + two ``psum`` merge the per-shard partial softmax — the
    decode-side counterpart of ring prefill (KV never moves; only the
    [B,Nh,D]-sized partials cross ICI).
    """
    n = dict(mesh.shape).get(AXIS_SEQ, 1)
    if k.shape[1] % n:
        raise ValueError(f"ctx len {k.shape[1]} not divisible by seq axis {n}")
    fn = jax.shard_map(
        functools.partial(_decode_local, axis_name=AXIS_SEQ),
        mesh=mesh,
        in_specs=(
            P(None, None, None, None),
            P(None, AXIS_SEQ, None, None),
            P(None, AXIS_SEQ, None, None),
            P(None),
        ),
        out_specs=P(None, None, None, None),
        check_vma=False,
    )
    return fn(q, k, v, lengths)
