"""GSPMD sharding rules for the Llama params pytree, KV pools, and batch state.

Megatron-style tensor parallelism expressed as NamedShardings — XLA inserts
the all-reduces over the ``model`` ICI axis (no hand-written collectives in
the forward pass). This replaces the reference's TP-by-delegation
(``worker/engines/llm_vllm.py:56`` just forwards ``tensor_parallel_size`` to
vLLM's process groups; SURVEY §2.2 flags it as passthrough-only).

Layout (params from ``models/llama.py``; L = stacked layer axis):

==================  ===========================  ==========================
param               shape                        spec
==================  ===========================  ==========================
embedding           [V, H]                       replicated
layers.attn_norm    [L, H]                       replicated
layers.wq           [L, H, Nh*D]                 shard out dim on ``model``
layers.wk / wv      [L, H, Nkv*D]                shard out dim on ``model``
layers.wo           [L, Nh*D, H]                 shard in dim on ``model``
layers.w_gate/up    [L, H, I]                    shard out dim on ``model``
layers.w_down       [L, I, H]                    shard in dim on ``model``
final_norm          [H]                          replicated
lm_head             [V, H]                       replicated
kv pools            [L, N, Hkv, Bk, D]           shard Hkv on ``model``
tokens/tables/lens  [B, ...]                     shard B on ``data``
==================  ===========================  ==========================

Pipeline (``stage``) sharding slices the L axis instead — see
``parallel/pipeline.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_gpu_inference_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SEQ,
)


def _ns(mesh: Mesh, *spec) -> NamedSharding:
    # drop axis names the mesh doesn't carry (trivial axes removed)
    clean = tuple(s if (s is None or s in mesh.axis_names) else None for s in spec)
    return NamedSharding(mesh, P(*clean))


def param_shardings(mesh: Mesh) -> Dict[str, Any]:
    """NamedSharding pytree matching ``models.llama.init_params`` layout."""
    return {
        "embedding": _ns(mesh, None, None),
        "layers": {
            "attn_norm": _ns(mesh, None, None),
            "wq": _ns(mesh, None, None, AXIS_MODEL),
            "wk": _ns(mesh, None, None, AXIS_MODEL),
            "wv": _ns(mesh, None, None, AXIS_MODEL),
            "wo": _ns(mesh, None, AXIS_MODEL, None),
            # Qwen2-style attention biases follow their projection's out dim
            "bq": _ns(mesh, None, AXIS_MODEL),
            "bk": _ns(mesh, None, AXIS_MODEL),
            "bv": _ns(mesh, None, AXIS_MODEL),
            "mlp_norm": _ns(mesh, None, None),
            "w_gate": _ns(mesh, None, None, AXIS_MODEL),
            "w_up": _ns(mesh, None, None, AXIS_MODEL),
            "w_down": _ns(mesh, None, AXIS_MODEL, None),
            # MoE: expert parallelism = shard the E axis over ``model``;
            # each chip computes its local experts, XLA all-reduces the
            # combine (models/llama.py _moe_mlp). Router replicated — every
            # chip needs all routing weights.
            "w_router": _ns(mesh, None, None, None),
            "we_gate": _ns(mesh, None, AXIS_MODEL, None, None),
            "we_up": _ns(mesh, None, AXIS_MODEL, None, None),
            "we_down": _ns(mesh, None, AXIS_MODEL, None, None),
        },
        "final_norm": _ns(mesh, None),
        "lm_head": _ns(mesh, None, None),
    }


def kv_sharding(mesh: Mesh) -> NamedSharding:
    """KV pools [L, N, Hkv, Bk, D]: heads sharded over ``model`` so each TP
    shard attends with its own KV heads — pages never cross chips."""
    return _ns(mesh, None, None, AXIS_MODEL, None, None)


def kv_sharding_seq(mesh: Mesh) -> NamedSharding:
    """KV pools with the BLOCK axis sharded over ``seq`` (heads still over
    ``model``): per-device pool memory scales 1/seq — the storage side of
    long-context serving (decode reads via
    ``ring_attention.seq_parallel_paged_decode_attention``; page writes are
    GSPMD-partitioned scatters, verified to keep this sharding without
    replication)."""
    return _ns(mesh, None, AXIS_SEQ, AXIS_MODEL, None, None)


def kv_scale_sharding(mesh: Mesh) -> NamedSharding:
    """int8-KV scale pools [L, N, Bk, D]: one scale per (page, token)
    shared across KV heads, so there is no head axis to shard — the scale
    pool rides replicated next to head-sharded data pools (it is Hkv x
    smaller, so replication costs less HBM than data-pool sharding saves).
    The quantize amax reduces over ALL heads (a cross-shard reduce XLA
    lowers to an all-reduce-max over ``model``), keeping scales — and
    therefore the stored int8 — bit-identical to a single-chip engine."""
    return _ns(mesh, None, None, None, None)


def kv_scale_sharding_seq(mesh: Mesh) -> NamedSharding:
    """int8-KV scale pools under seq-sharded data pools: the scale pool's
    BLOCK axis shards over ``seq`` exactly like its data pool, so a (page,
    token)'s scale lives on the same device as its int8 rows and the
    shard_map partial-softmax ops dequantize locally — no scale traffic."""
    return _ns(mesh, None, AXIS_SEQ, None, None)


def batch_shardings(mesh: Mesh) -> Dict[str, NamedSharding]:
    return {
        "tokens": _ns(mesh, AXIS_DATA, None),       # [B, S]
        "positions": _ns(mesh, AXIS_DATA, None),    # [B, S]
        "block_tables": _ns(mesh, AXIS_DATA, None), # [B, M]
        "kv_lens": _ns(mesh, AXIS_DATA),            # [B]
        "vec": _ns(mesh, AXIS_DATA),                # any per-seq vector
        "replicated": _ns(mesh),
    }


def _quantized_leaf_rules(rule: NamedSharding, leaf: Dict[str, Any]) -> Dict[str, Any]:
    """Expand a weight's sharding rule over a quantized ``{"qw","scale"}``
    sub-dict: qw keeps the weight spec; the scale drops axis names wherever
    its (size-1, reduced) dims can't carry a shard."""
    spec = tuple(rule.spec) + (None,) * (leaf["qw"].ndim - len(tuple(rule.spec)))
    scale_spec = tuple(
        s if (i < leaf["scale"].ndim and leaf["scale"].shape[i] > 1) else None
        for i, s in enumerate(spec)
    )
    return {
        "qw": rule,
        "scale": NamedSharding(rule.mesh, P(*scale_spec)),
    }


def prune_rules(rules: Dict[str, Any], params: Dict[str, Any]) -> Dict[str, Any]:
    """Restrict a sharding-rule pytree to the keys this model actually has
    (lm_head absent when tied; bias keys absent for bias-free families), and
    expand rules over quantized weight sub-dicts so the rule tree's structure
    matches the params tree exactly. Shared by the TP and pipeline pruners so
    they cannot drift."""
    rules = dict(rules)
    rules["layers"] = {
        k: (_quantized_leaf_rules(v, params["layers"][k])
            if isinstance(params["layers"][k], dict) else v)
        for k, v in rules["layers"].items() if k in params["layers"]
    }
    if "lm_head" not in params:
        rules.pop("lm_head", None)
    return rules


def shard_params(params: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    """device_put the params pytree onto the mesh under the TP rules.

    (With single-host multi-device this is a local reshard; multi-host uses
    the same rules via jax.make_array_from_process_local_data in the loader.)
    """
    return jax.device_put(params, prune_rules(param_shardings(mesh), params))


def shard_kv(kv: Dict[str, jax.Array], mesh: Mesh) -> Dict[str, jax.Array]:
    s = kv_sharding(mesh)
    return {k: jax.device_put(v, s) for k, v in kv.items()}
