"""Worker-side configuration system.

Capability parity with the reference's ``worker/config.py`` (WorkerConfig:60,
ServerConfig:29, GPUConfig:36 → TpuConfig here, DirectConfig:43,
LoadControlConfig:51; precedence env > yaml > defaults :138-170; dotenv
loader :110-135; per-engine model config from env :173-188;
DEFAULT_ENGINE_CONFIGS:191).

TPU-first deltas: the accelerator section describes a TPU mesh (chip type,
requested mesh shape and axis names for dp/tp/pp/sp) instead of CUDA device
ids; engine defaults point at the JAX engine family rather than
vLLM/SGLang backends.
"""

from __future__ import annotations

import logging
import os
from pathlib import Path
from typing import Any, Dict, List, Optional, Set, Tuple

import yaml
from pydantic import BaseModel, Field, model_validator

from distributed_gpu_inference_tpu.utils.data_structures import KV_BLOCK_TOKENS

log = logging.getLogger(__name__)

ENV_PREFIX = "TPU_WORKER_"

# Serving knobs obsoleted by the round-6 ragged serving path (one kernel
# invocation carrying prefill-chunk AND decode rows — admission appends
# rows to the next round instead of scheduling competing dispatches, so
# the admission-stall shaping these knobs tuned no longer exists). They
# stay ACCEPTED in worker YAML and remote pushes (rolling fleets, saved
# SLO configs) but are warned once per process; only the legacy path
# (``serving.ragged: false``) still reads them.
DEPRECATED_SERVING_KEYS: Dict[str, str] = {
    "subwave": (
        "the ragged serving path admits by appending chunk rows to the "
        "next decode round — there are no admission sub-waves to shape; "
        "only the legacy path (serving.ragged: false) reads this"
    ),
    "interleave": (
        "prefill chunks co-dispatch WITH decode rows in a ragged round — "
        "there are no separate dispatches left to interleave; only the "
        "legacy path (serving.ragged: false) reads this"
    ),
    "max_horizon": (
        "still caps the pure-decode scan horizon, but it is no longer the "
        "TTFT-shaping knob: admission latency is bounded by the ragged "
        "round itself, not by capping decode-scan depth"
    ),
}
_deprecated_serving_warned: Set[str] = set()


def warn_deprecated_serving_key(key: str, source: str) -> None:
    """One-time (per process, per key) deprecation warning for obsoleted
    serving knobs — the keys keep working so existing YAML and saved
    remote configs deploy unchanged, but operators learn the knob is
    degenerate under ragged serving."""
    if key not in DEPRECATED_SERVING_KEYS \
            or key in _deprecated_serving_warned:
        return
    _deprecated_serving_warned.add(key)
    log.warning(
        "serving.%s (%s) is deprecated since the ragged serving round: %s",
        key, source, DEPRECATED_SERVING_KEYS[key],
    )


class ServerConfig(BaseModel):
    """Control-plane endpoint + credentials (reference ServerConfig:29)."""

    url: str = "http://127.0.0.1:8000"
    fallback_urls: List[str] = Field(default_factory=list)
    api_key: Optional[str] = None
    worker_id: Optional[str] = None
    auth_token: Optional[str] = None
    refresh_token: Optional[str] = None
    signing_secret: Optional[str] = None
    request_timeout_s: float = 30.0
    verify_tls: bool = True


class TpuConfig(BaseModel):
    """Accelerator resources (replaces reference GPUConfig:36)."""

    chip_type: str = "auto"             # auto-detect from jax.devices()
    mesh_shape: Optional[List[int]] = None   # None → (num_devices,)
    mesh_axis_names: List[str] = Field(default_factory=lambda: ["data"])
    hbm_utilization: float = 0.9        # fraction of HBM the KV pool may claim
    kv_cache_block_tokens: int = KV_BLOCK_TOKENS
    max_model_len: int = 8192
    dtype: str = "bfloat16"


class DirectConfig(BaseModel):
    """Worker-hosted direct inference endpoint (reference DirectConfig:43)."""

    enabled: bool = False
    host: str = "0.0.0.0"
    port: int = 8471
    public_url: Optional[str] = None


class LoadControlConfig(BaseModel):
    """Volunteer-friendly load shaping (reference LoadControlConfig:51)."""

    acceptance_rate: float = 1.0
    max_concurrent_jobs: int = 4
    max_jobs_per_hour: int = 0          # 0 = unlimited
    hbm_limit_fraction: float = 0.95
    working_hours: Optional[Tuple[int, int]] = None   # (start_h, end_h) local
    job_type_weights: Dict[str, float] = Field(default_factory=dict)
    cooldown_seconds: float = 0.0


class ServingConfig(BaseModel):
    """Batcher-backed serving front-end (``engines.<type>.serving.*``) —
    the SLO knobs, now first-class worker YAML keys
    (``worker/engines/llm.py`` SERVING_DEFAULTS mirrors these).

    Since round 6 the default serving path runs RAGGED rounds (prefill
    chunk rows and decode rows in one kernel dispatch), which obsoletes
    the admission-stall shaping knobs: ``subwave`` / ``interleave`` /
    ``max_horizon`` are still accepted (and ``max_horizon`` still caps the
    pure-decode scan) but log a one-time deprecation warning when set —
    see ``DEPRECATED_SERVING_KEYS``. ``target_step_ms`` / ``queue_limit``
    / ``max_wait_ms`` / ``ragged`` are remote-pushable (server
    ``WorkerRemoteConfig.serving``) and retune a LIVE batcher;
    ``subwave`` / ``interleave`` / ``mode`` are compile-affecting and
    apply at engine load only."""

    mode: str = "batcher"               # batcher | direct (legacy driving)
    target_step_ms: float = 100.0       # adaptive round-latency target
    max_horizon: int = 64               # decode-scan cap (DEPRECATED knob)
    min_horizon: int = 1
    multi_step: int = 8                 # initial decode horizon
    adaptive: bool = True
    max_wait_ms: float = 5.0            # admission latch
    queue_limit: int = 1024
    default_timeout_s: float = 300.0
    max_preemptions: int = 3
    subwave: int = 0                    # DEPRECATED (legacy path only)
    interleave: int = 0                 # DEPRECATED (legacy path only)
    spec_max_batch: int = 2
    spec_max_active: int = 2
    # ragged rounds: None = auto (ragged whenever the engine supports it —
    # THE default serving path), False = force the legacy wave/chunk-
    # interleaved admission (A/B benchmarking), True = require ragged
    ragged: Optional[bool] = None
    # per-ROUND prefill token budget for ragged rounds: caps how many fresh
    # prompt tokens all concurrent admissions may prefill in one round
    # combined (fair water-fill split), so a 32k admission streams in over
    # many rounds instead of monopolizing every round's chunk bucket.
    # 0 = unbudgeted (pre-budget behavior). Remote-pushable.
    prefill_budget: int = 0
    # per-admission prefill chunk width override (engine ragged_chunk).
    # Read per-round and bucketed through compiled prefill widths, so it is
    # safe to retune live. None = keep the engine default. Remote-pushable.
    ragged_chunk: Optional[int] = None
    # hopeless-work abandonment (gray-failure round): when True the batcher
    # drops deadline-carrying work whose deadline has passed AND whose
    # projected remaining decode cannot land within ``deadline_grace_s``
    # (typed ``deadline_abandoned`` error; blocks freed at the next step
    # boundary). Never fires for deadline-less requests. Remote-pushable.
    abandon_deadlines: bool = False
    deadline_grace_s: float = 0.5
    # predictive abandonment (round 18): the same ITL projection fires
    # BEFORE the deadline passes, so a job that provably cannot land stops
    # burning ragged-round slots immediately (counted separately as
    # ``abandoned_predictive``). Requires abandon_deadlines. Remote-pushable.
    predictive_abandon: bool = False

    @model_validator(mode="after")
    def _warn_deprecated(self) -> "ServingConfig":
        for key in self.model_fields_set & DEPRECATED_SERVING_KEYS.keys():
            warn_deprecated_serving_key(key, "worker YAML")
        return self


class EngineModelConfig(BaseModel):
    """Per-task-type engine/model selection (reference :173-188)."""

    engine: str = "jax"                 # jax | jax-speculative | echo (tests)
    model: str = "llama3-tiny"
    dtype: str = "bfloat16"
    quantization: Optional[str] = None  # int8 | fp8 | None
    serving: Optional[ServingConfig] = None   # None → engine defaults
    extra: Dict[str, Any] = Field(default_factory=dict)


DEFAULT_ENGINE_CONFIGS: Dict[str, EngineModelConfig] = {
    "llm": EngineModelConfig(engine="jax", model="llama3-8b"),
    "embedding": EngineModelConfig(engine="jax-embedding", model="llama3-8b"),
    "vision": EngineModelConfig(engine="jax-vision", model="llama3-8b-vision"),
    "image_gen": EngineModelConfig(engine="jax-diffusion", model="tiny-diffusion"),
    "whisper": EngineModelConfig(engine="jax-whisper", model="tiny-whisper"),
}


class WorkerConfig(BaseModel):
    """Root worker configuration (reference WorkerConfig:60)."""

    name: str = "tpu-worker"
    region: str = "us-central"
    task_types: List[str] = Field(default_factory=lambda: ["llm"])
    # PD disaggregation role (reference pd_scheduler WorkerCapability roles):
    # "prefill" | "decode" | "hybrid". Decode-capable workers should also set
    # pd_data_plane_url so prefill peers can push KV handoffs to them.
    role: str = "hybrid"
    pd_data_plane_url: Optional[str] = None
    server: ServerConfig = Field(default_factory=ServerConfig)
    tpu: TpuConfig = Field(default_factory=TpuConfig)
    direct: DirectConfig = Field(default_factory=DirectConfig)
    load_control: LoadControlConfig = Field(default_factory=LoadControlConfig)
    engines: Dict[str, EngineModelConfig] = Field(default_factory=dict)
    poll_interval_s: float = 2.0
    heartbeat_interval_s: float = 30.0
    log_level: str = "INFO"
    config_version: int = 0             # server-pushed remote config version

    def engine_for(self, task_type: str) -> EngineModelConfig:
        if task_type in self.engines:
            return self.engines[task_type]
        if task_type in DEFAULT_ENGINE_CONFIGS:
            # deep copy: callers may mutate; the process-wide defaults must not
            return DEFAULT_ENGINE_CONFIGS[task_type].model_copy(deep=True)
        raise KeyError(f"no engine config for task type {task_type!r}")


# ---------------------------------------------------------------------------
# Loading: defaults < yaml < env  (reference precedence :138-170)
# ---------------------------------------------------------------------------


def load_dotenv(path: str | Path = ".env", override: bool = False) -> Dict[str, str]:
    """Minimal dotenv loader (reference hand-rolled loader :110-135)."""
    loaded: Dict[str, str] = {}
    p = Path(path)
    if not p.exists():
        return loaded
    for line in p.read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        key, _, val = line.partition("=")
        key, val = key.strip(), val.strip().strip("'\"")
        if override or key not in os.environ:
            os.environ[key] = val
        loaded[key] = val
    return loaded


def _deep_update(base: Dict[str, Any], upd: Dict[str, Any]) -> Dict[str, Any]:
    for k, v in upd.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            _deep_update(base[k], v)
        else:
            base[k] = v
    return base


def _env_overrides(environ: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """TPU_WORKER_SERVER__URL=... → {"server": {"url": ...}} (``__`` nests).

    Values stay strings except JSON/YAML-looking composites — pydantic performs
    the per-field numeric/bool coercion, so a numeric-looking API key or worker
    name is not corrupted into an int.
    """
    environ = os.environ if environ is None else environ
    out: Dict[str, Any] = {}
    for key, raw in environ.items():
        if not key.startswith(ENV_PREFIX):
            continue
        path = key[len(ENV_PREFIX):].lower().split("__")
        val: Any = raw
        if raw.startswith(("[", "{")):
            try:
                val = yaml.safe_load(raw)
            except Exception:
                pass
        node = out
        for part in path[:-1]:
            node = node.setdefault(part, {})
        node[path[-1]] = val
    return out


def load_worker_config(
    yaml_path: Optional[str | Path] = None,
    environ: Optional[Dict[str, str]] = None,
    dotenv_path: str | Path = ".env",
    missing_ok: bool = False,
) -> WorkerConfig:
    """Build a WorkerConfig with precedence env > yaml > defaults.

    A ``yaml_path`` that does not exist raises unless ``missing_ok=True``
    (workers booting for the first time pass missing_ok for the default path).
    ``.env`` is only folded into the process environment when reading from it
    (``environ is None``) — an explicit environ mapping keeps the call hermetic.
    """
    if environ is None:
        load_dotenv(dotenv_path)
    data: Dict[str, Any] = {}
    if yaml_path is not None:
        p = Path(yaml_path)
        if p.exists():
            with open(p) as f:
                file_data = yaml.safe_load(f) or {}
            if not isinstance(file_data, dict):
                raise ValueError(f"config file {yaml_path} must contain a mapping")
            _deep_update(data, file_data)
        elif not missing_ok:
            raise FileNotFoundError(f"config file not found: {yaml_path}")
    _deep_update(data, _env_overrides(environ))
    return WorkerConfig.model_validate(data)


def save_worker_config(cfg: WorkerConfig, yaml_path: str | Path) -> None:
    """Persist config (the worker writes issued credentials back after
    registration — reference main.py:133-136). Atomic temp+fsync+rename:
    this file carries ISSUED CREDENTIALS — a crash or disk-full torn write
    mid-save must leave the previous config intact, never a truncated one
    that locks the worker out on restart (round 19)."""
    from distributed_gpu_inference_tpu.runtime.io_guard import (
        atomic_write_text,
    )

    path = Path(yaml_path)
    path.parent.mkdir(parents=True, exist_ok=True)
    atomic_write_text(
        path, yaml.safe_dump(cfg.model_dump(mode="json"), sort_keys=False)
    )


def set_dotted(cfg: WorkerConfig, dotted_key: str, value: Any) -> WorkerConfig:
    """`gpu-worker set server.url http://…` style dotted update
    (reference cli.py:790)."""
    data = cfg.model_dump()
    node = data
    parts = dotted_key.split(".")
    for p in parts[:-1]:
        if p not in node or not isinstance(node[p], dict):
            raise KeyError(f"unknown config section {p!r} in {dotted_key!r}")
        node = node[p]
    if parts[-1] not in node:
        raise KeyError(f"unknown config key {dotted_key!r}")
    node[parts[-1]] = value
    return WorkerConfig.model_validate(data)
