"""Tensor wire framing for DCN / WAN hops.

Capability parity with the reference's ``common/serialization.py``
(TensorSerializer.serialize:55/deserialize:106, serialize_tensor:163 base64
dict for JSON transport, StreamingTensorBuffer:209 with 1 MB chunks) —
re-designed TPU-first:

- **In-slice hops never serialize.** Activations and KV pages move between
  chips inside jitted graphs via ICI collectives (see ``parallel/``); this
  module only frames tensors that cross DCN or the WAN (control plane, cold KV
  tiers, cross-host pipeline hops).
- **bfloat16 is a first-class wire dtype** (via ml_dtypes), not a float16
  round-trip carrier like the reference's :73-76 — TPU's native dtype must
  survive the wire bit-exactly.
- Compression is zstd (stdlib-adjacent, in-image) with a "none" fallback;
  the reference used lz4/zstd.
- Works on numpy arrays and jax Arrays (converted host-side); no torch.

Binary layout (little-endian)::

    magic   b"TPUT"                      4 bytes
    version u8                           1 byte
    flags   u8 (bit0: zstd)              1 byte
    hdr_len u32                          4 bytes
    header  msgpack {dtype, shape}       hdr_len bytes
    payload raw or zstd bytes            rest
"""

from __future__ import annotations

import base64
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

try:  # ml_dtypes ships with jax; gives native bfloat16/fp8 numpy dtypes
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
    _EXTRA_DTYPES = {
        "bfloat16": _BFLOAT16,
        "float8_e4m3fn": np.dtype(ml_dtypes.float8_e4m3fn),
        "float8_e5m2": np.dtype(ml_dtypes.float8_e5m2),
    }
except Exception:  # pragma: no cover - ml_dtypes is always in-image with jax
    _EXTRA_DTYPES = {}

try:
    import msgpack

    _HAVE_MSGPACK = True
except Exception:  # pragma: no cover
    import json as _json

    _HAVE_MSGPACK = False

try:
    import zstandard as zstd

    _HAVE_ZSTD = True
except Exception:  # pragma: no cover
    _HAVE_ZSTD = False

_MAGIC = b"TPUT"
_VERSION = 1
_FLAG_ZSTD = 1


def _pack_header(obj: Dict[str, Any]) -> bytes:
    if _HAVE_MSGPACK:
        return msgpack.packb(obj, use_bin_type=True)
    return _json.dumps(obj).encode()  # pragma: no cover


def _unpack_header(data: bytes) -> Dict[str, Any]:
    if _HAVE_MSGPACK:
        return msgpack.unpackb(data, raw=False)
    return _json.loads(data.decode())  # pragma: no cover


def _dtype_from_name(name: str) -> np.dtype:
    if name in _EXTRA_DTYPES:
        return _EXTRA_DTYPES[name]
    return np.dtype(name)


def _to_numpy(tensor: Any) -> np.ndarray:
    """Host-side numpy view of a numpy array or jax Array (no torch)."""
    if isinstance(tensor, np.ndarray):
        return tensor
    # jax.Array exposes __array__ / device transfer via np.asarray.
    return np.asarray(tensor)


class TensorSerializer:
    """Framed binary codec for single tensors.

    Parity surface: reference ``TensorSerializer.serialize``:55 /
    ``.deserialize``:106.
    """

    def __init__(self, compress: bool = True, compression_level: int = 3,
                 min_compress_bytes: int = 4096) -> None:
        self.compress = compress and _HAVE_ZSTD
        self.compression_level = compression_level
        self.min_compress_bytes = min_compress_bytes

    def serialize(self, tensor: Any) -> bytes:
        # np.asarray(order="C") rather than ascontiguousarray: the latter
        # promotes 0-d arrays to 1-d and would corrupt scalar shapes.
        arr = np.asarray(_to_numpy(tensor), order="C")
        dtype_name = (
            "bfloat16" if _EXTRA_DTYPES and arr.dtype == _EXTRA_DTYPES.get("bfloat16")
            else arr.dtype.name
        )
        payload = arr.tobytes()
        flags = 0
        if self.compress and len(payload) >= self.min_compress_bytes:
            compressed = zstd.ZstdCompressor(level=self.compression_level).compress(
                payload
            )
            if len(compressed) < len(payload):
                payload = compressed
                flags |= _FLAG_ZSTD
        header = _pack_header({"dtype": dtype_name, "shape": list(arr.shape)})
        return b"".join(
            [
                _MAGIC,
                struct.pack("<BB", _VERSION, flags),
                struct.pack("<I", len(header)),
                header,
                payload,
            ]
        )

    def deserialize(self, data: bytes) -> np.ndarray:
        if data[:4] != _MAGIC:
            raise ValueError("bad magic: not a TPUT tensor frame")
        version, flags = struct.unpack_from("<BB", data, 4)
        if version != _VERSION:
            raise ValueError(f"unsupported frame version {version}")
        (hdr_len,) = struct.unpack_from("<I", data, 6)
        header = _unpack_header(data[10 : 10 + hdr_len])
        payload = data[10 + hdr_len :]
        if flags & _FLAG_ZSTD:
            if not _HAVE_ZSTD:  # pragma: no cover
                raise RuntimeError("zstd frame but zstandard not available")
            payload = zstd.ZstdDecompressor().decompress(payload)
        dtype = _dtype_from_name(header["dtype"])
        arr = np.frombuffer(payload, dtype=dtype)
        return arr.reshape(header["shape"]).copy()


_DEFAULT = TensorSerializer()


def serialize_tensor_dict(tensor: Any, compress: bool = False) -> Dict[str, Any]:
    """Base64 JSON-safe dict (reference ``serialize_tensor``:163) for
    control-plane / debugging transport. The hot data plane never uses this."""
    ser = TensorSerializer(compress=compress)
    return {
        "__tensor__": True,
        "data": base64.b64encode(ser.serialize(tensor)).decode("ascii"),
    }


def deserialize_tensor_dict(d: Dict[str, Any]) -> np.ndarray:
    if not d.get("__tensor__"):
        raise ValueError("not a serialized tensor dict")
    return _DEFAULT.deserialize(base64.b64decode(d["data"]))


class StreamingTensorBuffer:
    """Chunked streaming of a tensor frame for bounded-memory DCN transfer.

    Parity: reference ``StreamingTensorBuffer``:209 (1 MB chunks with a packed
    per-chunk header). Chunk layout::

        seq   u32   chunk index
        total u32   total chunks
        len   u32   chunk payload length
        data  len bytes
    """

    CHUNK_HEADER = struct.Struct("<III")

    def __init__(self, chunk_bytes: int = 1 << 20,
                 serializer: Optional[TensorSerializer] = None) -> None:
        self.chunk_bytes = chunk_bytes
        self.serializer = serializer or _DEFAULT
        self._chunks: Dict[int, bytes] = {}
        self._total: Optional[int] = None

    def chunk(self, tensor: Any) -> Iterator[bytes]:
        frame = self.serializer.serialize(tensor)
        total = max(1, -(-len(frame) // self.chunk_bytes))
        for i in range(total):
            part = frame[i * self.chunk_bytes : (i + 1) * self.chunk_bytes]
            yield self.CHUNK_HEADER.pack(i, total, len(part)) + part

    def reset(self) -> None:
        self._chunks.clear()
        self._total = None

    def feed(self, chunk: bytes) -> Optional[np.ndarray]:
        """Feed one chunk; returns the tensor when the last chunk arrives.

        Any framing error resets the buffer so a shared instance is not
        poisoned for subsequent frames.
        """
        seq, total, length = self.CHUNK_HEADER.unpack_from(chunk)
        payload = chunk[self.CHUNK_HEADER.size : self.CHUNK_HEADER.size + length]
        if len(payload) != length:
            self.reset()
            raise ValueError("truncated chunk")
        if total < 1 or seq >= total:
            self.reset()
            raise ValueError(f"bad chunk header seq={seq} total={total}")
        if self._total is None:
            self._total = total
        elif self._total != total:
            self.reset()
            raise ValueError("inconsistent chunk totals")
        self._chunks[seq] = payload
        if len(self._chunks) == self._total:
            frame = b"".join(self._chunks[i] for i in range(self._total))
            self._chunks.clear()
            self._total = None
            return self.serializer.deserialize(frame)
        return None


def serialize_pytree(tree: Any, compress: bool = True) -> bytes:
    """Frame a flat dict of tensors (e.g. per-layer KV pages) as one message.

    Used by the KV migration path (reference TransferKVCache,
    ``proto/inference.proto:19`` / ``grpc_server.py:190``) when KV crosses DCN.
    """
    ser = TensorSerializer(compress=compress)
    if not isinstance(tree, dict):
        raise TypeError("serialize_pytree expects a flat dict of tensors")
    parts: List[bytes] = []
    keys: List[str] = []
    for k, v in tree.items():
        keys.append(str(k))
        parts.append(ser.serialize(v))
    header = _pack_header({"keys": keys, "lens": [len(p) for p in parts]})
    return struct.pack("<I", len(header)) + header + b"".join(parts)


def deserialize_pytree(data: bytes) -> Dict[str, np.ndarray]:
    (hdr_len,) = struct.unpack_from("<I", data, 0)
    header = _unpack_header(data[4 : 4 + hdr_len])
    out: Dict[str, np.ndarray] = {}
    off = 4 + hdr_len
    for k, ln in zip(header["keys"], header["lens"]):
        out[k] = _DEFAULT.deserialize(data[off : off + ln])
        off += ln
    return out
