"""Shared full-jitter retry backoff.

One formula for every client retry ladder (worker ``APIClient``, SDK
``InferenceClient``): ``delay ~ U(0, base·2^attempt)``. Full jitter
de-synchronizes a fleet that all lost the server at the same instant —
a deterministic schedule has every client retry in lockstep (thundering
herd on server restart). The optional ``remaining_s`` clamp implements a
per-request retry budget (None = budget exhausted, stop retrying).
"""

from __future__ import annotations

import random
from typing import Optional


def full_jitter_delay(
    base_s: float,
    attempt: int,
    rng: random.Random,
    remaining_s: Optional[float] = None,
) -> Optional[float]:
    """The next backoff delay in seconds, or None when ``remaining_s``
    (the caller's retry budget) is already spent. The caller sleeps and
    charges the returned delay against its budget."""
    if remaining_s is not None and remaining_s <= 0.0:
        return None
    delay = base_s * (2**attempt) * rng.uniform(0.0, 1.0)
    if remaining_s is not None:
        delay = min(delay, remaining_s)
    return delay
