"""Prefix fingerprints — the shared currency of cache-aware routing.

Workers hold a radix prefix cache keyed by *token* blocks
(``runtime/kv_cache.py``), but the control plane and the SDK are
tokenizer-free: they see prompt text and chat messages only. Routing
therefore trades in **text-space fingerprints**: a rolling hash of the
canonical prompt text, sampled at fixed ``PREFIX_BLOCK_CHARS`` boundaries.
Every layer — SDK (``prefix_hint``/auto), control plane (server-side
fallback at job creation), worker (radix-summary builder) — computes the
SAME boundary fingerprints from the SAME canonicalization, so a request
and a worker's advertised cache can be compared without ever tokenizing
on the control plane.

The mapping text-block → KV-block is approximate (one char ≈ one token
only for the byte tokenizer); that is fine BY DESIGN: summaries are
advisory routing hints, never correctness inputs. A wrong match costs one
re-prefill — exactly what a locality-blind scheduler pays on every
request.

The hash is a polynomial rolling hash mod a 61-bit Mersenne prime —
stable across processes and Python versions (``hash()`` is salted;
hashlib per boundary would cost a full digest per block). It is NOT a
cryptographic commitment: a malicious client can at worst steer its own
request to a warmer worker.
"""

from __future__ import annotations

import os

from typing import Any, Dict, List, Optional, Sequence

# one fingerprint boundary every this many canonical-text chars; both ends
# of a comparison MUST use the same value (workers advertise theirs and
# the registry rejects mismatches rather than mis-matching silently)
PREFIX_BLOCK_CHARS = 64


def _max_blocks_default() -> int:
    """Deployment-wide fingerprint depth cap, overridable via the
    ``TPU_PREFIX_MAX_BLOCKS`` env var (read once at import).

    The tradeoff is routing RESOLUTION vs summary cost: at the default 32
    blocks x 64 chars, affinity routing sees at most ~2k canonical chars —
    two 32k prompts sharing a 30k prefix look IDENTICAL to the router past
    depth 2k, so long-context fleets that want the router to distinguish
    deep RAG contexts should raise it (512 blocks ≈ 32k chars). The cost
    is linear everywhere: hashing per request, radix-summary wire size per
    heartbeat, and the control plane's advertised-set memory. Because every
    layer must agree on depth to compare fingerprints, set the SAME value
    on workers, planes, and SDK clients — a deeper client is harmless (the
    extra boundaries just never match) but a deeper worker advertises
    boundaries no request computes.
    """
    raw = os.environ.get("TPU_PREFIX_MAX_BLOCKS")
    if not raw:
        return 32
    try:
        val = int(raw)
    except ValueError:
        return 32
    return max(1, val)


# boundaries computed per prompt — bounds hashing work AND summary bloat
# for pathological prompts; 32 blocks = 2048 chars of routable prefix
# (see ``_max_blocks_default`` for the long-context resolution tradeoff)
MAX_PREFIX_BLOCKS = _max_blocks_default()

_MOD = (1 << 61) - 1          # Mersenne prime 2^61-1
_BASE = 1_000_003


def canonical_prompt_text(prompt_or_messages: Any) -> str:
    """One canonical text for a request's prompt, identical on every layer.

    Chat messages canonicalize to ``role\\x1fcontent`` records joined by
    ``\\x1e`` — NOT the worker's chat template (templates differ per
    tokenizer and the SDK cannot replicate them). What matters for routing
    is only that a conversation extended by one turn canonicalizes to a
    strict superstring of its previous turn, so the shared prefix grows
    monotonically.
    """
    if prompt_or_messages is None:
        return ""
    if isinstance(prompt_or_messages, str):
        return prompt_or_messages
    if isinstance(prompt_or_messages, (list, tuple)):
        parts = []
        for m in prompt_or_messages:
            if isinstance(m, dict):
                parts.append(
                    f"{m.get('role', '')}\x1f{m.get('content', '')}"
                )
            else:
                parts.append(str(m))
        return "\x1e".join(parts)
    return str(prompt_or_messages)


def prefix_fingerprints(text: str,
                        block_chars: int = PREFIX_BLOCK_CHARS,
                        max_blocks: int = MAX_PREFIX_BLOCKS) -> List[str]:
    """Boundary fingerprints of ``text``: entry ``i`` (0-based) is the
    rolling hash of the first ``(i+1) * block_chars`` characters. Only
    FULL blocks fingerprint (partial tails are never shared by the prefix
    cache either). One O(n) pass emits every boundary."""
    if block_chars <= 0:
        raise ValueError(f"block_chars must be positive, got {block_chars}")
    n_blocks = min(len(text) // block_chars, max_blocks)
    if n_blocks <= 0:
        return []
    out: List[str] = []
    h = 0
    data = text[: n_blocks * block_chars].encode("utf-8", "replace")
    # byte boundaries of char blocks (utf-8 multi-byte chars shift them)
    bounds = {
        len(text[: (i + 1) * block_chars].encode("utf-8", "replace")): i
        for i in range(n_blocks)
    }
    for pos, b in enumerate(data, start=1):
        h = (h * _BASE + b) % _MOD
        if pos in bounds:
            out.append(f"{h:016x}")
    return out


def fingerprints_for_params(params: Optional[Dict[str, Any]],
                            block_chars: int = PREFIX_BLOCK_CHARS,
                            max_blocks: int = MAX_PREFIX_BLOCKS
                            ) -> List[str]:
    """Request fingerprints from job params (server-side fallback when the
    client sent none): messages win over prompt, mirroring the worker's
    own input precedence (``TPULLMEngine.inference``)."""
    if not isinstance(params, dict):
        return []
    source = params.get("messages") or params.get("prompt")
    if not source:
        return []
    return prefix_fingerprints(
        canonical_prompt_text(source), block_chars, max_blocks
    )


def sanitize_fingerprints(fps: Any,
                          max_blocks: int = MAX_PREFIX_BLOCKS) -> List[str]:
    """Screen client-supplied fingerprints: a bounded list of short hex
    strings or nothing — the routing path must never choke on (or store
    unbounded) hostile input."""
    if not isinstance(fps, (list, tuple)):
        return []
    out: List[str] = []
    for fp in fps[:max_blocks]:
        if isinstance(fp, str) and 0 < len(fp) <= 32 and \
                all(c in "0123456789abcdef" for c in fp):
            out.append(fp)
        else:
            return []    # one malformed entry poisons the list: drop all
    return out


def deepest_match(request_fps: Sequence[str],
                  advertised: Dict[str, Any]) -> int:
    """Number of leading blocks of ``request_fps`` a worker's advertised
    fingerprint set covers: the DEEPEST request boundary present wins
    (boundary i implies boundaries 0..i-1 hashed the same prefix)."""
    for i in range(len(request_fps) - 1, -1, -1):
        if request_fps[i] in advertised:
            return i + 1
    return 0
