"""Substrate: typed data structures, tensor wire framing, configuration.

TPU-native re-design of the reference's ``common/`` package
(``common/data_structures.py``, ``common/serialization.py``) and the worker
config system (``worker/config.py``).
"""

from distributed_gpu_inference_tpu.utils.data_structures import (  # noqa: F401
    BlockRange,
    InferenceRequest,
    InferenceResponse,
    InferenceState,
    JobStatus,
    JobType,
    KVBlockMeta,
    ModelShardConfig,
    SessionConfig,
    WorkerInfo,
    WorkerRole,
    WorkerState,
    compute_prefix_hash,
    estimate_kv_cache_bytes,
)
from distributed_gpu_inference_tpu.utils.serialization import (  # noqa: F401
    StreamingTensorBuffer,
    TensorSerializer,
    deserialize_tensor_dict,
    serialize_tensor_dict,
)
