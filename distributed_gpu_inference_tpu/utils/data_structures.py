"""Core typed data structures shared across the framework.

Capability parity with the reference's ``common/data_structures.py``
(WorkerRole:13, WorkerState:20, BlockRange:29, WorkerInfo:50,
InferenceState:123, KVCacheBlock:147, InferenceRequest:183,
InferenceResponse:209, SessionConfig:232, ModelShardConfig:257,
compute_prefix_hash:293, estimate_kv_cache_size:299) — re-designed for TPU:

- Workers describe TPU topology (chip generation, chips, HBM per chip, mesh
  axes) instead of CUDA device properties.
- KV-cache metadata describes *pages in a device-resident HBM pool* addressed
  by block index, never host tensors; actual KV bytes live in
  ``runtime/kv_cache.py`` pools and move between chips via ICI collectives.
- Shard configs describe pipeline *stages over a mesh axis*, with the same
  layer-range planning surface the reference exposes for Petals-style
  pipelines.

Everything here is pure-Python (dataclasses + enums), importable without jax,
and hermetically unit-testable on CPU.
"""

from __future__ import annotations

import hashlib
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional, Sequence, Tuple


# ---------------------------------------------------------------------------
# Worker identity / roles
# ---------------------------------------------------------------------------


class WorkerRole(str, Enum):
    """Role a worker plays in a disaggregated deployment.

    Parity: reference ``common/data_structures.py:13`` (HYBRID/PREFILL/DECODE);
    we add PIPELINE_STAGE for layer-sharded serving.
    """

    HYBRID = "hybrid"          # both prefill and decode (default)
    PREFILL = "prefill"        # compute-bound pool (DistServe-style)
    DECODE = "decode"          # bandwidth-bound pool
    PIPELINE_STAGE = "pipeline_stage"  # owns a contiguous layer range


class WorkerState(str, Enum):
    """Lifecycle state of a worker (reference ``data_structures.py:20``)."""

    INITIALIZING = "initializing"
    IDLE = "idle"
    BUSY = "busy"
    DRAINING = "draining"       # graceful shutdown: finish running, accept none
    OFFLINE = "offline"
    FAILED = "failed"


class JobType(str, Enum):
    """Task families the platform schedules (reference engine registry types)."""

    LLM = "llm"
    EMBEDDING = "embedding"
    IMAGE_GEN = "image_gen"
    VISION = "vision"
    WHISPER = "whisper"


class JobStatus(str, Enum):
    """Job lifecycle (reference ``server/app/api/jobs.py:229-232``)."""

    QUEUED = "queued"
    RUNNING = "running"
    COMPLETED = "completed"
    FAILED = "failed"
    CANCELLED = "cancelled"


# ---------------------------------------------------------------------------
# Layer / stage ranges
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BlockRange:
    """A contiguous half-open range of transformer layers ``[start, end)``.

    Parity: reference ``common/data_structures.py:29``. Used by the shard
    planner to describe which layers a pipeline stage owns.
    """

    start: int
    end: int

    def __post_init__(self) -> None:
        if self.start < 0 or self.end < self.start:
            raise ValueError(f"invalid layer range [{self.start}, {self.end})")

    @property
    def num_layers(self) -> int:
        return self.end - self.start

    def __contains__(self, layer: int) -> bool:
        return self.start <= layer < self.end

    def overlaps(self, other: "BlockRange") -> bool:
        return self.start < other.end and other.start < self.end

    def to_dict(self) -> Dict[str, int]:
        return {"start": self.start, "end": self.end}

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> "BlockRange":
        return cls(start=int(d["start"]), end=int(d["end"]))


# ---------------------------------------------------------------------------
# Worker info
# ---------------------------------------------------------------------------


@dataclass
class TpuTopology:
    """Describes a worker's accelerator resources, TPU-first.

    Replaces the reference's GPU fields (gpu_model/gpu_memory_gb in
    ``WorkerInfo``, ``server`` Worker row §2.1) with mesh-aware TPU facts.
    """

    chip_type: str = "v5e"           # v4 / v5e / v5p / v6e / cpu (tests)
    num_chips: int = 1
    hbm_gb_per_chip: float = 16.0
    mesh_shape: Tuple[int, ...] = (1,)
    mesh_axis_names: Tuple[str, ...] = ("data",)
    ici_bandwidth_gbps: float = 400.0   # per-link ICI
    dcn_bandwidth_gbps: float = 25.0    # host-to-host
    peak_bf16_tflops: float = 197.0     # per chip (v5e ≈ 197 bf16 TFLOP/s)

    @property
    def total_hbm_gb(self) -> float:
        return self.num_chips * self.hbm_gb_per_chip

    def to_dict(self) -> Dict[str, Any]:
        return {
            "chip_type": self.chip_type,
            "num_chips": self.num_chips,
            "hbm_gb_per_chip": self.hbm_gb_per_chip,
            "mesh_shape": list(self.mesh_shape),
            "mesh_axis_names": list(self.mesh_axis_names),
            "ici_bandwidth_gbps": self.ici_bandwidth_gbps,
            "dcn_bandwidth_gbps": self.dcn_bandwidth_gbps,
            "peak_bf16_tflops": self.peak_bf16_tflops,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TpuTopology":
        d = dict(d)
        d["mesh_shape"] = tuple(d.get("mesh_shape", (1,)))
        d["mesh_axis_names"] = tuple(d.get("mesh_axis_names", ("data",)))
        return cls(**d)


@dataclass
class WorkerInfo:
    """A worker as seen by schedulers and pipeline routers.

    Parity: reference ``common/data_structures.py:50`` (WorkerInfo) — id,
    address, role, state, layer range, load, perf counters — with TPU topology
    in place of GPU facts.
    """

    worker_id: str = field(default_factory=lambda: uuid.uuid4().hex)
    host: str = "127.0.0.1"
    port: int = 8470
    region: str = "us-central"
    role: WorkerRole = WorkerRole.HYBRID
    state: WorkerState = WorkerState.INITIALIZING
    topology: TpuTopology = field(default_factory=TpuTopology)
    layer_range: Optional[BlockRange] = None
    model_name: Optional[str] = None
    supported_types: List[str] = field(default_factory=lambda: [JobType.LLM.value])
    # load / perf
    active_sessions: int = 0
    max_sessions: int = 32
    tokens_per_second: float = 0.0
    last_heartbeat: float = field(default_factory=time.time)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def is_available(self) -> bool:
        return (
            self.state in (WorkerState.IDLE, WorkerState.BUSY)
            and self.active_sessions < self.max_sessions
        )

    @property
    def load_fraction(self) -> float:
        if self.max_sessions <= 0:
            return 1.0
        return self.active_sessions / self.max_sessions

    def is_stale(self, timeout_s: float = 90.0, now: Optional[float] = None) -> bool:
        """Heartbeat staleness (reference heartbeat_timeout 90 s, config.py:35)."""
        now = time.time() if now is None else now
        return (now - self.last_heartbeat) > timeout_s

    def to_dict(self) -> Dict[str, Any]:
        return {
            "worker_id": self.worker_id,
            "host": self.host,
            "port": self.port,
            "region": self.region,
            "role": self.role.value,
            "state": self.state.value,
            "topology": self.topology.to_dict(),
            "layer_range": self.layer_range.to_dict() if self.layer_range else None,
            "model_name": self.model_name,
            "supported_types": list(self.supported_types),
            "active_sessions": self.active_sessions,
            "max_sessions": self.max_sessions,
            "tokens_per_second": self.tokens_per_second,
            "last_heartbeat": self.last_heartbeat,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WorkerInfo":
        d = dict(d)
        d["role"] = WorkerRole(d.get("role", "hybrid"))
        d["state"] = WorkerState(d.get("state", "initializing"))
        if d.get("topology"):
            d["topology"] = TpuTopology.from_dict(d["topology"])
        else:
            d["topology"] = TpuTopology()
        if d.get("layer_range"):
            d["layer_range"] = BlockRange.from_dict(d["layer_range"])
        return cls(**d)


# ---------------------------------------------------------------------------
# Inference session state
# ---------------------------------------------------------------------------


@dataclass
class InferenceState:
    """Per-request decode progress tracked by sessions and schedulers.

    Parity: reference ``common/data_structures.py:123``. On TPU the hidden
    states / KV never appear here — they are device-resident; this is pure
    host-side bookkeeping (token counts, positions, timing).
    """

    session_id: str = field(default_factory=lambda: uuid.uuid4().hex)
    request_id: str = field(default_factory=lambda: uuid.uuid4().hex)
    prompt_tokens: int = 0
    generated_tokens: int = 0
    position: int = 0                       # next position to write
    max_new_tokens: int = 256
    finished: bool = False
    finish_reason: Optional[str] = None     # "stop" | "length" | "abort" | "error"
    created_at: float = field(default_factory=time.time)
    first_token_at: Optional[float] = None
    last_token_at: Optional[float] = None

    def record_token(self, n: int = 1, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        if self.first_token_at is None:
            self.first_token_at = now
        self.last_token_at = now
        self.generated_tokens += n
        self.position += n
        if self.generated_tokens >= self.max_new_tokens:
            self.finished = True
            self.finish_reason = self.finish_reason or "length"

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return (self.first_token_at - self.created_at) * 1000.0

    @property
    def tpot_ms(self) -> Optional[float]:
        """Mean time-per-output-token after the first token."""
        if self.last_token_at is None or self.first_token_at is None:
            return None
        if self.generated_tokens <= 1:
            return 0.0
        return (
            (self.last_token_at - self.first_token_at)
            / (self.generated_tokens - 1)
            * 1000.0
        )


# ---------------------------------------------------------------------------
# KV cache block metadata
# ---------------------------------------------------------------------------

KV_BLOCK_TOKENS = 16  # tokens per page (reference kv_cache.py block_size=16)


@dataclass
class KVBlockMeta:
    """Host-side metadata for one page in a device-resident KV pool.

    Parity: reference ``common/data_structures.py:147`` (KVCacheBlock) with
    ref-count CoW semantics (:175-180) — but the payload is an *index into an
    HBM pool array*, not a tensor. Sharing a block = sharing the index;
    copy-on-write allocates a fresh index and copies the page on device.
    """

    block_id: int
    num_tokens: int = 0
    capacity: int = KV_BLOCK_TOKENS
    ref_count: int = 1
    prefix_hash: Optional[str] = None
    last_access: float = field(default_factory=time.time)

    @property
    def is_full(self) -> bool:
        return self.num_tokens >= self.capacity

    @property
    def is_shared(self) -> bool:
        return self.ref_count > 1

    def touch(self, now: Optional[float] = None) -> None:
        self.last_access = time.time() if now is None else now

    def incref(self) -> int:
        self.ref_count += 1
        return self.ref_count

    def decref(self) -> int:
        if self.ref_count <= 0:
            raise ValueError(f"block {self.block_id}: decref below zero")
        self.ref_count -= 1
        return self.ref_count


# ---------------------------------------------------------------------------
# Requests / responses
# ---------------------------------------------------------------------------


@dataclass
class SamplingParams:
    """Decode-time sampling controls (subset the reference exposes via
    ``GenerationConfig``, ``worker/engines/__init__.py:24``)."""

    max_new_tokens: int = 256
    temperature: float = 0.0      # 0 → greedy
    top_k: int = 0                # 0 → disabled
    top_p: float = 1.0            # 1.0 → disabled
    stop_token_ids: Tuple[int, ...] = ()
    seed: Optional[int] = None
    # run to the max_new_tokens budget, honoring NO stop ids (engine eos
    # included) — benchmark/oracle workloads where both A/B legs must
    # generate identical token counts (vLLM's ignore_eos parity knob)
    ignore_eos: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "max_new_tokens": self.max_new_tokens,
            "temperature": self.temperature,
            "top_k": self.top_k,
            "top_p": self.top_p,
            "stop_token_ids": list(self.stop_token_ids),
            "seed": self.seed,
            "ignore_eos": self.ignore_eos,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SamplingParams":
        d = dict(d)
        d["stop_token_ids"] = tuple(d.get("stop_token_ids", ()))
        d["ignore_eos"] = bool(d.get("ignore_eos", False))
        return cls(**d)


@dataclass
class InferenceRequest:
    """A unit of schedulable work (reference ``data_structures.py:183``)."""

    request_id: str = field(default_factory=lambda: uuid.uuid4().hex)
    job_type: JobType = JobType.LLM
    model: Optional[str] = None
    prompt: Optional[str] = None
    prompt_token_ids: Optional[List[int]] = None
    messages: Optional[List[Dict[str, str]]] = None   # chat format
    sampling: SamplingParams = field(default_factory=SamplingParams)
    priority: int = 0
    session_id: Optional[str] = None
    arrival_time: float = field(default_factory=time.time)
    # relative completion deadline (seconds from arrival). Advisory EDF
    # input for the batcher: WITHIN a priority band, earlier absolute
    # deadlines admit first and later-deadline slots are preferred
    # preemption victims. None (the default) = no deadline — ordering is
    # then byte-identical to the pre-deadline batcher.
    deadline_s: Optional[float] = None
    params: Dict[str, Any] = field(default_factory=dict)  # task-specific extras

    @property
    def deadline_at(self) -> float:
        """Absolute deadline (epoch seconds), +inf when none is set —
        directly usable as an EDF sort component."""
        if self.deadline_s is None:
            return float("inf")
        return self.arrival_time + float(self.deadline_s)

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids) if self.prompt_token_ids else 0


@dataclass
class InferenceResponse:
    """Result of an inference request (reference ``data_structures.py:209``)."""

    request_id: str
    text: Optional[str] = None
    token_ids: List[int] = field(default_factory=list)
    finish_reason: Optional[str] = None
    prompt_tokens: int = 0
    completion_tokens: int = 0
    cached_tokens: int = 0          # prefix-cache hits (reference GenerationResult)
    ttft_ms: Optional[float] = None
    e2e_ms: Optional[float] = None
    error: Optional[str] = None
    # machine-readable error class riding next to the human-readable
    # ``error`` text (round 12): ``request_timeout`` (client-side wait
    # budget elapsed — the request may still be generating), vs
    # ``shed_overload`` (the batcher rejected at admission — nothing ran,
    # safe to retry elsewhere). Surfaced through job results and SSE so
    # clients branch on the class, not on parsing the message.
    error_code: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.error is None


# ---------------------------------------------------------------------------
# Session / shard configuration
# ---------------------------------------------------------------------------


@dataclass
class SessionConfig:
    """Configuration of a distributed pipeline session
    (reference ``data_structures.py:232``)."""

    model_name: str = "llama3-8b"
    max_length: int = 8192
    dtype: str = "bfloat16"
    timeout_s: float = 60.0
    max_retries_per_hop: int = 3
    retry_backoff_s: float = 0.5
    compress_dcn: bool = True       # zstd-frame tensors on DCN/WAN hops
    use_ici_collectives: bool = True  # in-slice hops ride XLA collectives


@dataclass
class ModelShardConfig:
    """Stage plan for layer-sharded pipeline serving.

    Parity: reference ``data_structures.py:257`` + ``get_inference_route``:284.
    Stage order == inference route order (embeddings live in stage 0, final
    norm + lm_head in the last stage — reference model_shard.py:163-171).
    """

    model_name: str
    num_layers: int
    stages: List[BlockRange] = field(default_factory=list)
    stage_workers: List[str] = field(default_factory=list)  # worker_id per stage

    def __post_init__(self) -> None:
        if self.stages:
            self.validate()

    def validate(self) -> None:
        if not self.stages:
            raise ValueError("no stages")
        if self.stages[0].start != 0:
            raise ValueError("first stage must start at layer 0")
        if self.stages[-1].end != self.num_layers:
            raise ValueError(
                f"last stage ends at {self.stages[-1].end}, expected {self.num_layers}"
            )
        for a, b in zip(self.stages, self.stages[1:]):
            if a.end != b.start:
                raise ValueError(f"gap/overlap between stages {a} and {b}")
        if self.stage_workers and len(self.stage_workers) != len(self.stages):
            raise ValueError("stage_workers length != stages length")

    @property
    def num_stages(self) -> int:
        return len(self.stages)

    def get_inference_route(self) -> List[Tuple[str, BlockRange]]:
        """Ordered (worker_id, layer_range) hops for a full forward pass."""
        self.validate()
        if not self.stage_workers:
            raise ValueError("no workers assigned to stages")
        return list(zip(self.stage_workers, self.stages))

    def stage_for_layer(self, layer: int) -> int:
        for i, rng in enumerate(self.stages):
            if layer in rng:
                return i
        raise ValueError(f"layer {layer} not in any stage")


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------


def compute_prefix_hash(token_ids: Sequence[int], upto: Optional[int] = None) -> str:
    """Stable hash of a token prefix for prefix-cache keys.

    Parity: reference ``data_structures.py:293`` (sha256); block-aligned
    callers pass ``upto`` = multiple of KV_BLOCK_TOKENS.
    """
    ids = token_ids if upto is None else token_ids[:upto]
    h = hashlib.sha256()
    for t in ids:
        h.update(int(t).to_bytes(4, "little", signed=False))
    return h.hexdigest()


def estimate_kv_cache_bytes(
    num_layers: int,
    num_kv_heads: int,
    head_dim: int,
    seq_len: int,
    dtype_bytes: int = 2,
    batch: int = 1,
) -> int:
    """Bytes of KV cache for a sequence (reference ``data_structures.py:299``).

    2 (K and V) * layers * kv_heads * head_dim * seq * dtype_bytes * batch.
    """
    return 2 * num_layers * num_kv_heads * head_dim * seq_len * dtype_bytes * batch
