"""Chaos-scenario harness: a REAL control plane on a loopback socket.

The worker ``APIClient`` and SDK ``InferenceClient`` are synchronous httpx
clients, while the control plane is an aiohttp app. To drive both ends of
the real protocol in one test, :class:`LiveControlPlane` runs the server's
event loop on a background thread and binds the app to an ephemeral
loopback port; the test thread then talks real HTTP through the real
clients (retry ladders, signing, fault seams and all), and can reach into
the server's services (sweeps with a simulated clock, store queries) via
:meth:`call`.

:class:`LiveFleet` (round 9) scales the harness to a CLUSTER: N real
``worker.main.Worker`` instances — batcher-backed engines, direct servers,
heartbeat and poll threads, the production claim machinery — registered
behind one live control plane, plus a chaos driver that executes a seeded
:class:`~..testing.faults.FleetFaultPlan` (hard kills,
restart-with-reregistration, heartbeat blackouts, bidirectional
partitions, pressure storms, slow-replica latency) against wall-clock
offsets WHILE open-loop traffic runs. Every injected event is reported to
the plane's metrics (``chaos_*_total``) so a chaos run and the plane's
observed reactions share one scrape timeline.
"""

from __future__ import annotations

import asyncio
import os
import socket
import tempfile
import threading
import time
import uuid
from typing import Any, Coroutine, Dict, List, Optional, Union

from aiohttp import web

from ..server.app import ServerState, create_app
from . import faults as _faults
from .faults import FaultPlan, FaultRule, FleetFaultPlan


class LiveControlPlane:
    """Context manager: a served control plane + direct service access.

    Round 15 adds a kill/restart lifecycle for plane chaos: :meth:`kill`
    hard-stops the server mid-traffic (in-flight requests die, the store
    connection closes — the db FILE and its WAL survive for peer planes),
    and :meth:`start` after a kill rebuilds the replica cold on the SAME
    port, so endpoint lists held by workers and SDK clients keep working.
    """

    def __init__(self, **state_kw: Any) -> None:
        self._state_kw = state_kw
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._runner: Optional[web.AppRunner] = None
        self.state: Optional[ServerState] = None
        self.port: int = 0
        self.alive = False

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self) -> "LiveControlPlane":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.kill()

    def start(self) -> None:
        """Cold start (or cold RESTART after :meth:`kill`): fresh loop,
        fresh ServerState over the same ``db_path`` — migrations re-run
        idempotently, and a shared job store keeps every epoch fence."""
        if self.alive:
            return
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="live-control-plane",
            daemon=True,
        )
        self._thread.start()
        self.call(self._start())
        self.alive = True

    def kill(self) -> None:
        """Hard stop: in-flight requests die with the server. Safe to call
        twice; :meth:`start` afterwards is a restart on the same port."""
        if self._loop is None:
            return
        self.alive = False
        try:
            self.call(self._stop(), timeout_s=15.0)
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10.0)
            self._loop.close()
            self._loop = None
            self._thread = None
            self._runner = None

    async def _start(self) -> None:
        # ServerState (and its Store/asyncio primitives) is created on the
        # server loop so nothing binds to the test thread
        self.state = ServerState(**self._state_kw)
        app = create_app(self.state, start_background=False)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        sock = socket.socket()
        # a restart must land on the port the first start drew — every
        # registered worker/SDK endpoint list points there
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind(("127.0.0.1", self.port))
        self.port = sock.getsockname()[1]
        site = web.SockSite(self._runner, sock, shutdown_timeout=2.0)
        await site.start()

    async def _stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        if self.state is not None:
            self.state.store.close()

    # -- access --------------------------------------------------------------

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def call(self, coro: Coroutine, timeout_s: float = 30.0) -> Any:
        """Run a coroutine on the server loop from the test thread."""
        return asyncio.run_coroutine_threadsafe(
            coro, self._loop
        ).result(timeout=timeout_s)

    # -- common shortcuts ----------------------------------------------------

    def sweep(self, now: Optional[float] = None) -> Dict[str, List[str]]:
        return self.call(self.state.guarantee.sweep(now=now))

    def query(self, sql: str, params: tuple = ()) -> List[Dict[str, Any]]:
        return self.call(self.state.store.query(sql, params))

    def job(self, job_id: str) -> Optional[Dict[str, Any]]:
        return self.call(self.state.store.get_job(job_id))

    def worker(self, worker_id: str) -> Optional[Dict[str, Any]]:
        return self.call(self.state.store.get_worker(worker_id))


# ---------------------------------------------------------------------------
# fleet-scale harness (round 9): N real workers + seeded chaos under load
# ---------------------------------------------------------------------------

# engine geometry every fleet member shares unless overridden: tiny model,
# per-token checkpoint cadence (a seeded kill point must always have a
# checkpoint to resume from), a deep queue so backpressure is the PLANE's
# decision (submit_queue_limit), not the batcher's
DEFAULT_FLEET_ENGINE = {
    "model": "llama3-tiny",
    "max_batch_size": 4,
    "max_seq_len": 160,
    "multi_step": 4,
    "checkpoint_interval_tokens": 1,
    "serving": {"queue_limit": 4096, "default_timeout_s": 120.0},
}


class FleetWorker:
    """One fleet replica: a REAL ``worker.main.Worker`` wired exactly like
    production — batcher-backed ``TPULLMEngine``, ``DirectServer``, stream
    checkpoint sink, heartbeat + poll threads — except registration uses a
    STABLE synthetic machine fingerprint (process-global fingerprints would
    collapse an in-process fleet onto one worker row), and the heartbeat
    loop is gateable so blackout/partition events can silence it without
    touching worker code. ``kill()`` is a hard crash (no drain, no
    offline call); ``start()`` after a kill is a cold
    restart-with-reregistration that lands on the same worker row."""

    def __init__(self, index: int, plane_url: str,
                 engine_config: Optional[Dict[str, Any]] = None,
                 hb_interval_s: float = 0.2,
                 poll_interval_s: float = 0.05,
                 role: Optional[str] = None,
                 pd_data_plane: bool = False,
                 region: str = "us-west") -> None:
        self.index = index
        self.plane_url = plane_url
        self.engine_config = dict(engine_config or DEFAULT_FLEET_ENGINE)
        self.hb_interval_s = hb_interval_s
        self.poll_interval_s = poll_interval_s
        self.role = role
        # PD split fleets: run a real DataPlaneServer (/kv/transfer) so
        # prefill peers can stream KV handoffs at this member, and
        # register its URL. EVERY member of a PD fleet runs one — role
        # rebalance can hand decode work to a prefill-role worker when
        # the decode side browns out, and it must be able to receive.
        self.pd_data_plane = pd_data_plane
        self.region = region
        self.tag = f"fw{index}"
        self.pd_plane: Optional[Any] = None
        # stable across restarts of THIS member: re-registration must land
        # on the same worker row (rejoin accounting, job requeue)
        self.fingerprint = f"fleet-{index}-{uuid.uuid4().hex[:8]}"
        self.alive = False
        self.worker: Optional[Any] = None
        self.llm: Optional[Any] = None
        self.server: Optional[Any] = None
        self.api: Optional[Any] = None
        self.worker_id: Optional[str] = None
        self._hb_blocked = threading.Event()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Cold start (or cold RESTART): fresh engine, fresh server, fresh
        credentials — registered on the stable fingerprint."""
        from ..utils.config import WorkerConfig
        from ..utils.data_structures import TpuTopology, WorkerState
        from ..worker.api_client import APIClient
        from ..worker.direct_server import DirectServer
        from ..worker.main import Worker

        from ..worker.engines.llm import TPULLMEngine

        llm = TPULLMEngine(dict(self.engine_config))
        llm.load_model()
        # per-replica chaos targeting on the KV push seam
        # (worker.pd.push rules match {"worker": tag})
        llm.fault_tag = self.tag
        api = APIClient(self.plane_url, backoff_s=0.0)
        api.fault_tag = self.tag
        cfg = WorkerConfig(
            name=self.tag, region=self.region,
            heartbeat_interval_s=self.hb_interval_s,
            poll_interval_s=self.poll_interval_s,
        )
        cfg.task_types = ["llm"]
        w = Worker(
            cfg, api=api,
            topology=TpuTopology(chip_type="cpu", num_chips=1,
                                 hbm_gb_per_chip=4.0),
        )
        w.engines = {"llm": llm}
        w.fault_tag = self.tag
        llm.checkpoint_sink = w.push_stream_checkpoint
        ds = DirectServer(w, host="127.0.0.1", port=0)
        ds.start()
        # production wires the direct server into the heartbeat loop
        # (worker.main line of duty); an externally-built one must opt in
        # the same way or the plane's gray-failure health scoring never
        # sees this replica's direct latency/error samples
        w._direct = ds
        port = ds._runner.addresses[0][1]
        info: Dict[str, Any] = {
            "name": self.tag, "region": self.region,
            "machine_fingerprint": self.fingerprint,
            "supported_types": ["llm"], "supports_direct": True,
            "direct_url": f"http://127.0.0.1:{port}",
            # fresh per cold (re)start: a restart that beats the heartbeat
            # timeout still requeues the dead incarnation's RUNNING jobs
            "boot_id": w.boot_id,
        }
        if self.role:
            info["role"] = self.role
        if self.pd_data_plane:
            from ..comm.data_plane import DataPlaneServer
            from ..worker.main import _PDReceiverShim

            self.pd_plane = DataPlaneServer(
                _PDReceiverShim(llm), host="127.0.0.1", port=0,
                kv_receiver=llm.kv_receiver,
                kv_exporter=getattr(llm, "kv_export", None),
            )
            self.pd_plane.start()
            info["data_plane_url"] = (
                f"http://127.0.0.1:{self.pd_plane.bound_port}"
            )
        api.register(info)
        self.worker_id = api.worker_id
        w.state = WorkerState.IDLE
        self.worker, self.llm, self.server, self.api = w, llm, ds, api
        self._hb_blocked.clear()
        self._stop.clear()
        w._heartbeat_once()   # first beat lands before traffic arrives
        self._threads = [
            threading.Thread(target=self._hb_loop,
                             name=f"{self.tag}-hb", daemon=True),
            threading.Thread(target=w._main_loop,
                             name=f"{self.tag}-poll", daemon=True),
        ]
        for t in self._threads:
            t.start()
        self.alive = True

    def _hb_loop(self) -> None:
        w = self.worker
        while not self._stop.wait(self.hb_interval_s):
            if self._hb_blocked.is_set():
                continue   # blackout/partition window: beats are "lost"
            try:
                w._heartbeat_once()
            except Exception:  # noqa: BLE001 — outage: next tick retries
                pass

    def kill(self) -> None:
        """Hard crash: servers and threads stop mid-flight — no drain, no
        graceful offline, no checkpoint push. The plane finds out the way
        it would in production: heartbeats stop arriving."""
        if not self.alive:
            return
        self.alive = False
        self._stop.set()
        if self.worker is not None:
            self.worker._shutdown.set()   # stops the poll loop
        if self.server is not None:
            self.server.stop()            # in-flight sockets die abruptly
        if self.pd_plane is not None:
            # the KV receiver dies with the process: in-flight handoff
            # sessions are lost, senders see refused connections
            try:
                self.pd_plane.stop()
            except Exception:  # noqa: BLE001 — a crash is not graceful
                pass
            self.pd_plane = None
        if self.llm is not None:
            # resolves outstanding batcher futures with errors and stops
            # the engine — concurrent requests see a crashed process
            try:
                self.llm.unload()
            except Exception:  # noqa: BLE001 — a crash is not graceful
                pass
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []
        if self.api is not None:
            self.api.close()
        self.worker = self.llm = self.server = self.api = None

    def stop(self) -> None:
        """Teardown at harness exit (not a chaos event)."""
        self.kill()

    # -- chaos windows -------------------------------------------------------

    def blackout(self, on: bool) -> None:
        """Heartbeats stop/resume while the replica keeps serving — the
        one-directional partition that gets a LIVE worker swept offline."""
        if on:
            self._hb_blocked.set()
        else:
            self._hb_blocked.clear()

    def partition_rules(self) -> List[FaultRule]:
        """Rules a bidirectional partition arms on the installed plan: the
        replica's direct endpoint hard-drops every request/stream event,
        and its OWN control-plane calls (completions, checkpoints, polls)
        fail like a cut wire. Heartbeats are gated separately
        (:meth:`blackout`)."""
        return [
            FaultRule(site="worker.direct.request", kind="flap",
                      times=None, match={"worker": self.tag}),
            FaultRule(site="worker.direct.stream", kind="flap",
                      times=None, match={"worker": self.tag}),
            FaultRule(site="worker.api.request", kind="flap",
                      times=None, match={"worker": self.tag}),
        ]

    def handoff_rules(self) -> List[FaultRule]:
        """Rules a ``handoff_partition`` arms: THIS replica's outbound KV
        handoff pushes hard-drop — the prefill→decode stream is cut while
        both sides keep serving (the sender's piece-retry ladder, abort
        path, and the flow's re-prefill fallback take it from there)."""
        return [
            FaultRule(site="worker.pd.push", kind="flap", times=None,
                      match={"worker": self.tag}),
        ]

    def handoff_delay_rules(self, delay_s: float) -> List[FaultRule]:
        """Per-piece latency on THIS replica's outbound KV pushes."""
        return [
            FaultRule(site="worker.pd.push", kind="delay",
                      delay_s=delay_s, times=None,
                      match={"worker": self.tag}),
        ]

    def slow_rules(self, delay_s: float) -> List[FaultRule]:
        """Latency-injection rules: every direct request admission and
        stream event of THIS replica pays ``delay_s``."""
        return [
            FaultRule(site="worker.direct.request", kind="delay",
                      delay_s=delay_s, times=None,
                      match={"worker": self.tag}),
            FaultRule(site="worker.direct.stream", kind="delay",
                      delay_s=delay_s, times=None,
                      match={"worker": self.tag}),
        ]

    def jitter_rules(self, delay_s: float, prob: float) -> List[FaultRule]:
        """Gray jitter: each direct request/stream event of THIS replica
        pays ``delay_s`` at ``prob`` — a noisy NIC rather than a uniformly
        slow host, so latency-window health scoring sees a fat tail, not a
        shifted median."""
        return [
            FaultRule(site="worker.direct.request", kind="delay",
                      delay_s=delay_s, prob=prob, times=None,
                      match={"worker": self.tag}),
            FaultRule(site="worker.direct.stream", kind="delay",
                      delay_s=delay_s, prob=prob, times=None,
                      match={"worker": self.tag}),
        ]

    def flaky_rules(self, prob: float) -> List[FaultRule]:
        """Gray flakiness: THIS replica's direct admission answers HTTP 500
        at ``prob`` while the process — and its heartbeats — stay healthy.
        Consulted through the :func:`~.faults.http_reject` seam so the
        client sees a real status, not a cut socket."""
        return [
            FaultRule(site="worker.direct.request", kind="error",
                      status=500, prob=prob, times=None,
                      match={"worker": self.tag}),
        ]

    # -- introspection -------------------------------------------------------

    def engine_quiet(self) -> bool:
        return self.llm is None or self.llm.engine is None \
            or self.llm.engine.num_active == 0


class FakeFleetWorker:
    """Lightweight fleet member for plane-scale benchmarking (round 15):
    registers, heartbeats, claims and INSTANTLY completes jobs through the
    real :class:`~..worker.api_client.APIClient` — the full control-plane
    protocol (signing, epoch-fenced completion, plane failover) with no
    JAX engine, no batcher, no direct server. Hundreds of these fit in one
    process, which is what measuring claims/s and heartbeat ingest against
    the plane cohort needs. Exposes the :class:`FleetWorker` lifecycle
    subset the chaos driver touches (``alive``/``kill``/``start``/
    ``blackout``)."""

    def __init__(self, index: int, plane_url: Any,
                 hb_interval_s: float = 0.2,
                 poll_interval_s: float = 0.05,
                 region: str = "us-west") -> None:
        self.index = index
        self.plane_url = plane_url
        self.hb_interval_s = hb_interval_s
        self.poll_interval_s = poll_interval_s
        self.region = region
        self.tag = f"fk{index}"
        self.fingerprint = f"fake-{index}-{uuid.uuid4().hex[:8]}"
        self.alive = False
        self.api: Optional[Any] = None
        self.worker_id: Optional[str] = None
        self.completed = 0          # jobs this member instantly served
        self.heartbeats = 0         # beats that reached a plane
        self._hb_blocked = threading.Event()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    def start(self) -> None:
        from ..worker.api_client import APIClient

        api = APIClient(self.plane_url, backoff_s=0.0)
        api.fault_tag = self.tag
        api.register({
            "name": self.tag, "region": self.region,
            "machine_fingerprint": self.fingerprint,
            "supported_types": ["llm"], "supports_direct": False,
        })
        self.worker_id = api.worker_id
        self.api = api
        self._hb_blocked.clear()
        self._stop.clear()
        try:
            api.heartbeat(status="idle")
            self.heartbeats += 1
        except Exception:  # noqa: BLE001 — loop beats catch up
            pass
        self._threads = [
            threading.Thread(target=self._hb_loop,
                             name=f"{self.tag}-hb", daemon=True),
            threading.Thread(target=self._poll_loop,
                             name=f"{self.tag}-poll", daemon=True),
        ]
        for t in self._threads:
            t.start()
        self.alive = True

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.hb_interval_s):
            if self._hb_blocked.is_set():
                continue
            try:
                self.api.heartbeat(status="idle")
                self.heartbeats += 1
            except Exception:  # noqa: BLE001 — outage: next tick retries
                pass

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                job = self.api.fetch_next_job()
                if job is None:
                    continue
                self.api.complete_job(
                    job["id"], True,
                    result={"text": f"fake:{job['id']}"},
                    assignment_epoch=job.get("assignment_epoch"),
                )
                self.completed += 1
            except Exception:  # noqa: BLE001 — outage: next tick retries
                pass

    def blackout(self, on: bool) -> None:
        if on:
            self._hb_blocked.set()
        else:
            self._hb_blocked.clear()

    def kill(self) -> None:
        if not self.alive:
            return
        self.alive = False
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []
        if self.api is not None:
            self.api.close()
            self.api = None

    def stop(self) -> None:
        self.kill()


class LiveFleet:
    """Context manager: a live control plane + N real workers + a seeded
    chaos driver. The production composition in one object:

    - every member is a real ``Worker`` (shared serving claims, stream
      checkpoints, drain/zombie fencing) serving through the batcher;
    - a sweeper thread runs the guarantee sweeps on a fast cadence, like
      the production background worker;
    - :meth:`run_chaos` executes a :class:`FleetFaultPlan` against
      wall-clock offsets while the caller drives traffic, reporting every
      event to the plane's ``chaos_*`` metrics and the plan's trace.
    """

    def __init__(self, n: int = 2,
                 engine_config: Optional[Dict[str, Any]] = None,
                 heartbeat_timeout_s: float = 0.9,
                 hb_interval_s: float = 0.2,
                 poll_interval_s: float = 0.05,
                 sweep_interval_s: float = 0.25,
                 submit_queue_limit: int = 0,
                 roles: Optional[List[Optional[str]]] = None,
                 pd_data_plane: bool = False,
                 n_planes: int = 1,
                 fake_engines: bool = False) -> None:
        self.n = n
        self.engine_config = dict(engine_config or DEFAULT_FLEET_ENGINE)
        self.hb_interval_s = hb_interval_s
        self.poll_interval_s = poll_interval_s
        self.sweep_interval_s = sweep_interval_s
        self.roles = list(roles) if roles is not None else [None] * n
        if len(self.roles) != n:
            raise ValueError("roles must have one entry per member")
        # PD split fleets: every member runs a /kv/transfer data plane and
        # registers its URL (role rebalance can point a handoff anywhere)
        self.pd_data_plane = pd_data_plane
        # fake_engines (round 15): members are FakeFleetWorker — heartbeat
        # + claim + instant-complete through the real APIClient, no JAX
        # engine. The plane-scale bench packs hundreds into one process.
        self.fake_engines = fake_engines
        # replicated control planes (round 15): N plane replicas over ONE
        # shared sqlite file. ``:memory:`` cannot be shared across
        # connections, so a multi-plane fleet gets a temp db file; the
        # single-plane default keeps the exact round-9 construction
        # (in-memory store, PlaneCluster disabled — byte-identical).
        self.n_planes = max(1, int(n_planes))
        self._db_tmp: Optional[tempfile.TemporaryDirectory] = None
        if self.n_planes == 1:
            self.plane = LiveControlPlane(
                heartbeat_timeout_s=heartbeat_timeout_s,
                submit_queue_limit=submit_queue_limit,
            )
            self.planes: List[LiveControlPlane] = [self.plane]
        else:
            self._db_tmp = tempfile.TemporaryDirectory(prefix="dgi-planes-")
            db_path = os.path.join(self._db_tmp.name, "jobs.db")
            self.planes = [
                LiveControlPlane(
                    db_path=db_path,
                    heartbeat_timeout_s=heartbeat_timeout_s,
                    submit_queue_limit=submit_queue_limit,
                    plane_id=f"plane-{i}",
                )
                for i in range(self.n_planes)
            ]
            self.plane = self.planes[0]
        self.members: List[Union[FleetWorker, FakeFleetWorker]] = []
        self._sweep_stop = threading.Event()
        self._sweeper: Optional[threading.Thread] = None
        self._chaos_thread: Optional[threading.Thread] = None
        self._chaos_failure: Optional[BaseException] = None

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self) -> "LiveFleet":
        for p in self.planes:
            p.__enter__()
        if len(self.planes) > 1:
            # peer membership needs every port, which only exists after
            # start — wire it post-hoc (PlaneCluster reads peers per
            # forward, so a late assignment is safe)
            for p in self.planes:
                p.state.plane.peers = [
                    q.url for q in self.planes if q is not p
                ]
        try:
            for i in range(self.n):
                m = self._build_member(i, role=self.roles[i])
                m.start()
                self.members.append(m)
            self._sweep_stop.clear()
            self._sweeper = threading.Thread(
                target=self._sweep_loop, name="fleet-sweeper", daemon=True
            )
            self._sweeper.start()
        except BaseException:
            self.__exit__(None, None, None)
            raise
        return self

    def _build_member(
        self, index: int, role: Optional[str] = None
    ) -> Union[FleetWorker, FakeFleetWorker]:
        # workers get EVERY plane endpoint (single-plane: the same string
        # as always) — the APIClient's sticky health-probed failover owns
        # which one is active
        urls = self.plane_urls
        target = urls[0] if len(urls) == 1 else urls
        if self.fake_engines:
            return FakeFleetWorker(
                index, target,
                hb_interval_s=self.hb_interval_s,
                poll_interval_s=self.poll_interval_s,
            )
        return FleetWorker(
            index, target, self.engine_config,
            hb_interval_s=self.hb_interval_s,
            poll_interval_s=self.poll_interval_s,
            role=role,
            pd_data_plane=self.pd_data_plane,
        )

    def __exit__(self, *exc: Any) -> None:
        try:
            self.wait_chaos(timeout_s=30.0)
        finally:
            self._sweep_stop.set()
            if self._sweeper is not None:
                self._sweeper.join(timeout=5.0)
            for m in self.members:
                try:
                    m.stop()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            for p in self.planes:
                p.__exit__(None, None, None)
            if self._db_tmp is not None:
                try:
                    self._db_tmp.cleanup()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass

    def _sweep_loop(self) -> None:
        while not self._sweep_stop.wait(self.sweep_interval_s):
            # sweeps must survive plane chaos: run on the first ALIVE
            # replica (guarantee sweeps are fenced conditional writes over
            # the shared store, so any replica may run them)
            for p in self.planes:
                if not p.alive:
                    continue
                try:
                    p.sweep()
                    break
                except Exception:  # noqa: BLE001 — next plane / next tick
                    continue

    @property
    def url(self) -> str:
        return self.plane.url

    @property
    def plane_urls(self) -> List[str]:
        return [p.url for p in self.planes]

    def any_plane(self) -> LiveControlPlane:
        """The first ALIVE plane replica (for store queries / sweeps in
        tests while chaos may have killed the primary)."""
        for p in self.planes:
            if p.alive:
                return p
        return self.plane

    def alive_members(self) -> List[Union[FleetWorker, FakeFleetWorker]]:
        return [m for m in self.members if m.alive]

    # -- elastic capacity (round 12: the autoscaler's actuation surface) -----

    def scale_out(self, role: Optional[str] = None) -> FleetWorker:
        """Add one COLD replica to the running fleet: a fresh
        :class:`FleetWorker` (new engine build, registration, first
        heartbeat) appended after the existing members, so chaos-plan
        worker indices stay stable. Blocks until the replica is
        registered and heartbeating — the caller measuring cold-start
        lead time times this call."""
        m = self._build_member(len(self.members), role=role)
        m.start()
        self.members.append(m)
        self.roles.append(role)
        return m

    def scale_in(self) -> Optional[FleetWorker]:
        """Retire the most recently added ALIVE replica (LIFO — scaled-out
        capacity goes first, the founding members last). The kill is
        abrupt by design: the control plane's sweeps requeue anything it
        was running, which is exactly the failure path scale-in must
        compose with. Returns the retired member, or None when only one
        replica is alive (never scale to zero)."""
        alive = self.alive_members()
        if len(alive) <= 1:
            return None
        victim = alive[-1]
        victim.kill()
        return victim

    # -- chaos driver --------------------------------------------------------

    def run_chaos(self, plan: FleetFaultPlan,
                  block: bool = False) -> threading.Thread:
        """Execute ``plan`` on a background thread (or inline with
        ``block=True``): each event fires at its wall-clock offset from
        now, windowed events (blackout/partition/pressure/slow) arm their
        effect and disarm it ``duration_s`` later. A :class:`FaultPlan`
        seeded from the fleet plan is installed for the whole run — the
        rule container the windowed events arm into — so callers must not
        hold their own installed plan concurrently."""
        if self._chaos_thread is not None and \
                self._chaos_thread.is_alive():
            raise RuntimeError("a chaos run is already in flight")
        self._chaos_failure = None

        def drive() -> None:
            try:
                self._drive_chaos(plan)
            except BaseException as exc:  # noqa: BLE001 — surfaced on wait
                self._chaos_failure = exc

        t = threading.Thread(target=drive, name="fleet-chaos", daemon=True)
        self._chaos_thread = t
        t.start()
        if block:
            self.wait_chaos()
        return t

    def wait_chaos(self, timeout_s: float = 120.0) -> None:
        """Join the in-flight chaos run; re-raises a driver failure."""
        t = self._chaos_thread
        if t is not None:
            t.join(timeout=timeout_s)
            self._chaos_thread = None
        if self._chaos_failure is not None:
            failure, self._chaos_failure = self._chaos_failure, None
            raise failure

    def _emit(self, kind: str) -> None:
        try:
            self.plane.state.metrics.record_chaos_event(kind)
        except Exception:  # noqa: BLE001 — metrics are best-effort
            pass

    def _drive_chaos(self, plan: FleetFaultPlan) -> None:
        t0 = time.monotonic()
        pending = sorted(plan.events, key=lambda e: e.at_s)
        undo: List[tuple] = []   # (due_at_offset, fn)
        with _faults.active(FaultPlan(plan.seed)) as fp:
            while pending or undo:
                now = time.monotonic() - t0
                for due, fn in [u for u in undo if u[0] <= now]:
                    undo.remove((due, fn))
                    fn()
                while pending and pending[0].at_s <= now:
                    ev = pending.pop(0)
                    plan.record(now, ev.kind, ev.worker)
                    self._emit(ev.kind)
                    end = self._execute(ev, fp)
                    if end is not None:
                        # window duration runs from the ACTUAL arm time:
                        # a preceding kill/restart can block the driver
                        # past at_s, and anchoring the disarm to the
                        # scheduled offset would silently collapse the
                        # window to nothing on a slow box
                        undo.append((
                            (time.monotonic() - t0) + ev.duration_s, end
                        ))
                time.sleep(0.02)

    def _execute(self, ev: Any, fp: FaultPlan) -> Optional[Any]:
        """Apply one fleet event; returns the disarm callback for windowed
        kinds (None for kill/restart)."""
        member = (
            self.members[ev.worker]
            if ev.worker >= 0 and not ev.kind.startswith("plane_") else None
        )
        if ev.kind == "kill":
            member.kill()
            return None
        if ev.kind == "restart":
            member.start()
            return None
        if ev.kind == "blackout":
            member.blackout(True)
            return lambda: member.blackout(False)
        if ev.kind == "partition":
            member.blackout(True)
            rules = [fp.add_rule(r) for r in member.partition_rules()]

            def heal() -> None:
                for r in rules:
                    fp.remove_rule(r)
                member.blackout(False)

            return heal
        if ev.kind in ("slow", "degrade"):
            # degrade reuses the slow seam with a far heavier delay over a
            # far longer window — the gray failure the quarantine exists
            # to catch (the replica heartbeats fine the whole time)
            rules = [fp.add_rule(r) for r in member.slow_rules(ev.delay_s)]
            return lambda: [fp.remove_rule(r) for r in rules]
        if ev.kind == "jitter":
            rules = [fp.add_rule(r)
                     for r in member.jitter_rules(ev.delay_s, ev.prob)]
            return lambda: [fp.remove_rule(r) for r in rules]
        if ev.kind == "flaky":
            rules = [fp.add_rule(r) for r in member.flaky_rules(ev.prob)]
            return lambda: [fp.remove_rule(r) for r in rules]
        if ev.kind == "pressure":
            rule = fp.add_rule(FaultRule(
                site="kv.block.alloc", kind="pressure", prob=ev.prob,
            ))
            return lambda: fp.remove_rule(rule)
        if ev.kind == "handoff_partition":
            rules = (member.handoff_rules() if member is not None else
                     [FaultRule(site="worker.pd.push", kind="flap",
                                times=None)])
            armed = [fp.add_rule(r) for r in rules]
            return lambda: [fp.remove_rule(r) for r in armed]
        if ev.kind == "handoff_corrupt":
            # fleet-wide: any receiver sees truncated handoff messages at
            # ev.prob — pieces poison their session, commits abort, and
            # the sender's retry/abort + the flow's re-prefill recover
            rule = fp.add_rule(FaultRule(
                site="kv.receiver.message", kind="truncate", cut=48,
                prob=ev.prob, times=None,
            ))
            return lambda: fp.remove_rule(rule)
        if ev.kind == "handoff_delay":
            rules = (member.handoff_delay_rules(ev.delay_s)
                     if member is not None else
                     [FaultRule(site="worker.pd.push", kind="delay",
                                delay_s=ev.delay_s, times=None)])
            armed = [fp.add_rule(r) for r in rules]
            return lambda: [fp.remove_rule(r) for r in armed]
        if ev.kind == "disk_full":
            # the durable tier fills up fleet-wide: every WRITE surface
            # fails for the window — store mutations (sql-matched so
            # reads keep serving and the typed-503 contract is what
            # clients observe), checkpoint upserts, spill puts, file
            # persists. Recovery is pure disarm: space "frees up".
            armed = [fp.add_rule(FaultRule(site=s, kind="error",
                                           times=None, **kw))
                     for s, kw in (
                         ("server.store.execute",
                          {"match": {"sql": "INSERT*"}}),
                         ("server.store.execute",
                          {"match": {"sql": "UPDATE*"}}),
                         ("server.store.checkpoint", {}),
                         ("io.spill.*.put", {}),
                         ("io.file.write", {}),
                     )]
            return lambda: [fp.remove_rule(r) for r in armed]
        if ev.kind == "io_error":
            # flaky device: spill-tier reads AND writes fail at ev.prob
            # (both directions — the breaker sees consecutive failures),
            # checkpoint writes too
            armed = [fp.add_rule(FaultRule(
                site=s, kind="error", prob=ev.prob, times=None,
            )) for s in ("io.spill.*", "server.store.checkpoint")]
            return lambda: [fp.remove_rule(r) for r in armed]
        if ev.kind == "io_slow":
            # browned-out device: every spill op pays ev.delay_s — the
            # redis path converts sustained slowness into slow_trips +
            # backoff, the rest just rides it out (worker-side seams
            # only: no event-loop stalls on the plane)
            rule = fp.add_rule(FaultRule(
                site="io.spill.*", kind="delay", delay_s=ev.delay_s,
                times=None,
            ))
            return lambda: fp.remove_rule(rule)
        if ev.kind == "corrupt_read":
            # bit rot: spilled frames and handoff staging buffers read
            # back flipped at ev.prob — the CRC catches the spill frames
            # (quarantine + next tier / recompute), the piece contract
            # catches the staging buffers
            armed = [fp.add_rule(FaultRule(
                site=s, kind="corrupt", prob=ev.prob, times=None,
            )) for s in ("io.spill.remote.get", "io.handoff.stage")]
            return lambda: [fp.remove_rule(r) for r in armed]
        if ev.kind == "torn_write":
            # power-loss torn writes: spilled frames persist only a
            # prefix at ev.prob — detected at READ time by the frame CRC
            # (or the torn-header check), quarantined, never served
            rule = fp.add_rule(FaultRule(
                site="io.spill.remote.put", kind="truncate", cut=32,
                prob=ev.prob, times=None,
            ))
            return lambda: fp.remove_rule(rule)
        if ev.kind == "plane_kill":
            # ev.worker indexes the PLANE cohort for plane events
            self.planes[ev.worker].kill()
            return None
        if ev.kind == "plane_restart":
            self.planes[ev.worker].start()
            return None
        if ev.kind in ("plane_partition", "plane_slow"):
            # cut (or tax) every request ADDRESSED TO this plane at the
            # client seams — worker API calls, health probes, SDK calls —
            # while the plane process itself stays up. Matching on the
            # destination endpoint means failover probes see exactly what
            # real requests see.
            pat = f"*:{self.planes[ev.worker].port}"
            kw: Dict[str, Any] = (
                {"kind": "flap"} if ev.kind == "plane_partition"
                else {"kind": "delay", "delay_s": ev.delay_s}
            )
            armed = [
                fp.add_rule(FaultRule(site="worker.api.request", times=None,
                                      match={"server": pat}, **kw)),
                fp.add_rule(FaultRule(site="sdk.client.request", times=None,
                                      match={"server": pat}, **kw)),
            ]
            return lambda: [fp.remove_rule(r) for r in armed]
        raise ValueError(f"unknown fleet event kind {ev.kind!r}")


# ---------------------------------------------------------------------------
# brownout-driven autoscaling (round 12): the controller's actuation loop
# ---------------------------------------------------------------------------


class FleetAutoscaler:
    """Ticker thread wiring a
    :class:`~..server.autoscaler.BrownoutAutoscaler` to a
    :class:`LiveFleet`: every ``tick_s`` the controller sees the CURRENT
    alive replica count (chaos kills included — scaling decisions and
    failures compose) and a utilization estimate from the plane's queue
    stats; ``scale_out`` adds a cold replica (the bring-up is timed and
    fed back as the measured cold-start lead time), ``scale_in`` retires
    the youngest. The traffic driver feeds per-request SLO samples via
    ``autoscaler.observe`` directly."""

    def __init__(self, fleet: LiveFleet, autoscaler: Any,
                 tick_s: float = 0.5,
                 scale_out_role: Optional[str] = None,
                 rebalancer: Optional[Any] = None) -> None:
        self.fleet = fleet
        self.autoscaler = autoscaler
        self.tick_s = tick_s
        self.scale_out_role = scale_out_role
        # predictive rebalance (round 18): a
        # ``server.autoscaler.PredictiveRebalancer`` ticked every loop —
        # its starved-side suggestion overrides the static scale_out_role
        # so a projected prefill shortage lands a prefill replica
        self.rebalancer = rebalancer
        self.actions: List[tuple] = []       # (wall_offset_s, action)
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._failure: Optional[BaseException] = None

    def _utilization(self) -> Optional[float]:
        """Coarse fleet utilization in [0, 1]: queued work saturates to
        1.0; otherwise the busy fraction of live workers."""
        try:
            stats = self.fleet.plane.call(
                self.fleet.plane.state.store.queue_stats()
            )
        except Exception:  # noqa: BLE001 — plane busy: skip this tick
            return None
        if int(stats.get("queued") or 0) > 0:
            return 1.0
        w = stats.get("workers") or {}
        busy = int(w.get("busy") or 0)
        idle = int(w.get("idle") or 0)
        return busy / (busy + idle) if (busy + idle) else None

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            replicas = len(self.fleet.alive_members())
            suggested = None
            if self.rebalancer is not None:
                # every tick, not just scale-outs: restores (projection
                # recovered) must land even while the fleet holds
                try:
                    suggested = self.rebalancer.tick()
                except Exception:  # noqa: BLE001 — advisory
                    suggested = None
            action = self.autoscaler.tick(replicas, self._utilization())
            if action == "scale_out":
                self.actions.append(
                    (time.monotonic() - self._t0, "scale_out"))
                self.autoscaler.note_scale_out_started()
                self.fleet.scale_out(role=suggested or self.scale_out_role)
                # scale_out blocks through engine build + registration +
                # first heartbeat: the replica is ready to serve, so this
                # IS the cold-start lead time the projection needs
                self.autoscaler.note_replica_serving()
            elif action == "scale_in":
                self.actions.append(
                    (time.monotonic() - self._t0, "scale_in"))
                self.fleet.scale_in()

    def start(self) -> "FleetAutoscaler":
        self._t0 = time.monotonic()

        def run() -> None:
            try:
                self._loop()
            except BaseException as exc:  # noqa: BLE001 — surfaced on stop
                self._failure = exc

        self._thread = threading.Thread(
            target=run, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 60.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
        if self._failure is not None:
            failure, self._failure = self._failure, None
            raise failure
