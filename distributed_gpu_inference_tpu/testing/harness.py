"""Chaos-scenario harness: a REAL control plane on a loopback socket.

The worker ``APIClient`` and SDK ``InferenceClient`` are synchronous httpx
clients, while the control plane is an aiohttp app. To drive both ends of
the real protocol in one test, :class:`LiveControlPlane` runs the server's
event loop on a background thread and binds the app to an ephemeral
loopback port; the test thread then talks real HTTP through the real
clients (retry ladders, signing, fault seams and all), and can reach into
the server's services (sweeps with a simulated clock, store queries) via
:meth:`call`.
"""

from __future__ import annotations

import asyncio
import socket
import threading
from typing import Any, Coroutine, Dict, List, Optional

from aiohttp import web

from ..server.app import ServerState, create_app


class LiveControlPlane:
    """Context manager: a served control plane + direct service access."""

    def __init__(self, **state_kw: Any) -> None:
        self._state_kw = state_kw
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._runner: Optional[web.AppRunner] = None
        self.state: Optional[ServerState] = None
        self.port: int = 0

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self) -> "LiveControlPlane":
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="live-control-plane",
            daemon=True,
        )
        self._thread.start()
        self.call(self._start())
        return self

    def __exit__(self, *exc: Any) -> None:
        try:
            self.call(self._stop())
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10.0)
            self._loop.close()

    async def _start(self) -> None:
        # ServerState (and its Store/asyncio primitives) is created on the
        # server loop so nothing binds to the test thread
        self.state = ServerState(**self._state_kw)
        app = create_app(self.state, start_background=False)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        self.port = sock.getsockname()[1]
        site = web.SockSite(self._runner, sock)
        await site.start()

    async def _stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        if self.state is not None:
            self.state.store.close()

    # -- access --------------------------------------------------------------

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def call(self, coro: Coroutine, timeout_s: float = 30.0) -> Any:
        """Run a coroutine on the server loop from the test thread."""
        return asyncio.run_coroutine_threadsafe(
            coro, self._loop
        ).result(timeout=timeout_s)

    # -- common shortcuts ----------------------------------------------------

    def sweep(self, now: Optional[float] = None) -> Dict[str, List[str]]:
        return self.call(self.state.guarantee.sweep(now=now))

    def query(self, sql: str, params: tuple = ()) -> List[Dict[str, Any]]:
        return self.call(self.state.store.query(sql, params))

    def job(self, job_id: str) -> Optional[Dict[str, Any]]:
        return self.call(self.state.store.get_job(job_id))

    def worker(self, worker_id: str) -> Optional[Dict[str, Any]]:
        return self.call(self.state.store.get_worker(worker_id))
