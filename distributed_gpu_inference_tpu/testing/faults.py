"""Deterministic, seed-driven fault injection for chaos scenarios.

A :class:`FaultPlan` is a seeded RNG plus an ordered list of
:class:`FaultRule`\\ s. Injection seams threaded through the production code
(``worker.api_client``, ``sdk.client``, ``server.store``, ``comm.session``,
``comm.grpc_plane``, ``runtime.kv_handoff``) consult the installed plan on
every hit; the FIRST matching rule that fires decides the effect. Rules are
matched by glob against a dotted site name (e.g. ``worker.api.request``)
and optionally against call context (``match={"path": "*/complete"}``).

Determinism contract: with the same seed, the same rules, and the same call
sequence, a plan fires identically and records an identical ``trace`` —
chaos scenarios assert this (same seed → same fault trace) and replay
across many seeds.

Zero cost when disabled: no plan is ever constructed in production paths,
and every seam helper starts with ``if _ACTIVE is None: passthrough``.

Rule kinds and where they apply:

=========  =======================================================
kind       effect at a seam
=========  =======================================================
drop       HTTP/RPC: raise a transport error. ``where="response"``
           performs the call first (delivered, response lost) —
           the building block for duplicate-delivery scenarios.
           Store: silently skip the mutation (lost write).
           Byte/stream: message lost in transit.
delay      sleep ``delay_s`` then proceed.
error      HTTP: synthesize a ``status`` response without calling.
           Store: raise ``sqlite3.OperationalError``.
truncate   byte seams: keep only the first ``cut`` bytes.
duplicate  HTTP: perform the call twice, return the second
           response. Stream filter: deliver the message twice.
flap       unconditional drop for the next ``times`` hits — a
           server/link that is down for a window, then recovers.
reorder    stream filter only: hold the message and deliver it
           right after the next delivered message (or last).
pressure   KV allocator seam only: the hit sees a pool with zero
           free blocks (``OutOfBlocksError`` at the call site) —
           drives seeded preemption storms through the engine's
           preempt → spill → resume recovery path.
=========  =======================================================
"""

from __future__ import annotations

import fnmatch
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field, replace
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

_KINDS = {
    "drop", "delay", "error", "truncate", "duplicate", "flap", "reorder",
    "pressure",
}


@dataclass
class FaultRule:
    """One injection rule; see the module docstring for kind semantics."""

    site: str                      # glob over the dotted site name
    kind: str
    prob: float = 1.0              # per-hit firing probability (seeded RNG)
    after: int = 0                 # skip the first N matching hits
    times: Optional[int] = None    # max firings (None = unlimited)
    where: str = "request"         # drop: "request" | "response"
    status: int = 500              # error: synthesized HTTP status
    delay_s: float = 0.0
    cut: int = 64                  # truncate: bytes kept
    match: Dict[str, str] = field(default_factory=dict)  # ctx key → glob
    # live counters, owned by the plan (plans copy rules on construction
    # so one rule list can seed many replays)
    hits: int = 0
    fired: int = 0

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (one of {sorted(_KINDS)})"
            )
        if self.where not in ("request", "response"):
            raise ValueError("where must be 'request' or 'response'")


def flap(site: str, times: int = 1, after: int = 0, **kw: Any) -> FaultRule:
    """Sugar: the site is hard-down for the next ``times`` hits."""
    return FaultRule(site=site, kind="flap", prob=1.0, times=times,
                     after=after, **kw)


class FaultInjected(ConnectionError):
    """Raised at non-HTTP seams for injected drops (bytes/RPC)."""


class FaultPlan:
    """Seeded rule set + trace. Install with :func:`install` /
    :func:`active`; seams consult it via the module-level helpers."""

    def __init__(self, seed: int = 0,
                 rules: Sequence[FaultRule] = ()) -> None:
        self.seed = seed
        # private copies: firing mutates counters, and scenario code reuses
        # one rule list across seeded replays
        self.rules: List[FaultRule] = [
            replace(r, hits=0, fired=0) for r in rules
        ]
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.trace: List[Tuple[str, str, str]] = []

    # -- core ---------------------------------------------------------------

    def fire(self, site: str, **ctx: Any) -> Optional[FaultRule]:
        """Return the first rule that fires for this hit, else None.
        Thread-safe; every firing is appended to ``trace``."""
        with self._lock:
            for r in self.rules:
                if not fnmatch.fnmatchcase(site, r.site):
                    continue
                if any(
                    not fnmatch.fnmatchcase(str(ctx.get(k, "")), pat)
                    for k, pat in r.match.items()
                ):
                    continue
                r.hits += 1
                if r.hits <= r.after:
                    continue
                if r.times is not None and r.fired >= r.times:
                    continue
                if r.prob < 1.0 and self._rng.random() >= r.prob:
                    continue
                r.fired += 1
                self.trace.append((site, r.kind, _ctx_str(ctx)))
                return r
        return None

    # -- stream filtering (transport-level loss/reorder/dup) ----------------

    def filter_stream(
        self,
        site: str,
        messages: Iterable[bytes],
        ctx_fn: Optional[Callable[[bytes], Dict[str, Any]]] = None,
    ) -> Iterator[bytes]:
        """Model an unreliable in-flight message sequence: apply drop /
        duplicate / reorder / truncate rules to each message of ``site``.
        ``ctx_fn(msg)`` supplies per-message match context (e.g. the stream
        message kind) so rules can target, say, only ``commit`` frames.

        ``reorder`` holds the message and releases it right after the next
        DELIVERED message (messages dropped in between don't flush it, and
        consecutive reorders queue up in order); anything still held when
        the sequence ends is delivered last."""
        held: List[bytes] = []
        for msg in messages:
            ctx = ctx_fn(msg) if ctx_fn is not None else {}
            rule = self.fire(site, **ctx)
            if rule is None:
                out = [msg]
            elif rule.kind in ("drop", "flap"):
                out = []
            elif rule.kind == "duplicate":
                out = [msg, msg]
            elif rule.kind == "truncate":
                out = [msg[: rule.cut]]
            elif rule.kind == "reorder":
                held.append(msg)
                continue
            elif rule.kind == "delay":
                time.sleep(rule.delay_s)
                out = [msg]
            else:
                raise ValueError(
                    f"rule kind {rule.kind!r} unsupported in filter_stream"
                )
            for m in out:
                yield m
                if held:
                    yield from held
                    held = []
        yield from held


def _ctx_str(ctx: Dict[str, Any]) -> str:
    return ",".join(f"{k}={ctx[k]}" for k in sorted(ctx))


# ---------------------------------------------------------------------------
# plan installation
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> FaultPlan:
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError(
            "a FaultPlan is already installed — uninstall it first "
            "(leaked plan from a previous scenario?)"
        )
    _ACTIVE = plan
    return plan


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def current() -> Optional[FaultPlan]:
    return _ACTIVE


@contextmanager
def active(plan: FaultPlan) -> Iterator[FaultPlan]:
    install(plan)
    try:
        yield plan
    finally:
        uninstall()


# ---------------------------------------------------------------------------
# injection seams (all no-ops when no plan is installed)
# ---------------------------------------------------------------------------


def wrap_http(site: str, call: Callable[[], Any], **ctx: Any):
    """HTTP client seam: ``call`` performs the real transport request and
    returns an ``httpx.Response``. Injected effects surface exactly like
    real network behavior so the caller's retry ladder is exercised."""
    plan = _ACTIVE
    if plan is None:
        return call()
    rule = plan.fire(site, **ctx)
    if rule is None:
        return call()
    import httpx

    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return call()
    if rule.kind in ("drop", "flap"):
        if rule.where == "response":
            call()  # delivered server-side; the response is lost
        raise httpx.ConnectError(f"fault injected: {rule.kind} at {site}")
    if rule.kind == "error":
        req = httpx.Request(
            str(ctx.get("method", "GET")), f"http://fault.invalid/{site}"
        )
        return httpx.Response(
            rule.status, request=req,
            json={"detail": f"fault injected at {site}"},
        )
    if rule.kind == "duplicate":
        call()
        return call()
    raise ValueError(f"rule kind {rule.kind!r} unsupported at HTTP seam")


def wrap_rpc(site: str, call: Callable[[], Any], **ctx: Any):
    """Generic RPC seam (gRPC data plane): drops surface as
    :class:`FaultInjected` (a ``ConnectionError``)."""
    plan = _ACTIVE
    if plan is None:
        return call()
    rule = plan.fire(site, **ctx)
    if rule is None:
        return call()
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return call()
    if rule.kind in ("drop", "flap", "error"):
        if rule.where == "response":
            call()
        raise FaultInjected(f"fault injected: {rule.kind} at {site}")
    if rule.kind == "duplicate":
        call()
        return call()
    raise ValueError(f"rule kind {rule.kind!r} unsupported at RPC seam")


def store_fault(site: str, **ctx: Any) -> bool:
    """Store mutation seam. Returns True when the write must be SKIPPED
    (injected lost write); raises ``sqlite3.OperationalError`` for injected
    backend errors."""
    plan = _ACTIVE
    if plan is None:
        return False
    rule = plan.fire(site, **ctx)
    if rule is None:
        return False
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return False
    if rule.kind == "drop":
        return True
    if rule.kind in ("error", "flap"):
        import sqlite3

        raise sqlite3.OperationalError(f"fault injected at {site}")
    raise ValueError(f"rule kind {rule.kind!r} unsupported at store seam")


def kv_pressure(site: str, num_free: int, **ctx: Any) -> bool:
    """KV block-allocator seam (``PagedKVCacheManager._pop_free_block``).
    Returns True when THIS allocation must behave as pool-exhausted — the
    caller raises ``OutOfBlocksError`` exactly as a genuinely full pool
    would, exercising the engine's preempt → spill → resume recovery.
    ``num_free`` rides in the trace context so a storm's firing points are
    reproducible down to the observed pool state."""
    plan = _ACTIVE
    if plan is None:
        return False
    rule = plan.fire(site, num_free=num_free, **ctx)
    if rule is None:
        return False
    if rule.kind == "pressure":
        return True
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return False
    raise ValueError(f"rule kind {rule.kind!r} unsupported at kv seam")


def stream_cut(site: str, **ctx: Any) -> bool:
    """Server-push stream seam (worker SSE): returns True when the stream
    must die ABRUPTLY at this event — the handler hard-closes the socket,
    modelling a worker process crash mid-generation. ``after=N`` on the
    rule lets exactly N events through first, so a seeded kill point is
    reproducible to the event."""
    plan = _ACTIVE
    if plan is None:
        return False
    rule = plan.fire(site, **ctx)
    if rule is None:
        return False
    if rule.kind in ("drop", "flap"):
        return True
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return False
    raise ValueError(f"rule kind {rule.kind!r} unsupported at stream seam")


def mutate_bytes(site: str, data: bytes, **ctx: Any) -> bytes:
    """Byte-message seam (KV handoff receiver): truncate or lose a message
    in transit. Drops raise :class:`FaultInjected`, which the transport
    layer reports to the sender like any receive failure."""
    plan = _ACTIVE
    if plan is None:
        return data
    rule = plan.fire(site, size=len(data), **ctx)
    if rule is None:
        return data
    if rule.kind == "truncate":
        return data[: rule.cut]
    if rule.kind in ("drop", "flap"):
        raise FaultInjected(f"fault injected: {rule.kind} at {site}")
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return data
    raise ValueError(f"rule kind {rule.kind!r} unsupported at byte seam")
