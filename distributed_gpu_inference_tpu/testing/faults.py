"""Deterministic, seed-driven fault injection for chaos scenarios.

A :class:`FaultPlan` is a seeded RNG plus an ordered list of
:class:`FaultRule`\\ s. Injection seams threaded through the production code
(``worker.api_client``, ``sdk.client``, ``server.store``, ``comm.session``,
``comm.grpc_plane``, ``runtime.kv_handoff``) consult the installed plan on
every hit; the FIRST matching rule that fires decides the effect. Rules are
matched by glob against a dotted site name (e.g. ``worker.api.request``)
and optionally against call context (``match={"path": "*/complete"}``).

Determinism contract: with the same seed, the same rules, and the same call
sequence, a plan fires identically and records an identical ``trace`` —
chaos scenarios assert this (same seed → same fault trace) and replay
across many seeds.

Zero cost when disabled: no plan is ever constructed in production paths,
and every seam helper starts with ``if _ACTIVE is None: passthrough``.

Rule kinds and where they apply:

=========  =======================================================
kind       effect at a seam
=========  =======================================================
drop       HTTP/RPC: raise a transport error. ``where="response"``
           performs the call first (delivered, response lost) —
           the building block for duplicate-delivery scenarios.
           Store: silently skip the mutation (lost write).
           Byte/stream: message lost in transit.
delay      sleep ``delay_s`` then proceed.
error      HTTP: synthesize a ``status`` response without calling.
           Store: raise ``sqlite3.OperationalError``.
truncate   byte seams: keep only the first ``cut`` bytes.
corrupt    byte seams (IO reads): flip one byte mid-payload — the
           bit-rot a checksum exists to catch (truncation is caught
           by length framing; corruption needs the CRC).
duplicate  HTTP: perform the call twice, return the second
           response. Stream filter: deliver the message twice.
flap       unconditional drop for the next ``times`` hits — a
           server/link that is down for a window, then recovers.
reorder    stream filter only: hold the message and deliver it
           right after the next delivered message (or last).
pressure   KV allocator seam only: the hit sees a pool with zero
           free blocks (``OutOfBlocksError`` at the call site) —
           drives seeded preemption storms through the engine's
           preempt → spill → resume recovery path.
=========  =======================================================
"""

from __future__ import annotations

import fnmatch
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field, replace
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

_KINDS = {
    "drop", "delay", "error", "truncate", "duplicate", "flap", "reorder",
    "pressure", "corrupt",
}


@dataclass
class FaultRule:
    """One injection rule; see the module docstring for kind semantics."""

    site: str                      # glob over the dotted site name
    kind: str
    prob: float = 1.0              # per-hit firing probability (seeded RNG)
    after: int = 0                 # skip the first N matching hits
    times: Optional[int] = None    # max firings (None = unlimited)
    where: str = "request"         # drop: "request" | "response"
    status: int = 500              # error: synthesized HTTP status
    delay_s: float = 0.0
    cut: int = 64                  # truncate: bytes kept
    match: Dict[str, str] = field(default_factory=dict)  # ctx key → glob
    # live counters, owned by the plan (plans copy rules on construction
    # so one rule list can seed many replays)
    hits: int = 0
    fired: int = 0

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (one of {sorted(_KINDS)})"
            )
        if self.where not in ("request", "response"):
            raise ValueError("where must be 'request' or 'response'")


def flap(site: str, times: int = 1, after: int = 0, **kw: Any) -> FaultRule:
    """Sugar: the site is hard-down for the next ``times`` hits."""
    return FaultRule(site=site, kind="flap", prob=1.0, times=times,
                     after=after, **kw)


class FaultInjected(ConnectionError):
    """Raised at non-HTTP seams for injected drops (bytes/RPC)."""


class FaultPlan:
    """Seeded rule set + trace. Install with :func:`install` /
    :func:`active`; seams consult it via the module-level helpers."""

    def __init__(self, seed: int = 0,
                 rules: Sequence[FaultRule] = ()) -> None:
        self.seed = seed
        # private copies: firing mutates counters, and scenario code reuses
        # one rule list across seeded replays
        self.rules: List[FaultRule] = [
            replace(r, hits=0, fired=0) for r in rules
        ]
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.trace: List[Tuple[str, str, str]] = []

    # -- core ---------------------------------------------------------------

    def fire(self, site: str, **ctx: Any) -> Optional[FaultRule]:
        """Return the first rule that fires for this hit, else None.
        Thread-safe; every firing is appended to ``trace``."""
        with self._lock:
            for r in self.rules:
                if not fnmatch.fnmatchcase(site, r.site):
                    continue
                if any(
                    not fnmatch.fnmatchcase(str(ctx.get(k, "")), pat)
                    for k, pat in r.match.items()
                ):
                    continue
                r.hits += 1
                if r.hits <= r.after:
                    continue
                if r.times is not None and r.fired >= r.times:
                    continue
                if r.prob < 1.0 and self._rng.random() >= r.prob:
                    continue
                r.fired += 1
                self.trace.append((site, r.kind, _ctx_str(ctx)))
                return r
        return None

    def add_rule(self, rule: FaultRule) -> FaultRule:
        """Append a rule to a LIVE plan (counters zeroed, private copy).
        The fleet chaos driver uses this to arm pressure-storm / latency
        rules at their scheduled instant while the plan is installed —
        rule matching holds the same lock as :meth:`fire`, so arming
        mid-traffic is safe."""
        r = replace(rule, hits=0, fired=0)
        with self._lock:
            self.rules.append(r)
        return r

    def remove_rule(self, rule: FaultRule) -> None:
        """Disarm a rule previously returned by :meth:`add_rule` — the end
        of a scheduled chaos window (partition heals, storm passes)."""
        with self._lock:
            try:
                self.rules.remove(rule)
            except ValueError:
                pass

    # -- stream filtering (transport-level loss/reorder/dup) ----------------

    def filter_stream(
        self,
        site: str,
        messages: Iterable[bytes],
        ctx_fn: Optional[Callable[[bytes], Dict[str, Any]]] = None,
    ) -> Iterator[bytes]:
        """Model an unreliable in-flight message sequence: apply drop /
        duplicate / reorder / truncate rules to each message of ``site``.
        ``ctx_fn(msg)`` supplies per-message match context (e.g. the stream
        message kind) so rules can target, say, only ``commit`` frames.

        ``reorder`` holds the message and releases it right after the next
        DELIVERED message (messages dropped in between don't flush it, and
        consecutive reorders queue up in order); anything still held when
        the sequence ends is delivered last."""
        held: List[bytes] = []
        for msg in messages:
            ctx = ctx_fn(msg) if ctx_fn is not None else {}
            rule = self.fire(site, **ctx)
            if rule is None:
                out = [msg]
            elif rule.kind in ("drop", "flap"):
                out = []
            elif rule.kind == "duplicate":
                out = [msg, msg]
            elif rule.kind == "truncate":
                out = [msg[: rule.cut]]
            elif rule.kind == "reorder":
                held.append(msg)
                continue
            elif rule.kind == "delay":
                time.sleep(rule.delay_s)
                out = [msg]
            else:
                raise ValueError(
                    f"rule kind {rule.kind!r} unsupported in filter_stream"
                )
            for m in out:
                yield m
                if held:
                    yield from held
                    held = []
        yield from held


def _ctx_str(ctx: Dict[str, Any]) -> str:
    return ",".join(f"{k}={ctx[k]}" for k in sorted(ctx))


# ---------------------------------------------------------------------------
# plan installation
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> FaultPlan:
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError(
            "a FaultPlan is already installed — uninstall it first "
            "(leaked plan from a previous scenario?)"
        )
    _ACTIVE = plan
    return plan


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def current() -> Optional[FaultPlan]:
    return _ACTIVE


@contextmanager
def active(plan: FaultPlan) -> Iterator[FaultPlan]:
    install(plan)
    try:
        yield plan
    finally:
        uninstall()


# ---------------------------------------------------------------------------
# injection seams (all no-ops when no plan is installed)
# ---------------------------------------------------------------------------


def wrap_http(site: str, call: Callable[[], Any], **ctx: Any):
    """HTTP client seam: ``call`` performs the real transport request and
    returns an ``httpx.Response``. Injected effects surface exactly like
    real network behavior so the caller's retry ladder is exercised."""
    plan = _ACTIVE
    if plan is None:
        return call()
    rule = plan.fire(site, **ctx)
    if rule is None:
        return call()
    import httpx

    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return call()
    if rule.kind in ("drop", "flap"):
        if rule.where == "response":
            call()  # delivered server-side; the response is lost
        raise httpx.ConnectError(f"fault injected: {rule.kind} at {site}")
    if rule.kind == "error":
        req = httpx.Request(
            str(ctx.get("method", "GET")), f"http://fault.invalid/{site}"
        )
        return httpx.Response(
            rule.status, request=req,
            json={"detail": f"fault injected at {site}"},
        )
    if rule.kind == "duplicate":
        call()
        return call()
    raise ValueError(f"rule kind {rule.kind!r} unsupported at HTTP seam")


def wrap_rpc(site: str, call: Callable[[], Any], **ctx: Any):
    """Generic RPC seam (gRPC data plane): drops surface as
    :class:`FaultInjected` (a ``ConnectionError``)."""
    plan = _ACTIVE
    if plan is None:
        return call()
    rule = plan.fire(site, **ctx)
    if rule is None:
        return call()
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return call()
    if rule.kind in ("drop", "flap", "error"):
        if rule.where == "response":
            call()
        raise FaultInjected(f"fault injected: {rule.kind} at {site}")
    if rule.kind == "duplicate":
        call()
        return call()
    raise ValueError(f"rule kind {rule.kind!r} unsupported at RPC seam")


def store_fault(site: str, **ctx: Any) -> bool:
    """Store mutation seam. Returns True when the write must be SKIPPED
    (injected lost write); raises ``sqlite3.OperationalError`` for injected
    backend errors."""
    plan = _ACTIVE
    if plan is None:
        return False
    rule = plan.fire(site, **ctx)
    if rule is None:
        return False
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return False
    if rule.kind == "drop":
        return True
    if rule.kind in ("error", "flap"):
        import sqlite3

        raise sqlite3.OperationalError(f"fault injected at {site}")
    raise ValueError(f"rule kind {rule.kind!r} unsupported at store seam")


def kv_pressure(site: str, num_free: int, **ctx: Any) -> bool:
    """KV block-allocator seam (``PagedKVCacheManager._pop_free_block``).
    Returns True when THIS allocation must behave as pool-exhausted — the
    caller raises ``OutOfBlocksError`` exactly as a genuinely full pool
    would, exercising the engine's preempt → spill → resume recovery.
    ``num_free`` rides in the trace context so a storm's firing points are
    reproducible down to the observed pool state."""
    plan = _ACTIVE
    if plan is None:
        return False
    rule = plan.fire(site, num_free=num_free, **ctx)
    if rule is None:
        return False
    if rule.kind == "pressure":
        return True
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return False
    raise ValueError(f"rule kind {rule.kind!r} unsupported at kv seam")


def stream_cut(site: str, **ctx: Any) -> bool:
    """Server-push stream seam (worker SSE): returns True when the stream
    must die ABRUPTLY at this event — the handler hard-closes the socket,
    modelling a worker process crash mid-generation. ``after=N`` on the
    rule lets exactly N events through first, so a seeded kill point is
    reproducible to the event."""
    plan = _ACTIVE
    if plan is None:
        return False
    rule = plan.fire(site, **ctx)
    if rule is None:
        return False
    if rule.kind in ("drop", "flap"):
        return True
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return False
    raise ValueError(f"rule kind {rule.kind!r} unsupported at stream seam")


def http_reject(site: str, **ctx: Any) -> Optional[int]:
    """Server-side rejection seam (worker direct endpoints). One fire()
    per event so first-match stays well-defined whatever kind is armed:

    - ``error`` rules → returns ``rule.status``: the handler must ANSWER
      with that status — a flaky replica that 5xxs requests while its
      process (and its heartbeats) stay perfectly healthy.
    - ``drop``/``flap`` rules → returns ``0``: cut the connection (same
      contract as :func:`stream_cut` returning True).
    - ``delay`` rules sleep and pass through (returns None).
    - None = serve normally."""
    plan = _ACTIVE
    if plan is None:
        return None
    rule = plan.fire(site, **ctx)
    if rule is None:
        return None
    if rule.kind == "error":
        return rule.status
    if rule.kind in ("drop", "flap"):
        return 0
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return None
    raise ValueError(f"rule kind {rule.kind!r} unsupported at reject seam")


# ---------------------------------------------------------------------------
# fleet-level chaos: seeded schedules of whole-replica events
# ---------------------------------------------------------------------------

# kinds a generated schedule draws from. ``restart`` never appears here —
# every ``kill`` emits its own paired restart event, so a schedule can
# never leave a replica dead forever by construction.
FLEET_EVENT_KINDS = ("kill", "blackout", "partition", "pressure", "slow")

# handoff-targeted kinds (round 11 — PD split fleets): chaos on the
# prefill→decode KV stream itself rather than on whole replicas.
# ``handoff_partition`` cuts a worker's outbound KV pushes (sender-side
# flap on ``worker.pd.push``), ``handoff_corrupt`` truncates received
# handoff messages in transit (``kv.receiver.message``, fleet-wide,
# probabilistic), ``handoff_delay`` injects per-piece latency so send
# timeouts + retries fire. Kept OUT of FLEET_EVENT_KINDS so round-9
# seeds keep regenerating their exact historical schedules.
HANDOFF_EVENT_KINDS = ("handoff_partition", "handoff_corrupt",
                       "handoff_delay")

# control-plane-targeted kinds (round 15 — replicated planes): chaos on the
# plane REPLICAS themselves rather than on workers. ``plane_kill``
# hard-stops one plane server mid-traffic (every ``plane_kill`` emits its
# own paired ``plane_restart``, mirroring worker kill/restart),
# ``plane_partition`` makes one plane unreachable (requests to it fail at
# the transport) while the process stays up, ``plane_slow`` taxes every
# request that plane answers with injected latency. The ``worker`` field of
# a plane event indexes the PLANE cohort, not the worker fleet. Kept OUT of
# FLEET_EVENT_KINDS so historical seeds keep regenerating their exact
# schedules.
PLANE_EVENT_KINDS = ("plane_kill", "plane_partition", "plane_slow")

# gray-failure kinds (round 18 — slow-worker quarantine): the worker is
# ALIVE and heartbeating the whole time, just wrong. ``degrade`` is a
# persistent slowdown (every direct request/stream of the replica pays
# ``delay_s`` for the WHOLE window — the 10x-slow worker that passes
# health checks), ``jitter`` is the probabilistic version (each event
# pays ``delay_s`` at ``prob`` — a noisy NIC / contended host), and
# ``flaky`` answers direct requests with a 5xx at ``prob`` while the
# process stays up. Kept OUT of FLEET_EVENT_KINDS so historical seeds
# keep regenerating their exact schedules.
GRAY_EVENT_KINDS = ("degrade", "jitter", "flaky")

# durable-tier kinds (round 19 — IO-fault immunity): storms on the bytes
# we PERSIST rather than the processes/links that move them. ``disk_full``
# fails every durable write fleet-wide (store mutations, spill puts,
# checkpoint saves, file writes) while reads keep serving; ``io_error``
# fails spill-tier/checkpoint IO probabilistically in BOTH directions;
# ``io_slow`` taxes every spill/checkpoint op with injected latency (the
# browning-out device the per-tier breaker exists to fence); ``corrupt_
# read`` flips bytes in spill entries read back (the entry CRC must catch
# it and quarantine, never poison a request); ``torn_write`` persists only
# a prefix of written spill entries (detected at read time the same way).
# Kept OUT of FLEET_EVENT_KINDS so historical seeds keep regenerating
# their exact schedules.
IO_CHAOS_KINDS = ("disk_full", "io_error", "io_slow", "corrupt_read",
                  "torn_write")
ALL_FLEET_EVENT_KINDS = (
    FLEET_EVENT_KINDS + HANDOFF_EVENT_KINDS + PLANE_EVENT_KINDS
    + GRAY_EVENT_KINDS + IO_CHAOS_KINDS
)

# the canonical suite/CLI geometry: ``--replay`` must reconstruct the EXACT
# schedule a failing suite seed ran, so both sides share these defaults
FLEET_CHAOS_WORKERS = 2
FLEET_CHAOS_DURATION_S = 6.0

# PD-split chaos suite geometry (tests/test_pd_chaos.py): 3 workers
# (1 prefill + 2 decode), kills + partitions + every handoff kind —
# ``--replay SEED --pd`` reconstructs these schedules
PD_CHAOS_WORKERS = 3
PD_CHAOS_KINDS = ("kill", "partition") + HANDOFF_EVENT_KINDS

# plane chaos suite geometry (tests/test_plane_chaos.py): 2 plane replicas
# over one shared job store, 2 workers, plane-level events mixed with
# worker kills so plane death lands mid-claim / mid-heartbeat / mid-stream
# — ``--replay SEED --planes`` reconstructs these schedules
PLANE_CHAOS_PLANES = 2
PLANE_CHAOS_WORKERS = 2
PLANE_CHAOS_KINDS = PLANE_EVENT_KINDS + ("kill",)

# gray-chaos suite geometry (tests/test_gray_chaos.py): 3 workers so the
# quarantine of one degraded replica still leaves a 2-replica serving
# fleet, gray kinds composed with clean kills — ``--replay SEED --gray``
# reconstructs these schedules
GRAY_CHAOS_WORKERS = 3
GRAY_CHAOS_KINDS = GRAY_EVENT_KINDS + ("kill",)

# io-chaos suite geometry (tests/test_io_chaos.py): 2 workers with spill
# tiers + per-token checkpoints enabled, every io kind composed with clean
# kills so a crash can land right after a window of failed/torn/corrupt
# durable writes — ``--replay SEED --io`` reconstructs these schedules
IO_CHAOS_WORKERS = 2
IO_CHAOS_SUITE_KINDS = IO_CHAOS_KINDS + ("kill",)


@dataclass(frozen=True)
class FleetEvent:
    """One scheduled fleet-level event.

    =========  ==========================================================
    kind       effect in :class:`~..testing.harness.LiveFleet`
    =========  ==========================================================
    kill       hard-stop a replica's servers/threads mid-traffic (no
               drain, no offline call) — a crashed process
    restart    rebuild the replica cold and re-register it on the SAME
               machine fingerprint (restart-with-reregistration)
    blackout   heartbeats stop for ``duration_s`` while the replica keeps
               serving — the one-directional partition that gets a LIVE
               worker swept offline
    partition  bidirectional: heartbeats stop AND the replica's direct
               endpoint refuses traffic for ``duration_s``
    pressure   fleet-wide KV pressure storm: ``kv.block.alloc`` fires
               pool-exhausted for ``duration_s`` at ``prob``
    slow       latency injection: every direct request/stream event of
               the replica sleeps ``delay_s`` for ``duration_s``
    handoff_partition  the worker's outbound KV handoff pushes hard-drop
               for ``duration_s`` (``worker.pd.push`` flap) — the
               prefill→decode stream is cut while both replicas live
    handoff_corrupt    received handoff messages truncate in transit at
               ``prob`` for ``duration_s`` (``kv.receiver.message``,
               fleet-wide) — pieces poison their session, commits abort
    handoff_delay      every outbound handoff piece of the worker pays
               ``delay_s`` for ``duration_s`` — send timeouts/retries
    plane_kill         hard-stop plane replica ``worker`` (index into the
               PLANE cohort) mid-traffic — a crashed control plane
    plane_restart      rebuild the killed plane over the SAME shared job
               store and rejoin the cluster
    plane_partition    every request to plane ``worker`` fails at the
               transport for ``duration_s`` while the process stays up
    plane_slow         every request plane ``worker`` answers pays
               ``delay_s`` for ``duration_s``
    degrade    persistent gray slowdown: every direct request/stream
               event of the replica pays ``delay_s`` for ``duration_s``
               (stretched to ≥ half the run) while heartbeats stay
               healthy — the alive-but-10x-slow worker
    jitter     probabilistic gray slowdown: each direct request/stream
               event of the replica pays ``delay_s`` at ``prob`` for
               ``duration_s``
    flaky      probabilistic 5xx: the replica's direct requests answer
               HTTP 500 at ``prob`` for ``duration_s`` while the
               process (and its heartbeats) stay up
    disk_full  fleet-wide: every durable WRITE fails for ``duration_s``
               (store INSERT/UPDATE, spill puts, checkpoint saves, file
               writes raise like a full disk) while reads keep serving
    io_error   fleet-wide: spill-tier and checkpoint IO fails at
               ``prob`` in both directions for ``duration_s``
    io_slow    fleet-wide: every spill/checkpoint op pays ``delay_s``
               for ``duration_s`` — the browning-out device the
               per-tier breaker fences off the serving path
    corrupt_read  spill entries read back bit-flipped at ``prob`` for
               ``duration_s`` — the entry CRC quarantines, serving
               falls back to the next tier or recompute
    torn_write    spill writes persist only a prefix at ``prob`` for
               ``duration_s`` — detected by the CRC at read time
    =========  ==========================================================
    """

    at_s: float            # offset from chaos start
    kind: str
    worker: int            # fleet member index; -1 = fleet-wide.
    #                        plane_* events index the plane cohort instead
    duration_s: float = 0.0
    prob: float = 1.0      # pressure: per-allocation firing probability
    delay_s: float = 0.0   # slow: injected per-hit latency


class FleetFaultPlan:
    """Seeded, deterministic schedule of fleet-level events.

    Pure function of ``(seed, n_workers, duration_s, kinds)``: the same
    arguments always produce the identical event list — the suite asserts
    this, and ``python -m distributed_gpu_inference_tpu.testing.faults
    --replay <seed>`` prints the exact schedule a failing seed ran.

    Generated disruption windows are SEQUENTIAL (next window starts after
    the previous ends), so with ≥ 2 replicas at least one replica can take
    work at every instant — the suite's liveness assertions rely on it.
    ``trace`` records what the executor actually ran, wall-clock-stamped.
    """

    def __init__(self, seed: int,
                 n_workers: int = FLEET_CHAOS_WORKERS,
                 duration_s: float = FLEET_CHAOS_DURATION_S,
                 kinds: Sequence[str] = FLEET_EVENT_KINDS,
                 max_disruptions: int = 2,
                 n_planes: int = PLANE_CHAOS_PLANES) -> None:
        for k in kinds:
            if k not in ALL_FLEET_EVENT_KINDS:
                raise ValueError(
                    f"unknown fleet event kind {k!r} "
                    f"(one of {ALL_FLEET_EVENT_KINDS})"
                )
        self.seed = seed
        self.n_workers = n_workers
        self.duration_s = duration_s
        self.kinds = tuple(kinds)
        self.max_disruptions = max_disruptions
        # plane cohort size — only consulted when a plane_* kind is drawn,
        # so schedules without plane kinds are bit-identical to round 9
        self.n_planes = n_planes
        self.events: List[FleetEvent] = self._generate()
        self.trace: List[Tuple[float, str, int]] = []

    def _generate(self) -> List[FleetEvent]:
        rng = random.Random(0xF1EE7 * (self.seed + 1) + self.n_workers)
        n = 1
        if self.max_disruptions > 1 and rng.random() < 0.5:
            n = 2
        events: List[FleetEvent] = []
        cursor = self.duration_s * (0.10 + 0.15 * rng.random())
        for _ in range(n):
            kind = self.kinds[rng.randrange(len(self.kinds))]
            # plane events target the plane cohort; everything else the
            # worker fleet. One randrange draw either way, so kind sets
            # WITHOUT plane kinds consume the rng identically to round 9.
            if kind in PLANE_EVENT_KINDS:
                worker = rng.randrange(max(1, self.n_planes))
            else:
                worker = rng.randrange(self.n_workers)
            dur = self.duration_s * (0.20 + 0.25 * rng.random())
            if kind == "kill":
                events.append(FleetEvent(round(cursor, 3), "kill", worker))
                events.append(
                    FleetEvent(round(cursor + dur, 3), "restart", worker)
                )
            elif kind == "pressure":
                events.append(FleetEvent(
                    round(cursor, 3), "pressure", -1,
                    duration_s=round(dur, 3),
                    prob=0.25 + 0.5 * rng.random(),
                ))
            elif kind == "slow":
                events.append(FleetEvent(
                    round(cursor, 3), "slow", worker,
                    duration_s=round(dur, 3),
                    delay_s=round(0.02 + 0.08 * rng.random(), 3),
                ))
            elif kind == "handoff_corrupt":
                events.append(FleetEvent(
                    round(cursor, 3), "handoff_corrupt", -1,
                    duration_s=round(dur, 3),
                    prob=0.25 + 0.5 * rng.random(),
                ))
            elif kind == "handoff_delay":
                events.append(FleetEvent(
                    round(cursor, 3), "handoff_delay", worker,
                    duration_s=round(dur, 3),
                    delay_s=round(0.02 + 0.08 * rng.random(), 3),
                ))
            elif kind == "plane_kill":
                # like worker kill: every plane_kill pairs its own
                # plane_restart, so no schedule strands a dead plane
                events.append(
                    FleetEvent(round(cursor, 3), "plane_kill", worker)
                )
                events.append(
                    FleetEvent(round(cursor + dur, 3), "plane_restart",
                               worker)
                )
            elif kind == "plane_slow":
                events.append(FleetEvent(
                    round(cursor, 3), "plane_slow", worker,
                    duration_s=round(dur, 3),
                    delay_s=round(0.02 + 0.08 * rng.random(), 3),
                ))
            elif kind == "degrade":
                # persistent slowdown: heavier than ``slow`` (the worker
                # is 5-15x a healthy replica's latency, not 1.2x) and the
                # window stretches to most of the run — the gray failure
                # quarantine exists to catch. ``dur`` is stretched so the
                # sequential-window cursor below still never overlaps.
                dur = max(dur, self.duration_s * 0.5)
                events.append(FleetEvent(
                    round(cursor, 3), "degrade", worker,
                    duration_s=round(dur, 3),
                    delay_s=round(0.10 + 0.20 * rng.random(), 3),
                ))
            elif kind == "jitter":
                events.append(FleetEvent(
                    round(cursor, 3), "jitter", worker,
                    duration_s=round(dur, 3),
                    prob=0.25 + 0.5 * rng.random(),
                    delay_s=round(0.05 + 0.10 * rng.random(), 3),
                ))
            elif kind == "flaky":
                events.append(FleetEvent(
                    round(cursor, 3), "flaky", worker,
                    duration_s=round(dur, 3),
                    prob=0.25 + 0.5 * rng.random(),
                ))
            elif kind == "disk_full":
                # a full disk fails EVERY write until space frees — no
                # probability draw, so historical rng sequences without
                # io kinds are untouched by construction
                events.append(FleetEvent(
                    round(cursor, 3), "disk_full", -1,
                    duration_s=round(dur, 3),
                ))
            elif kind == "io_error":
                events.append(FleetEvent(
                    round(cursor, 3), "io_error", -1,
                    duration_s=round(dur, 3),
                    prob=0.5 + 0.5 * rng.random(),
                ))
            elif kind == "io_slow":
                events.append(FleetEvent(
                    round(cursor, 3), "io_slow", -1,
                    duration_s=round(dur, 3),
                    delay_s=round(0.02 + 0.08 * rng.random(), 3),
                ))
            elif kind in ("corrupt_read", "torn_write"):
                events.append(FleetEvent(
                    round(cursor, 3), kind, -1,
                    duration_s=round(dur, 3),
                    prob=0.25 + 0.5 * rng.random(),
                ))
            else:  # blackout / partition / handoff_partition / plane_partition
                events.append(FleetEvent(
                    round(cursor, 3), kind, worker,
                    duration_s=round(dur, 3),
                ))
            # sequential windows + breathing room: disruptions never
            # overlap, so a 2-replica fleet always has a live replica
            cursor += dur + self.duration_s * 0.10 * (1.0 + rng.random())
        return sorted(events, key=lambda e: e.at_s)

    def record(self, offset_s: float, kind: str, worker: int) -> None:
        """Executor hook: stamp one executed event into the trace."""
        self.trace.append((round(offset_s, 3), kind, worker))

    def describe(self) -> List[str]:
        out = [
            f"FleetFaultPlan(seed={self.seed}, workers={self.n_workers}, "
            f"duration={self.duration_s}s, kinds={','.join(self.kinds)})"
        ]
        for e in self.events:
            if e.worker < 0:
                tgt = "fleet"
            elif e.kind.startswith("plane_"):
                tgt = f"plane[{e.worker}]"
            else:
                tgt = f"worker[{e.worker}]"
            extra = ""
            if e.duration_s:
                extra += f" for {e.duration_s}s"
            if e.kind in ("pressure", "handoff_corrupt", "jitter",
                          "flaky", "io_error", "corrupt_read",
                          "torn_write"):
                extra += f" prob={e.prob:.2f}"
            if e.kind in ("slow", "handoff_delay", "plane_slow",
                          "degrade", "jitter", "io_slow"):
                extra += f" delay={e.delay_s}s"
            out.append(f"  t+{e.at_s:6.2f}s  {e.kind:<9} {tgt}{extra}")
        return out


def mutate_bytes(site: str, data: bytes, **ctx: Any) -> bytes:
    """Byte-message seam (KV handoff receiver): truncate or lose a message
    in transit. Drops raise :class:`FaultInjected`, which the transport
    layer reports to the sender like any receive failure."""
    plan = _ACTIVE
    if plan is None:
        return data
    rule = plan.fire(site, size=len(data), **ctx)
    if rule is None:
        return data
    if rule.kind == "truncate":
        return data[: rule.cut]
    if rule.kind in ("drop", "flap"):
        raise FaultInjected(f"fault injected: {rule.kind} at {site}")
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return data
    raise ValueError(f"rule kind {rule.kind!r} unsupported at byte seam")


def io_fault(site: str, **ctx: Any) -> None:
    """Durable-IO seam (host spill tier, store checkpoints, file writes):
    injected backend failures surface as :class:`OSError` — exactly what a
    full disk, a dying device, or a flaky mount raises — so callers
    exercise their degraded paths (tier isolation, breakers, atomic-write
    cleanup). ``delay`` models a browning-out device."""
    plan = _ACTIVE
    if plan is None:
        return
    rule = plan.fire(site, **ctx)
    if rule is None:
        return
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return
    if rule.kind in ("drop", "flap", "error"):
        raise OSError(f"fault injected: {rule.kind} at {site}")
    raise ValueError(f"rule kind {rule.kind!r} unsupported at io seam")


def io_bytes(site: str, data: Optional[bytes],
             **ctx: Any) -> Optional[bytes]:
    """Byte-carrying durable-IO seam (remote spill tier): ``truncate``
    models a TORN WRITE (only a prefix of the payload lands) or a
    short read, ``corrupt`` flips one byte mid-payload (bit rot the
    entry checksum must catch), ``error``/``drop``/``flap`` raise
    :class:`OSError`. One ``fire`` per hit whatever is armed, so
    first-match stays well-defined. ``data`` may be None (a read that
    missed) — mutating kinds pass a miss through untouched."""
    plan = _ACTIVE
    if plan is None:
        return data
    rule = plan.fire(site, size=len(data) if data is not None else 0, **ctx)
    if rule is None:
        return data
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return data
    if rule.kind in ("drop", "flap", "error"):
        raise OSError(f"fault injected: {rule.kind} at {site}")
    if data is None:
        return None
    if rule.kind == "truncate":
        return data[: rule.cut]
    if rule.kind == "corrupt":
        if not data:
            return data
        i = len(data) // 2
        return data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
    raise ValueError(f"rule kind {rule.kind!r} unsupported at io seam")


# ---------------------------------------------------------------------------
# seeded-replay CLI: reconstruct a failing fleet-chaos seed's exact schedule
# ---------------------------------------------------------------------------


def _replay_main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m distributed_gpu_inference_tpu.testing.faults --replay N``

    Prints the exact fleet FaultPlan a chaos-suite seed runs (same
    generator, same defaults as ``tests/test_fleet_chaos.py``), so a chaos
    flake reproduces one-shot: read the CI failure's seed, replay it, and
    the printed schedule IS what the failing run injected."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m distributed_gpu_inference_tpu.testing.faults",
        description="Replay a seeded fleet FaultPlan schedule.",
    )
    ap.add_argument("--replay", type=int, required=True, metavar="SEED",
                    help="the failing suite seed to reconstruct")
    ap.add_argument("--workers", type=int, default=None,
                    help="fleet size the suite ran (default: suite default; "
                    "the PD suite's with --pd)")
    ap.add_argument("--duration", type=float,
                    default=FLEET_CHAOS_DURATION_S,
                    help="chaos window seconds (default: suite default)")
    ap.add_argument("--kinds", default=None,
                    help="comma-separated event kinds the suite allowed "
                    "(default: the fleet suite's kinds, or the PD suite's "
                    "with --pd)")
    ap.add_argument("--pd", action="store_true",
                    help="reconstruct a tests/test_pd_chaos.py seed: the "
                    "PD-split suite's kinds (kill/partition + handoff_"
                    "partition/corrupt/delay) and its 3-worker fleet "
                    "geometry")
    ap.add_argument("--planes", action="store_true",
                    help="reconstruct a tests/test_plane_chaos.py seed: "
                    "the plane suite's kinds (plane_kill/plane_partition/"
                    "plane_slow + worker kill) and its 2-plane / 2-worker "
                    "geometry")
    ap.add_argument("--gray", action="store_true",
                    help="reconstruct a tests/test_gray_chaos.py seed: "
                    "the gray-failure suite's kinds (degrade/jitter/flaky "
                    "+ worker kill) and its 3-worker fleet geometry")
    ap.add_argument("--io", action="store_true",
                    help="reconstruct a tests/test_io_chaos.py seed: the "
                    "durable-tier suite's kinds (disk_full/io_error/"
                    "io_slow/corrupt_read/torn_write + worker kill) and "
                    "its 2-worker fleet geometry")
    args = ap.parse_args(argv)
    if sum(1 for f in (args.pd, args.planes, args.gray, args.io) if f) > 1:
        ap.error("--pd, --planes, --gray and --io are mutually exclusive")
    kinds = args.kinds
    if kinds is None:
        if args.pd:
            kinds = ",".join(PD_CHAOS_KINDS)
        elif args.planes:
            kinds = ",".join(PLANE_CHAOS_KINDS)
        elif args.gray:
            kinds = ",".join(GRAY_CHAOS_KINDS)
        elif args.io:
            kinds = ",".join(IO_CHAOS_SUITE_KINDS)
        else:
            kinds = ",".join(FLEET_EVENT_KINDS)
    workers = args.workers
    if workers is None:
        if args.pd:
            workers = PD_CHAOS_WORKERS
        elif args.planes:
            workers = PLANE_CHAOS_WORKERS
        elif args.gray:
            workers = GRAY_CHAOS_WORKERS
        elif args.io:
            workers = IO_CHAOS_WORKERS
        else:
            workers = FLEET_CHAOS_WORKERS
    plan = FleetFaultPlan(
        args.replay, n_workers=workers, duration_s=args.duration,
        kinds=tuple(k for k in kinds.split(",") if k),
        n_planes=PLANE_CHAOS_PLANES,
    )
    for line in plan.describe():
        print(line)
    return 0


if __name__ == "__main__":  # pragma: no cover - manual entry point
    raise SystemExit(_replay_main())
