"""Deterministic test instrumentation for the mesh.

``testing.faults`` is the seeded fault-injection (chaos) subsystem: a
:class:`~distributed_gpu_inference_tpu.testing.faults.FaultPlan` installs
per-site rules (drop / delay / error / truncate / duplicate / flap) behind
the injection seams threaded through the production clients, store, comm
planes, and KV-handoff receiver. With no plan installed every seam is a
no-op passthrough — production paths never construct plan state.

``testing.fakes`` holds lightweight engine stand-ins for receiver-side
protocol tests; ``testing.harness`` runs a real control plane on a loopback
socket so synchronous worker/SDK clients can be driven end-to-end on CPU.

See ``docs/failure-semantics.md`` for the delivery guarantees these tools
exist to verify and for how to write a chaos scenario.
"""

from .faults import (  # noqa: F401
    FaultPlan,
    FaultRule,
    active,
    current,
    install,
    uninstall,
)
