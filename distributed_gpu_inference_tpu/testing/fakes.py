"""Lightweight engine stand-ins for receiver-side protocol tests.

:class:`FakeKVEngine` implements exactly the surface
:class:`~distributed_gpu_inference_tpu.runtime.kv_handoff.HandoffReceiver`
and ``_bind_migrated`` touch — block accounting, pending upload staging,
slot binding — with real conservation semantics (blocks leave a free list
on allocate and return on free) but no device, no model, no jit. Chaos
scenarios replay streamed-handoff failures across dozens of seeds in
milliseconds while still driving the production receiver code.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple


@dataclass
class FakeEngineConfig:
    block_size: int = 4
    max_blocks_per_seq: int = 16
    max_seq_len: int = 64


@dataclass
class _FakeModelCfg:
    name: str = "fake-model"
    sliding_window: Optional[int] = None


class _FakePending:
    def __init__(self) -> None:
        self.uploads: List[Tuple[int, Any]] = []
        self.scale_uploads: List[Tuple[int, Any]] = []


class FakeBlockManager:
    """Free-list block accounting with the BlockManager call surface the
    handoff receiver uses. No prefix cache (``cached_tokens`` is 0)."""

    def __init__(self, num_blocks: int, block_size: int) -> None:
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.free_blocks: List[int] = list(range(num_blocks))
        self.seq_blocks: Dict[str, List[int]] = {}
        self.seq_tokens: Dict[str, List[int]] = {}
        self.seq_window_front: Dict[str, int] = {}
        self.pending = _FakePending()
        # block id → last page applied (what a commit would decode from)
        self.applied: Dict[int, Any] = {}

    def allocate_sequence(self, seq_id: str,
                          token_ids: Sequence[int]) -> Tuple[List[int], int]:
        if seq_id in self.seq_blocks:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        n = max(1, -(-len(token_ids) // self.block_size))
        if n > len(self.free_blocks):
            raise RuntimeError("fake pool out of blocks")
        blocks = [self.free_blocks.pop(0) for _ in range(n)]
        self.seq_blocks[seq_id] = blocks
        self.seq_tokens[seq_id] = [int(t) for t in token_ids]
        return list(blocks), 0

    def append_token(self, seq_id: str, token_id: int) -> None:
        toks = self.seq_tokens[seq_id]
        toks.append(int(token_id))
        if -(-len(toks) // self.block_size) > len(self.seq_blocks[seq_id]):
            self.seq_blocks[seq_id].append(self.free_blocks.pop(0))

    def free_sequence(self, seq_id: str, cache: bool = True) -> None:
        self.free_blocks.extend(self.seq_blocks.pop(seq_id))
        self.seq_tokens.pop(seq_id, None)
        self.seq_window_front.pop(seq_id, None)

    def seed_window_front(self, seq_id: str, front: int) -> None:
        self.seq_window_front[seq_id] = front


class FakeKVEngine:
    """Engine facade for :class:`HandoffReceiver` tests."""

    def __init__(self, cfg: Optional[FakeEngineConfig] = None,
                 num_blocks: int = 64, num_slots: int = 4,
                 model_name: str = "fake-model") -> None:
        self.cfg = cfg or FakeEngineConfig()
        self.model_cfg = _FakeModelCfg(name=model_name)
        self.kv: Dict[str, Any] = {"k": None, "v": None}
        self.manager = FakeBlockManager(num_blocks, self.cfg.block_size)
        self.slots: List[Any] = [None] * num_slots
        self._kv_lens = [0] * num_slots
        self._last_tokens = [0] * num_slots
        self._slot_keys: List[Any] = [None] * num_slots
        self.binds = 0

    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def _bind_slot(self, slot: int, s: Any, kv_len: int) -> None:
        self.slots[slot] = s
        self._kv_lens[slot] = kv_len
        self.binds += 1

    def _apply_pending(self) -> None:
        # mirrors the real engine: staged uploads land immediately and the
        # pending lists drain; ``applied`` records what reached "device"
        for bid, page in self.manager.pending.uploads:
            self.manager.applied[bid] = page
        self.manager.pending.uploads = []
        self.manager.pending.scale_uploads = []

    # -- invariants ----------------------------------------------------------

    def leaked_blocks(self) -> int:
        """Blocks neither free nor owned by a live sequence."""
        owned = sum(len(b) for b in self.manager.seq_blocks.values())
        return self.manager.num_blocks - len(self.manager.free_blocks) - owned


# ---------------------------------------------------------------------------
# synthetic streamed-handoff message sequences
# ---------------------------------------------------------------------------


def stream_kind(msg: bytes) -> str:
    """Human name of a streamed-handoff message's kind byte (for fault-rule
    ``match`` context in ``FaultPlan.filter_stream``)."""
    if len(msg) < 6 or msg[:4] != b"TPUS":
        return "blob"
    return {0: "begin", 1: "piece", 2: "commit", 3: "abort"}.get(
        msg[5], "unknown"
    )


def make_stream_messages(
    key: str,
    prompt: Sequence[int],
    block_size: int = 4,
    piece_blocks: int = 2,
    max_new_tokens: int = 4,
    pending_token: int = 7,
) -> List[bytes]:
    """Build a full begin → pieces → commit sequence a
    :class:`HandoffReceiver` over a :class:`FakeKVEngine` accepts: the wire
    framing is the real one (``runtime.kv_handoff._pack_stream``), only the
    page payloads are tiny synthetic tensors. Chaos scenarios mangle this
    sequence (loss / reorder / duplication / truncation) and assert the
    receiver's cleanup invariants."""
    import numpy as np

    from ..runtime.kv_handoff import (  # deferred: pulls jax via engine deps
        _KIND_BEGIN,
        _KIND_COMMIT,
        _KIND_PIECE,
        _pack_stream,
    )
    from ..utils.serialization import TensorSerializer

    prompt = [int(t) for t in prompt]
    token_ids = prompt + [int(pending_token)]
    n_blocks = -(-len(token_ids) // block_size)
    ser = TensorSerializer(compress=False)
    msgs = [_pack_stream(_KIND_BEGIN, {
        "key": key,
        "model_name": "fake-model",
        "block_size": block_size,
        "int8_kv": False,
        "request": {
            "request_id": f"r-{key}",
            "model": None,
            "prompt_token_ids": prompt,
            "sampling": {"max_new_tokens": max_new_tokens,
                         "temperature": 0.0, "top_k": 0, "top_p": 1.0,
                         "stop_token_ids": [], "seed": None},
            "priority": 0,
            "session_id": key,
        },
    })]
    for lo in range(0, n_blocks, piece_blocks):
        hi = min(n_blocks, lo + piece_blocks)
        # [n, L=1, 2, H=1, Bk, D=2], value = block index (checkable later)
        pages = np.stack([
            np.full((1, 2, 1, block_size, 2), float(i), np.float32)
            for i in range(lo, hi)
        ])
        msgs.append(_pack_stream(
            _KIND_PIECE, {"key": key, "block_lo": lo}, ser.serialize(pages)
        ))
    msgs.append(_pack_stream(_KIND_COMMIT, {
        "key": key,
        "token_ids": token_ids,
        "kv_len": len(prompt),
        "pending_token": int(pending_token),
        "prompt_len": len(prompt),
        "generated": [],
        "start_time": 0.0,
        "first_token_time": 0.001,
        "slot_key": [1, 2, 3, 4],
        "finish_reason": None,
    }))
    return msgs
