"""Minimal protobuf wire-format codec for the data-plane IDL.

``grpc_tools``/``protoc``-generated stubs are not available in the image;
rather than leave ``proto/inference.proto`` unwired (the reference's exact
gap, ``worker/distributed/grpc_server.py:427-429``), the handful of messages
it declares are encoded/decoded here against the proto3 wire format
directly. The format is small: a message is a sequence of
``(field_number << 3 | wire_type)`` tags; this plane needs wire types 0
(varint: int32/int64/bool) and 2 (length-delimited: string/bytes/message).

Messages are declared as field specs and round-trip as plain dicts —
``grpc_plane.py`` plugs these into grpc's generic handlers as the
request/response serializers, so the bytes on the wire ARE conformant
protobuf for the IDL, interoperable with any stub-generated client.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

# field spec: {field_number: (name, kind)} where kind ∈
# {"string", "bytes", "varint", "bool", ("msg", spec)}


def _encode_varint(value: int) -> bytes:
    if value < 0:
        # proto3 int32/int64 negatives ride as 10-byte two's complement
        value += 1 << 64
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _decode_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        byte = buf[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")
    if result >= 1 << 63:      # re-interpret as signed 64-bit
        result -= 1 << 64
    return result, pos


def encode(spec: Dict[int, Tuple[str, Any]], msg: Dict[str, Any]) -> bytes:
    """Dict → proto3 bytes. Default-valued fields are omitted (proto3)."""
    by_name = {name: (num, kind) for num, (name, kind) in spec.items()}
    out = bytearray()
    for name, value in msg.items():
        if name not in by_name:
            raise KeyError(f"unknown field {name!r}")
        num, kind = by_name[name]
        if value is None:
            continue
        if kind == "string":
            data = value.encode("utf-8")
            if not data:
                continue
            out += _encode_varint(num << 3 | 2) + _encode_varint(len(data))
            out += data
        elif kind == "bytes":
            if not value:
                continue
            out += _encode_varint(num << 3 | 2) + _encode_varint(len(value))
            out += bytes(value)
        elif kind == "varint":
            if value == 0:
                continue
            out += _encode_varint(num << 3 | 0) + _encode_varint(int(value))
        elif kind == "bool":
            if not value:
                continue
            out += _encode_varint(num << 3 | 0) + _encode_varint(1)
        elif isinstance(kind, tuple) and kind[0] == "msg":
            data = encode(kind[1], value)
            out += _encode_varint(num << 3 | 2) + _encode_varint(len(data))
            out += data
        else:
            raise TypeError(f"unknown kind {kind!r}")
    return bytes(out)


def decode(spec: Dict[int, Tuple[str, Any]], data: bytes) -> Dict[str, Any]:
    """proto3 bytes → dict with every spec'd field present (defaults
    filled), unknown fields skipped — standard proto forward compat."""
    buf = memoryview(data)
    out: Dict[str, Any] = {}
    for num, (name, kind) in spec.items():
        if kind == "string":
            out[name] = ""
        elif kind == "bytes":
            out[name] = b""
        elif kind == "varint":
            out[name] = 0
        elif kind == "bool":
            out[name] = False
        else:
            out[name] = None
    pos = 0
    while pos < len(buf):
        tag, pos = _decode_varint(buf, pos)
        num, wtype = tag >> 3, tag & 0x7
        field = spec.get(num)
        if wtype == 0:
            value, pos = _decode_varint(buf, pos)
            if field is not None:
                name, kind = field
                out[name] = bool(value) if kind == "bool" else value
        elif wtype == 2:
            ln, pos = _decode_varint(buf, pos)
            chunk = bytes(buf[pos:pos + ln])
            if len(chunk) != ln:
                raise ValueError("truncated length-delimited field")
            pos += ln
            if field is not None:
                name, kind = field
                if kind == "string":
                    out[name] = chunk.decode("utf-8")
                elif kind == "bytes":
                    out[name] = chunk
                elif isinstance(kind, tuple) and kind[0] == "msg":
                    out[name] = decode(kind[1], chunk)
                else:
                    raise ValueError(
                        f"field {name} kind {kind} can't be length-delimited"
                    )
        elif wtype == 5:       # fixed32 (unused by this IDL) — skip
            if pos + 4 > len(buf):
                raise ValueError("truncated fixed32 field")
            pos += 4
        elif wtype == 1:       # fixed64 — skip
            if pos + 8 > len(buf):
                raise ValueError("truncated fixed64 field")
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wtype}")
    return out


# --------------------------------------------------------------------------
# Message specs mirroring proto/inference.proto (field numbers must match)
# --------------------------------------------------------------------------

TENSOR = {1: ("frame", "bytes")}

CREATE_SESSION_REQUEST = {1: ("session_id", "string")}
CREATE_SESSION_RESPONSE = {1: ("session_id", "string"),
                           2: ("existing", "bool")}

FORWARD_REQUEST = {
    1: ("session_id", "string"),
    2: ("kv_len_after", "varint"),
    3: ("x", ("msg", TENSOR)),
    4: ("positions", ("msg", TENSOR)),
}
FORWARD_RESPONSE = {
    1: ("session_id", "string"),
    2: ("hidden", ("msg", TENSOR)),
    3: ("logits", ("msg", TENSOR)),
}

TRANSFER_KV_REQUEST = {1: ("handoff", "bytes")}
TRANSFER_KV_RESPONSE = {1: ("slot", "varint"), 2: ("bytes_received", "varint")}

CLOSE_SESSION_REQUEST = {1: ("session_id", "string")}
CLOSE_SESSION_RESPONSE = {1: ("status", "string")}

HEALTH_REQUEST: Dict[int, Tuple[str, Any]] = {}
HEALTH_RESPONSE = {
    1: ("status", "string"),
    2: ("layer_start", "varint"),
    3: ("layer_end", "varint"),
    4: ("is_first", "bool"),
    5: ("is_last", "bool"),
    6: ("active_sessions", "varint"),
    7: ("free_blocks", "varint"),
}


def serializer(spec):
    return lambda msg: encode(spec, msg)


def deserializer(spec):
    return lambda data: decode(spec, data)
