"""HTTP data plane serving a pipeline stage worker.

Working transport of the cross-host pipeline, parity with the reference's
``HTTPInferenceServer`` (``worker/distributed/grpc_server.py:450-562``,
routes ``/inference/forward``, ``/inference/close``, ``/health``) plus the
proto surface the reference never wired (``proto/inference.proto:11-27``):
CreateSession / CloseSession / Forward / TransferKVCache / HealthCheck all
respond for real here.

Bodies are TPUM binary frames (``comm.wire``), not base64 JSON. KV transfer
accepts a serialized :mod:`runtime.kv_handoff` payload so a PD decode pool
can receive pages over the same socket.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable, Dict, Optional

from aiohttp import web

from .stage_worker import PipelineStageWorker, StageOutOfBlocksError
from .wire import pack_message, unpack_message


class DataPlaneServer:
    """aiohttp front for one stage worker (or a PD KV-receiving engine)."""

    def __init__(self, stage: PipelineStageWorker,
                 host: str = "0.0.0.0", port: int = 8472,
                 kv_receiver: Optional[Callable[[bytes], Dict[str, Any]]] = None,
                 kv_exporter: Optional[Callable[[bytes], bytes]] = None,
                 ) -> None:
        self.stage = stage
        self.host = host
        self.port = port
        self.kv_receiver = kv_receiver
        # cluster-wide KV migration: serve peers' prefix pulls (the
        # response body is a framed run of streamed-handoff messages)
        self.kv_exporter = kv_exporter
        self._runner: Optional[web.AppRunner] = None
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()

    # -- handlers ------------------------------------------------------------

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response(self.stage.health())

    async def _create_session(self, request: web.Request) -> web.Response:
        body = await request.json()
        sid = body.get("session_id")
        if not sid:
            return web.json_response({"detail": "session_id required"},
                                     status=400)
        return web.json_response(self.stage.create_session(sid))

    async def _close_session(self, request: web.Request) -> web.Response:
        body = await request.json()
        self.stage.close_session(body.get("session_id", ""))
        return web.json_response({"status": "closed"})

    async def _forward(self, request: web.Request) -> web.Response:
        raw = await request.read()
        try:
            meta, tensors = unpack_message(raw)
        except ValueError as exc:
            return web.json_response({"detail": str(exc)}, status=400)
        loop = asyncio.get_running_loop()
        try:
            out = await loop.run_in_executor(
                None,
                self.stage.forward,
                meta["session_id"],
                tensors["x"],
                tensors["positions"],
                int(meta["kv_len_after"]),
            )
        except KeyError as exc:
            return web.json_response({"detail": str(exc)}, status=404)
        except StageOutOfBlocksError as exc:
            return web.json_response({"detail": str(exc)}, status=507)
        except Exception as exc:  # noqa: BLE001
            return web.json_response({"detail": str(exc)}, status=500)
        return web.Response(
            body=pack_message({"session_id": meta["session_id"]}, out),
            content_type="application/octet-stream",
        )

    async def _transfer_kv(self, request: web.Request) -> web.Response:
        """PD KV handoff receiver (proto TransferKVCache:19, made real)."""
        if self.kv_receiver is None:
            return web.json_response(
                {"detail": "this endpoint is not a KV receiver"}, status=501
            )
        raw = await request.read()
        loop = asyncio.get_running_loop()
        try:
            result = await loop.run_in_executor(None, self.kv_receiver, raw)
        except Exception as exc:  # noqa: BLE001
            return web.json_response({"detail": str(exc)}, status=500)
        return web.json_response(result)

    async def _export_kv(self, request: web.Request) -> web.Response:
        """Cluster-KV prefix export: a cold peer pulls our cached prefix
        (``runtime/kv_handoff.py`` prefix-only frames). Mismatched model/
        dtype/geometry answers 400 — the puller treats any non-200 as a
        failed pull and recomputes."""
        if self.kv_exporter is None:
            return web.json_response(
                {"detail": "this endpoint is not a KV exporter"}, status=501
            )
        raw = await request.read()
        loop = asyncio.get_running_loop()
        try:
            body = await loop.run_in_executor(None, self.kv_exporter, raw)
        except ValueError as exc:
            return web.json_response({"detail": str(exc)}, status=400)
        except Exception as exc:  # noqa: BLE001
            return web.json_response({"detail": str(exc)}, status=500)
        return web.Response(
            body=body, content_type="application/octet-stream",
        )

    # -- lifecycle -----------------------------------------------------------

    def make_app(self) -> web.Application:
        app = web.Application(client_max_size=1 << 30)
        app.router.add_get("/health", self._health)
        app.router.add_post("/inference/create_session", self._create_session)
        app.router.add_post("/inference/close", self._close_session)
        app.router.add_post("/inference/forward", self._forward)
        app.router.add_post("/kv/transfer", self._transfer_kv)
        app.router.add_post("/kv/export", self._export_kv)
        return app

    def start(self) -> None:
        def _run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            runner = web.AppRunner(self.make_app())
            loop.run_until_complete(runner.setup())
            self._runner = runner
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            self._started.set()
            loop.run_forever()
            loop.run_until_complete(runner.cleanup())
            loop.close()

        self._thread = threading.Thread(
            target=_run, name="data-plane", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=15.0):
            raise RuntimeError("data plane server failed to start")

    @property
    def bound_port(self) -> int:
        assert self._runner is not None
        return self._runner.addresses[0][1]

    def stop(self) -> None:
        if self._loop is not None and not self._loop.is_closed():
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
