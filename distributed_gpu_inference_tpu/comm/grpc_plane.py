"""Real gRPC data plane for cross-host pipeline hops and PD KV transfer.

Closes the reference's unwired-gRPC gap for real (SURVEY gap #2 /
VERDICT r1 next-step #9): the reference declares a gRPC contract and serves
everything over ad-hoc HTTP JSON because stub registration was never
implemented (``worker/distributed/grpc_server.py:427-429``). Here the
service in ``proto/inference.proto`` is served over REAL gRPC (HTTP/2,
multiplexed, deadline-aware) without generated code: ``grpc``'s generic
method handlers take the hand-written proto3 codecs from :mod:`comm.pb`,
so the wire bytes are conformant protobuf any stub-generated client can
interoperate with.

On top of the unary surface the HTTP plane already serves
(``comm/data_plane.py``), this adds the **bidirectional-streaming Forward**
the reference declared and dropped (its ``StreamInference``,
ref ``proto/inference.proto:13``): one long-lived HTTP/2 stream per
pipeline session carries every decode-step hop — no per-token connection
or header overhead, in-order delivery guaranteed by the stream.

Transport choice stays layered (SURVEY §5.8): intra-slice hops are XLA
collectives (parallel/pipeline.py, no RPC at all); this plane is the
CROSS-HOST fallback, and deployments can pick HTTP (curl-debuggable) or
gRPC (streaming, multiplexed) — both carry the same TPUT tensor frames.
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Any, Callable, Dict, Optional

import numpy as np

from distributed_gpu_inference_tpu.comm import pb
from distributed_gpu_inference_tpu.comm.stage_worker import (
    PipelineStageWorker,
    StageOutOfBlocksError,
)
from distributed_gpu_inference_tpu.testing import faults as _faults
from distributed_gpu_inference_tpu.utils.serialization import TensorSerializer

_SERVICE = "dgi_tpu.dataplane.v1.PipelineDataPlane"


def _tensor_msg(arr: np.ndarray, ser: TensorSerializer) -> Dict[str, bytes]:
    return {"frame": ser.serialize(np.asarray(arr))}


def _tensor_arr(msg: Optional[Dict[str, Any]],
                ser: TensorSerializer) -> Optional[np.ndarray]:
    if not msg or not msg.get("frame"):
        return None
    return ser.deserialize(msg["frame"])


class GrpcDataPlane:
    """gRPC front for one stage worker (and optionally a PD KV receiver).

    Same behavior surface as :class:`comm.data_plane.DataPlaneServer`,
    different transport."""

    def __init__(
        self,
        stage: PipelineStageWorker,
        host: str = "0.0.0.0",
        port: int = 0,
        kv_receiver: Optional[Callable[[bytes], Dict[str, Any]]] = None,
        max_workers: int = 8,
    ) -> None:
        import grpc

        self.stage = stage
        self.kv_receiver = kv_receiver
        self._ser = TensorSerializer(compress=True)
        # the engine/stage is single-threaded — serialize compute calls
        self._stage_lock = threading.Lock()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self._server.add_generic_rpc_handlers((self._make_handler(grpc),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")

    # ------------------------------------------------------------ handlers

    def _make_handler(self, grpc):
        def unary(fn, req_spec, resp_spec):
            return grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=pb.deserializer(req_spec),
                response_serializer=pb.serializer(resp_spec),
            )

        method_handlers = {
            "CreateSession": unary(
                self._create_session,
                pb.CREATE_SESSION_REQUEST, pb.CREATE_SESSION_RESPONSE),
            "Forward": unary(
                self._forward, pb.FORWARD_REQUEST, pb.FORWARD_RESPONSE),
            "StreamForward": grpc.stream_stream_rpc_method_handler(
                self._stream_forward,
                request_deserializer=pb.deserializer(pb.FORWARD_REQUEST),
                response_serializer=pb.serializer(pb.FORWARD_RESPONSE),
            ),
            "TransferKVCache": unary(
                self._transfer_kv,
                pb.TRANSFER_KV_REQUEST, pb.TRANSFER_KV_RESPONSE),
            "CloseSession": unary(
                self._close_session,
                pb.CLOSE_SESSION_REQUEST, pb.CLOSE_SESSION_RESPONSE),
            "HealthCheck": unary(
                self._health, pb.HEALTH_REQUEST, pb.HEALTH_RESPONSE),
        }
        return grpc.method_handlers_generic_handler(_SERVICE, method_handlers)

    def _create_session(self, request, context):
        out = self.stage.create_session(request["session_id"])
        return {"session_id": out.get("session_id", request["session_id"]),
                "existing": bool(out.get("existing", False))}

    def _do_forward(self, request, context):
        import grpc

        x = _tensor_arr(request["x"], self._ser)
        positions = _tensor_arr(request["positions"], self._ser)
        if x is None or positions is None:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "x and positions tensors required")
        try:
            with self._stage_lock:
                out = self.stage.forward(
                    request["session_id"], x, positions,
                    int(request["kv_len_after"]),
                )
        except KeyError as exc:
            context.abort(grpc.StatusCode.NOT_FOUND, str(exc))
        except StageOutOfBlocksError as exc:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(exc))
        resp: Dict[str, Any] = {"session_id": request["session_id"]}
        if "hidden" in out:
            resp["hidden"] = _tensor_msg(out["hidden"], self._ser)
        if "logits" in out:
            resp["logits"] = _tensor_msg(out["logits"], self._ser)
        return resp

    def _forward(self, request, context):
        return self._do_forward(request, context)

    def _stream_forward(self, request_iterator, context):
        """Bidi stream: one response per request, in order — a pipeline
        session's whole decode runs on one HTTP/2 stream."""
        for request in request_iterator:
            yield self._do_forward(request, context)

    def _transfer_kv(self, request, context):
        import grpc

        if self.kv_receiver is None:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "this endpoint is not a KV receiver")
        try:
            result = self.kv_receiver(request["handoff"])
        except Exception as exc:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, str(exc))
        return {"slot": int(result.get("slot", -1)),
                "bytes_received": len(request["handoff"])}

    def _close_session(self, request, context):
        self.stage.close_session(request["session_id"])
        return {"status": "closed"}

    def _health(self, request, context):
        h = self.stage.health()
        return {
            "status": h.get("status", "ok"),
            "layer_start": int(h.get("layer_start", 0)),
            "layer_end": int(h.get("layer_end", 0)),
            "is_first": bool(h.get("is_first", False)),
            "is_last": bool(h.get("is_last", False)),
            "active_sessions": int(h.get("active_sessions", 0)),
            "free_blocks": int(h.get("free_blocks", 0)),
        }

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)


class GrpcStageClient:
    """Client for one remote stage over gRPC. Mirrors the call surface the
    HTTP pipeline session uses, plus a persistent streaming channel."""

    def __init__(self, target: str, timeout_s: float = 30.0) -> None:
        import grpc

        self._grpc = grpc
        self.timeout_s = timeout_s
        self._ser = TensorSerializer(compress=True)
        self.channel = grpc.insecure_channel(target)

        def u(method, req_spec, resp_spec):
            return self.channel.unary_unary(
                f"/{_SERVICE}/{method}",
                request_serializer=pb.serializer(req_spec),
                response_deserializer=pb.deserializer(resp_spec),
            )

        self._create = u("CreateSession", pb.CREATE_SESSION_REQUEST,
                         pb.CREATE_SESSION_RESPONSE)
        self._forward = u("Forward", pb.FORWARD_REQUEST, pb.FORWARD_RESPONSE)
        self._transfer = u("TransferKVCache", pb.TRANSFER_KV_REQUEST,
                           pb.TRANSFER_KV_RESPONSE)
        self._close = u("CloseSession", pb.CLOSE_SESSION_REQUEST,
                        pb.CLOSE_SESSION_RESPONSE)
        self._health = u("HealthCheck", pb.HEALTH_REQUEST, pb.HEALTH_RESPONSE)
        self._stream = self.channel.stream_stream(
            f"/{_SERVICE}/StreamForward",
            request_serializer=pb.serializer(pb.FORWARD_REQUEST),
            response_deserializer=pb.deserializer(pb.FORWARD_RESPONSE),
        )

    def create_session(self, session_id: str) -> Dict[str, Any]:
        return self._create({"session_id": session_id},
                            timeout=self.timeout_s)

    def forward(self, session_id: str, x: np.ndarray,
                positions: np.ndarray, kv_len_after: int) -> Dict[str, Any]:
        # chaos seam: drop/delay this hop like a flaky cross-host link
        # (no-op passthrough without an installed FaultPlan)
        resp = _faults.wrap_rpc(
            "comm.grpc.forward",
            lambda: self._forward(
                {
                    "session_id": session_id,
                    "kv_len_after": int(kv_len_after),
                    "x": _tensor_msg(x, self._ser),
                    "positions": _tensor_msg(positions, self._ser),
                },
                timeout=self.timeout_s,
            ),
            session_id=session_id,
        )
        return self._unpack_forward(resp)

    def open_stream(self) -> "ForwardStream":
        return ForwardStream(self)

    def transfer_kv(self, handoff: bytes) -> Dict[str, Any]:
        resp = _faults.wrap_rpc(
            "comm.grpc.transfer_kv",
            lambda: self._transfer({"handoff": handoff},
                                   timeout=self.timeout_s),
            size=len(handoff),
        )
        return {"slot": resp["slot"], "bytes_received": resp["bytes_received"]}

    def close_session(self, session_id: str) -> None:
        self._close({"session_id": session_id}, timeout=self.timeout_s)

    def health(self) -> Dict[str, Any]:
        return dict(self._health({}, timeout=self.timeout_s))

    def close(self) -> None:
        self.channel.close()

    def _unpack_forward(self, resp) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        hidden = _tensor_arr(resp.get("hidden"), self._ser)
        if hidden is not None:
            out["hidden"] = hidden
        logits = _tensor_arr(resp.get("logits"), self._ser)
        if logits is not None:
            out["logits"] = logits
        return out


class ForwardStream:
    """One bidi StreamForward stream: ``step()`` sends a hop and blocks for
    its (in-order) response **up to the client timeout** — a hung remote
    stage cancels the call and raises instead of wedging the pipeline
    driver forever (ADVICE r2: the stream_stream call has no deadline of
    its own, unlike the unary calls). Responses are pulled by a reader
    thread so the per-step wait can be bounded; ``close()`` half-closes,
    waits briefly for the server to finish, then cancels."""

    def __init__(self, client: GrpcStageClient) -> None:
        import queue

        self._client = client
        self._q: "queue.Queue[Optional[Dict[str, Any]]]" = queue.Queue()
        self._resp_q: "queue.Queue" = queue.Queue()
        self._call = client._stream(iter(self._q.get, None))
        self._reader = threading.Thread(
            target=self._read_loop, name="grpc-forward-stream-reader",
            daemon=True,
        )
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            for resp in self._call:
                self._resp_q.put(("ok", resp))
            self._resp_q.put(("end", None))
        except Exception as e:  # noqa: BLE001 — surfaced to step()/close()
            self._resp_q.put(("err", e))

    def step(self, session_id: str, x: np.ndarray, positions: np.ndarray,
             kv_len_after: int) -> Dict[str, Any]:
        import queue

        self._q.put(
            {
                "session_id": session_id,
                "kv_len_after": int(kv_len_after),
                "x": _tensor_msg(x, self._client._ser),
                "positions": _tensor_msg(positions, self._client._ser),
            }
        )
        try:
            kind, payload = self._resp_q.get(
                timeout=self._client.timeout_s
            )
        except queue.Empty:
            self._call.cancel()
            raise TimeoutError(
                f"StreamForward hop timed out after "
                f"{self._client.timeout_s}s"
            ) from None
        if kind == "ok":
            return self._client._unpack_forward(payload)
        if kind == "err":
            raise payload
        raise ConnectionError("StreamForward closed by remote")

    def close(self) -> None:
        import queue
        import time as _time

        self._q.put(None)        # ends the request iterator → half-close
        deadline = _time.monotonic() + min(self._client.timeout_s, 2.0)
        while _time.monotonic() < deadline:
            try:
                kind, _ = self._resp_q.get(timeout=0.05)
            except queue.Empty:
                continue
            if kind in ("end", "err"):
                return
        self._call.cancel()      # remote never finished: don't wait forever

    def __enter__(self) -> "ForwardStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
