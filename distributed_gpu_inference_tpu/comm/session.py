"""Client-side distributed pipeline session with REAL failure recovery.

Parity surface: reference ``worker/distributed/session.py`` —
``WorkerSession`` (connect/health/forward :79-166), route walking with
per-hop retry + backoff (:303-329), ``SessionManager`` pool (:398-455).

The reference's failure hook RAISES (``session.py:362-365`` — SURVEY gap #3).
Here ``_handle_hop_failure`` actually recovers: the dead hop is swapped for a
spare worker serving the same layer range, a fresh stage session is created
on it, and the chunk history is REPLAYED through the pipeline prefix to
rebuild the replacement's KV. Replays are safe because page writes are
idempotent (same position + same deterministic values), so healthy stages
just rewrite what they already hold.
"""

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import httpx
import numpy as np

from distributed_gpu_inference_tpu.testing import faults as _faults
from distributed_gpu_inference_tpu.utils.data_structures import (
    BlockRange,
    SessionConfig,
)
from .wire import pack_message, unpack_message

log = logging.getLogger("tpu_pipeline_session")


class PipelineHopError(RuntimeError):
    def __init__(self, hop: int, detail: str) -> None:
        super().__init__(f"hop {hop}: {detail}")
        self.hop = hop
        self.detail = detail


class WorkerSession:
    """One hop: HTTP client to a stage worker's data plane."""

    def __init__(self, base_url: str, layer_range: BlockRange,
                 timeout_s: float = 60.0,
                 transport: Optional[httpx.BaseTransport] = None) -> None:
        self.base_url = base_url.rstrip("/")
        self.layer_range = layer_range
        self._client = httpx.Client(
            base_url=self.base_url, timeout=timeout_s, transport=transport
        )

    def health(self) -> Dict[str, Any]:
        resp = self._client.get("/health")
        resp.raise_for_status()
        return resp.json()

    def create(self, session_id: str) -> None:
        resp = self._client.post(
            "/inference/create_session", json={"session_id": session_id}
        )
        resp.raise_for_status()

    def forward(self, session_id: str, x: np.ndarray, positions: np.ndarray,
                kv_len_after: int) -> Dict[str, np.ndarray]:
        body = pack_message(
            {"session_id": session_id, "kv_len_after": kv_len_after},
            {"x": x, "positions": positions},
        )
        # chaos seam: drop/delay/error this hop like a flaky stage worker
        # (no-op passthrough without an installed FaultPlan) — exercises
        # the per-hop retry + spare-reroute-and-replay recovery above
        resp = _faults.wrap_http(
            "comm.session.forward",
            lambda: self._client.post(
                "/inference/forward", content=body,
                headers={"Content-Type": "application/octet-stream"},
            ),
            url=self.base_url, method="POST",
        )
        if resp.status_code != 200:
            detail = ""
            try:
                detail = resp.json().get("detail", "")
            except ValueError:
                pass
            raise httpx.HTTPStatusError(
                f"{resp.status_code}: {detail}", request=resp.request,
                response=resp,
            )
        _, tensors = unpack_message(resp.content)
        return tensors

    def close(self, session_id: str) -> None:
        try:
            self._client.post(
                "/inference/close", json={"session_id": session_id}
            )
        except httpx.HTTPError:
            pass

    def dispose(self) -> None:
        self._client.close()


@dataclass
class _ChunkRecord:
    tokens: np.ndarray        # [B, S] int32 (what stage 0 consumed)
    positions: np.ndarray     # [B, S] int32
    kv_len_after: int


class DistributedInferenceSession:
    """Drives a route of stage workers for one generation."""

    def __init__(
        self,
        route: Sequence[WorkerSession],
        config: Optional[SessionConfig] = None,
        spare_workers: Optional[List[WorkerSession]] = None,
        session_id: Optional[str] = None,
    ) -> None:
        if not route:
            raise ValueError("empty route")
        self.route: List[WorkerSession] = list(route)
        self.config = config or SessionConfig()
        self.spares: List[WorkerSession] = list(spare_workers or [])
        self.session_id = session_id or uuid.uuid4().hex
        self.kv_len = 0
        self.history: List[_ChunkRecord] = []
        self.stats: Dict[str, Any] = {
            "steps": 0, "retries": 0, "hop_failures": 0, "reroutes": 0,
            "replayed_chunks": 0,
        }
        self._setup_done = False

    # -- lifecycle -----------------------------------------------------------

    def setup(self) -> None:
        """Connect every hop and create the stage sessions (reference
        session.py:246-258)."""
        for i, ws in enumerate(self.route):
            try:
                ws.create(self.session_id)
            except httpx.HTTPError as exc:
                raise PipelineHopError(i, f"create failed: {exc}") from exc
        self._setup_done = True

    def close(self) -> None:
        for ws in self.route:
            ws.close(self.session_id)
        self._setup_done = False

    # -- stepping ------------------------------------------------------------

    def _hop_forward(self, hop: int, x: np.ndarray, positions: np.ndarray,
                     kv_len_after: int) -> Dict[str, np.ndarray]:
        """One hop with per-hop retry + backoff (reference :303-329), then
        failure recovery."""
        ws = self.route[hop]
        last: Optional[Exception] = None
        for attempt in range(self.config.max_retries_per_hop):
            try:
                return ws.forward(
                    self.session_id, x, positions, kv_len_after
                )
            except (httpx.TransportError, httpx.HTTPStatusError) as exc:
                # 4xx except 404 are protocol bugs, not worker death
                if isinstance(exc, httpx.HTTPStatusError) and \
                        exc.response.status_code not in (404, 500, 502, 503, 507):
                    raise PipelineHopError(hop, str(exc)) from exc
                last = exc
                self.stats["retries"] += 1
                time.sleep(self.config.retry_backoff_s * (2**attempt))
        self.stats["hop_failures"] += 1
        self._handle_hop_failure(hop, last)
        # the replacement is installed and warmed; replay THIS chunk on it
        return self.route[hop].forward(
            self.session_id, x, positions, kv_len_after
        )

    def _handle_hop_failure(self, hop: int, cause: Optional[Exception]) -> None:
        """Swap the dead hop for a spare serving the same layers and rebuild
        its KV by replaying history through the pipeline prefix (the recovery
        the reference declares but never implements, session.py:362-365)."""
        dead = self.route[hop]
        replacement: Optional[WorkerSession] = None
        for i, spare in enumerate(self.spares):
            if spare.layer_range == dead.layer_range:
                replacement = self.spares.pop(i)
                break
        if replacement is None:
            raise PipelineHopError(
                hop,
                f"worker {dead.base_url} failed ({cause}) and no spare "
                f"serves layers {dead.layer_range}",
            )
        log.warning(
            "hop %d (%s) failed: rerouting to %s and replaying %d chunks",
            hop, dead.base_url, replacement.base_url, len(self.history),
        )
        replacement.create(self.session_id)
        self.route[hop] = replacement
        dead.dispose()
        self.stats["reroutes"] += 1
        # rebuild the replacement's KV: drive every past chunk through hops
        # [0, hop] — healthy prefix stages rewrite identical pages (idempotent)
        for rec in self.history:
            x: np.ndarray = rec.tokens
            for j in range(hop + 1):
                out = self.route[j].forward(
                    self.session_id, x, rec.positions, rec.kv_len_after
                )
                x = out["hidden"]
            self.stats["replayed_chunks"] += 1

    def step(self, token_ids: np.ndarray,
             positions: Optional[np.ndarray] = None) -> np.ndarray:
        """Walk all hops for one chunk (prefill piece or a single decode
        token). Returns logits [B, S, V] from the last stage."""
        if not self._setup_done:
            self.setup()
        token_ids = np.asarray(token_ids, np.int32)
        if token_ids.ndim == 1:
            token_ids = token_ids[None, :]
        b, s = token_ids.shape
        if positions is None:
            positions = np.tile(
                np.arange(self.kv_len, self.kv_len + s, dtype=np.int32), (b, 1)
            )
        kv_len_after = int(positions.max()) + 1
        if self.config.max_length and kv_len_after > self.config.max_length:
            raise ValueError(
                f"context {kv_len_after} exceeds session max_length "
                f"{self.config.max_length}"
            )

        x: np.ndarray = token_ids
        out: Dict[str, np.ndarray] = {}
        for hop in range(len(self.route)):
            out = self._hop_forward(hop, x, positions, kv_len_after)
            x = out["hidden"]
        self.history.append(
            _ChunkRecord(token_ids, positions, kv_len_after)
        )
        self.kv_len = max(self.kv_len, kv_len_after)
        self.stats["steps"] += 1
        if "logits" not in out:
            raise PipelineHopError(
                len(self.route) - 1, "last stage returned no logits"
            )
        return out["logits"]

    # -- convenience ---------------------------------------------------------

    def generate_greedy(self, prompt_ids: Sequence[int],
                        max_new_tokens: int = 16,
                        stop_ids: Sequence[int] = ()) -> List[int]:
        """Simple greedy driver (prefill chunk + per-token decode steps)."""
        prompt = np.asarray(list(prompt_ids), np.int32)[None, :]
        logits = self.step(prompt)
        out: List[int] = []
        tok = int(np.argmax(logits[0, -1]))
        for _ in range(max_new_tokens):
            out.append(tok)
            if tok in stop_ids:
                break
            logits = self.step(np.asarray([[tok]], np.int32))
            tok = int(np.argmax(logits[0, -1]))
        return out


class SessionManager:
    """Pool of live sessions keyed by session_id with LRU capacity eviction
    (reference SessionManager, session.py:398-455)."""

    def __init__(self, max_sessions: int = 16) -> None:
        self.max_sessions = max_sessions
        self._sessions: Dict[str, DistributedInferenceSession] = {}
        self._last_used: Dict[str, float] = {}

    def add(self, session: DistributedInferenceSession) -> None:
        while len(self._sessions) >= self.max_sessions:
            lru = min(self._last_used, key=self._last_used.get)
            self.remove(lru)
        self._sessions[session.session_id] = session
        self._last_used[session.session_id] = time.time()

    def get(self, session_id: str) -> Optional[DistributedInferenceSession]:
        s = self._sessions.get(session_id)
        if s is not None:
            self._last_used[session_id] = time.time()
        return s

    def remove(self, session_id: str) -> None:
        s = self._sessions.pop(session_id, None)
        self._last_used.pop(session_id, None)
        if s is not None:
            s.close()

    def close_all(self) -> None:
        for sid in list(self._sessions):
            self.remove(sid)

    def __len__(self) -> int:
        return len(self._sessions)
