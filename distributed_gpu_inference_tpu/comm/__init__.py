"""Cross-host data plane: binary tensor wire, pipeline stage workers, and
client-side distributed sessions.

TPU-native re-design of the reference's ``worker/distributed`` P2P layer
(``grpc_server.py`` servicer + aiohttp JSON data plane, ``session.py``
client pipeline): within a slice, pipeline hops are XLA collectives
(``parallel/pipeline.py``) and never touch this package; across hosts,
activations ride a length-prefixed binary frame (msgpack header + zstd
tensor frames) instead of the reference's base64-JSON (SURVEY §3.3 calls
that the #1 throughput sin).
"""

from .wire import pack_message, unpack_message
from .stage_worker import PipelineStageWorker
from .session import (
    DistributedInferenceSession,
    PipelineHopError,
    SessionManager,
    WorkerSession,
)

__all__ = [
    "pack_message",
    "unpack_message",
    "PipelineStageWorker",
    "DistributedInferenceSession",
    "PipelineHopError",
    "SessionManager",
    "WorkerSession",
]
