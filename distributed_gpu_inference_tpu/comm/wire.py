"""Binary message framing for the cross-host data plane.

One message = msgpack metadata + N framed tensors. Replaces the reference's
base64-tensors-inside-JSON (``worker/distributed/session.py:125-160``,
``grpc_server.py:479-524``) with zero-copy-friendly binary: each tensor is a
``utils.serialization.TensorSerializer`` frame (native dtype incl. bfloat16,
optional zstd), so the wire cost is ~1x payload instead of base64's 1.33x
plus JSON escaping, and the same codec serves KV handoff and WAN tiers.

Layout:
    magic   b"TPUM"
    u8      version (=1)
    u32     header length
    bytes   msgpack header {"meta": {...}, "tensors": [name, ...]}
    repeat per tensor: u64 frame length + TensorSerializer frame
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Tuple

import numpy as np

from distributed_gpu_inference_tpu.utils.serialization import (
    TensorSerializer,
    _pack_header,
    _unpack_header,
)

_MAGIC = b"TPUM"
_VERSION = 1


def pack_message(meta: Dict[str, Any],
                 tensors: Dict[str, Any] | None = None,
                 compress: bool = True) -> bytes:
    tensors = tensors or {}
    ser = TensorSerializer(compress=compress)
    header = _pack_header({"meta": meta, "tensors": list(tensors)})
    parts = [_MAGIC, struct.pack("<B", _VERSION),
             struct.pack("<I", len(header)), header]
    for name, t in tensors.items():
        frame = ser.serialize(np.asarray(t))
        parts.append(struct.pack("<Q", len(frame)))
        parts.append(frame)
    return b"".join(parts)


def unpack_message(data: bytes) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    view = memoryview(data)
    if bytes(view[:4]) != _MAGIC:
        raise ValueError("bad magic: not a TPUM message")
    (version,) = struct.unpack_from("<B", view, 4)
    if version != _VERSION:
        raise ValueError(f"unsupported message version {version}")
    (hlen,) = struct.unpack_from("<I", view, 5)
    header = _unpack_header(bytes(view[9 : 9 + hlen]))
    off = 9 + hlen
    ser = TensorSerializer()
    tensors: Dict[str, np.ndarray] = {}
    for name in header["tensors"]:
        (flen,) = struct.unpack_from("<Q", view, off)
        off += 8
        tensors[name] = ser.deserialize(bytes(view[off : off + flen]))
        off += flen
    return header["meta"], tensors
