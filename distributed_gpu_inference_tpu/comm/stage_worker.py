"""Pipeline stage worker: owns a contiguous layer range and serves forwards.

Cross-host counterpart of the reference's ``ModelShard`` + ``InferenceServicer``
(``worker/distributed/model_shard.py:28-259``, ``grpc_server.py:36-374``):

- Stage 0 receives token ids and embeds; middle stages receive hidden states;
  the last stage applies final norm + LM head and returns logits
  (reference model_shard.py:163-171, 230-246).
- Each stage keeps its OWN paged-KV pools for its layers, addressed by
  per-session block tables — device-resident, never shipped (the reference
  ships per-layer KV over the wire; here only [B, S, H] activations cross
  hosts, the KV stays put).
- Replays are idempotent: a page write at the same position with the same
  values is a no-op in effect, which is what makes failure recovery by
  re-driving history through healthy stages safe (see ``comm.session``).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from distributed_gpu_inference_tpu.models import llama
from distributed_gpu_inference_tpu.models.configs import ModelConfig, get_model_config
from distributed_gpu_inference_tpu.parallel.pipeline import slice_stage_params


@dataclass
class _StageSession:
    session_id: str
    blocks: List[int] = field(default_factory=list)
    kv_len: int = 0
    created_at: float = field(default_factory=time.time)
    steps: int = 0


class StageOutOfBlocksError(RuntimeError):
    pass


class PipelineStageWorker:
    """One host's stage of a cross-host pipeline."""

    def __init__(
        self,
        model_cfg: ModelConfig | str,
        layer_range: Tuple[int, int],
        *,
        params: Optional[llama.Params] = None,
        full_params: Optional[llama.Params] = None,
        num_blocks: int = 256,
        block_size: int = 16,
        max_batch: int = 8,
        max_blocks_per_seq: int = 64,
        dtype: str = "float32",
        seed: int = 0,
    ) -> None:
        import jax
        import jax.numpy as jnp

        self.cfg = (
            get_model_config(model_cfg) if isinstance(model_cfg, str) else model_cfg
        )
        self.start, self.end = layer_range
        self.is_first = self.start == 0
        self.is_last = self.end == self.cfg.num_layers
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.dtype = jnp.dtype(dtype)

        if params is None:
            full = full_params if full_params is not None else llama.init_params(
                self.cfg, jax.random.PRNGKey(seed), self.dtype
            )
            params = slice_stage_params(
                full, self.start, self.end, num_layers=self.cfg.num_layers
            )
        self.params = params

        # per-stage KV pools cover ONLY the owned layers (head-major pages,
        # models/llama.py init_kv_pools layout)
        stage_cfg_layers = self.end - self.start
        self.kv = {
            k: jnp.zeros(
                (stage_cfg_layers, num_blocks, self.cfg.num_kv_heads,
                 block_size, self.cfg.head_dim),
                self.dtype,
            )
            for k in ("k", "v")
        }
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))  # 0 reserved
        self._sessions: Dict[str, _StageSession] = {}
        self._lock = threading.Lock()
        self._jit_cache: Dict[Tuple[int, int], Any] = {}
        self.stats: Dict[str, Any] = {
            "forwards": 0, "sessions_created": 0, "sessions_closed": 0,
            "tokens_processed": 0,
        }

    # -- session lifecycle ---------------------------------------------------

    def create_session(self, session_id: str) -> Dict[str, Any]:
        with self._lock:
            if session_id in self._sessions:
                # idempotent create: recovery may re-create after a reconnect
                return {"session_id": session_id, "existing": True}
            self._sessions[session_id] = _StageSession(session_id)
            self.stats["sessions_created"] += 1
        return {"session_id": session_id, "existing": False}

    def close_session(self, session_id: str) -> None:
        with self._lock:
            sess = self._sessions.pop(session_id, None)
            if sess is not None:
                self._free.extend(reversed(sess.blocks))
                self.stats["sessions_closed"] += 1

    def _ensure_blocks(self, sess: _StageSession, kv_len_after: int) -> None:
        needed = max(1, -(-kv_len_after // self.block_size))
        if needed > self.max_blocks_per_seq:
            raise StageOutOfBlocksError(
                f"session {sess.session_id} needs {needed} blocks "
                f"> per-seq limit {self.max_blocks_per_seq}"
            )
        while len(sess.blocks) < needed:
            if not self._free:
                raise StageOutOfBlocksError("stage KV pool exhausted")
            sess.blocks.append(self._free.pop())

    # -- forward -------------------------------------------------------------

    def _fns(self, b: int, s: int):
        """Jitted forward for a (B, S) shape bucket."""
        import jax

        key = (b, s)
        if key in self._jit_cache:
            return self._jit_cache[key]
        cfg, bs = self.cfg, self.block_size

        def run(params, kv, x, positions, block_table, kv_lens):
            hidden = x
            if self.is_first:
                hidden = llama.embed_tokens(params, x, cfg)
            hidden, kv = llama.forward_hidden_chunk(
                cfg, params, hidden, positions, kv, block_table, kv_lens,
                block_size=bs,
            )
            if self.is_last:
                logits = llama.project_logits(cfg, params, hidden)
                return hidden, kv, logits
            return hidden, kv, None

        fn = jax.jit(run, donate_argnums=(1,))
        self._jit_cache[key] = fn
        return fn

    def forward(
        self,
        session_id: str,
        x: np.ndarray,              # tokens [B,S] int32 (first) | hidden [B,S,H]
        positions: np.ndarray,      # [B,S] int32, -1 = pad
        kv_len_after: int,
    ) -> Dict[str, np.ndarray]:
        """Run one chunk through this stage's layers. Returns {"hidden": ...}
        and, on the last stage, {"logits": ...}."""
        import jax.numpy as jnp

        with self._lock:
            sess = self._sessions.get(session_id)
            if sess is None:
                raise KeyError(f"unknown session {session_id}")
            self._ensure_blocks(sess, kv_len_after)
            table = np.zeros((self.max_blocks_per_seq,), np.int32)
            table[: len(sess.blocks)] = sess.blocks
        b, s = x.shape[0], x.shape[1]
        fn = self._fns(b, s)
        if self.is_first:
            xin = jnp.asarray(x.astype(np.int32))
        else:
            xin = jnp.asarray(x, dtype=self.dtype)
        hidden, self.kv, logits = fn(
            self.params, self.kv, xin,
            jnp.asarray(positions.astype(np.int32)),
            jnp.asarray(np.tile(table, (b, 1))),
            jnp.asarray(np.full((b,), kv_len_after, np.int32)),
        )
        with self._lock:
            # replay of an already-seen chunk must not advance the clock
            sess.kv_len = max(sess.kv_len, kv_len_after)
            sess.steps += 1
        self.stats["forwards"] += 1
        n_valid = int((positions >= 0).sum())
        self.stats["tokens_processed"] += n_valid
        out: Dict[str, np.ndarray] = {"hidden": np.asarray(hidden, np.float32)}
        if logits is not None:
            out["logits"] = np.asarray(logits, np.float32)
        return out

    # -- introspection -------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "status": "ok",
                "layer_range": [self.start, self.end],
                "is_first": self.is_first,
                "is_last": self.is_last,
                "active_sessions": len(self._sessions),
                "free_blocks": len(self._free),
                "stats": dict(self.stats),
            }
