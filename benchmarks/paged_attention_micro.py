#!/usr/bin/env python
"""Micro-benchmark: Pallas paged-attention decode kernel vs the XLA gather
fallback, on-device (chained fori_loop + value readback — through a TPU
tunnel, ``block_until_ready`` alone does not wait for device completion and
single-call timing only measures the control RTT).

Measured on v5e (2026-07, ctx window of a llama3-8b-geometry decode batch):

==========================  =========  =========  ========
scenario (B=8, Hkv=8, 128d)  XLA        Pallas     speedup
==========================  =========  =========  ========
uniform ctx=8000             357 us     367 us     ~1x
mixed lens 50..8000          282 us      84 us     3.4x
uniform ctx=1000             9.8 us     15.8 us    0.6x
==========================  =========  =========  ========

The win comes from walking only live pages: the XLA path gathers the full
padded block table for every sequence, the kernel's fori_loop bound is the
sequence's actual page count (and the sliding-window start group). Mixed
lengths are the continuous-batching steady state, so the kernel is the
default on TPU for decode (ops/attention.py impl="auto").

Batch-size crossover (VERDICT r5 weak #6): this micro-bench's NON-FUSED
read kernel loses to XLA gather at large batch (measured on v5e, r5 wedge
table: 2050-2237 µs vs 482-1065 µs at batch 32) while winning 3.4x at
batch 8 mixed — the per-row page re-staging overhead scales with rows.
SERVING never sees this: the model's decode path calls the fused kernel
through ``ops/attention.py resolve_impl`` (label emitted as
``serving_impl`` below). For the micro-bench itself, ``micro_read_impl``
encodes the measured crossover: both variants still run (this IS the
comparison harness), but the emitted ``micro_auto_impl`` labels the
winner for the batch size and the derived ``live_kv_gb_s`` is computed
from the auto-selected variant's timing, so no regime's headline number
comes from the losing kernel.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# Measured crossover of the NON-FUSED micro-bench read kernel vs XLA
# gather (r5 wedge table, v5e): pallas wins at batch <= 8 (3.4x mixed),
# loses 2-4x by batch 32. Between the measured points the conservative
# boundary is 16 rows — at/above it the micro-bench's auto dispatch
# reads through XLA gather.
MICRO_READ_XLA_MIN_BATCH = 16


def micro_read_impl(batch: int) -> str:
    """The variant the micro-bench's ``auto`` dispatch measures for a
    given batch size — the batch-axis crossover the serving-path
    ``resolve_impl`` (context-length axis) deliberately does not model,
    because serving reads through the FUSED in-model kernel instead."""
    return "xla" if batch >= MICRO_READ_XLA_MIN_BATCH else "pallas"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=8)
    ap.add_argument("--q-heads", type=int, default=32)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--ctx", type=int, default=8000)
    ap.add_argument("--mixed", action="store_true",
                    help="heterogeneous lens 50..ctx (continuous batching)")
    ap.add_argument("--iters", type=int, default=500)
    ap.add_argument("--skip-xla", action="store_true",
                    help="skip the XLA-gather variant (its full-table "
                         "gather materializes [B, M*Bk, Hkv, D] context — "
                         "hundreds of MB at batch 32 x ctx 4k, which can "
                         "wedge/OOM the compile on the tunnel chip)")
    ap.add_argument("--skip-pallas", action="store_true",
                    help="skip the Pallas kernel variants (CPU smoke runs: "
                         "interpret-mode pallas inside the timing fori_loop "
                         "trips a JAX lowering-cache limitation)")
    ap.add_argument("--int8", action="store_true",
                    help="also measure the int8-KV (per-token scales) "
                         "kernel path")
    args = ap.parse_args()
    if args.skip_xla and args.skip_pallas:
        ap.error("--skip-xla and --skip-pallas leave nothing to measure")
    if args.int8 and args.skip_pallas:
        ap.error("--int8 measures the Pallas int8 kernel; it cannot be "
                 "combined with --skip-pallas")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_gpu_inference_tpu.ops.attention import paged_attention_xla
    from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
        paged_attention_pallas,
    )

    b, hkv, nh, d = args.batch, args.kv_heads, args.q_heads, args.head_dim
    block, ctx, iters = args.block_size, args.ctx, args.iters

    def timed(fn, *a):
        out = fn(*a)
        float(jnp.sum(out))  # compile + warm
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out = fn(*a)
            float(jnp.sum(out))  # readback forces device completion
            best = min(best, time.perf_counter() - t0)
        return best

    tiny = jnp.ones((8, 128), jnp.float32)
    rtt = min(timed(jax.jit(lambda x: x + 1), tiny) for _ in range(3))

    m = -(-ctx // block)
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 4)
    kp = jax.random.normal(ks[0], (1 + b * m, hkv, block, d), jnp.bfloat16)
    vp = jax.random.normal(ks[1], (1 + b * m, hkv, block, d), jnp.bfloat16)
    tables = jnp.asarray(
        np.arange(1, 1 + b * m, dtype=np.int32).reshape(b, m)
    )
    if args.mixed:
        base = [ctx, 100, ctx // 2, 50, ctx // 4, ctx, 500, 1000]
        lens = jnp.asarray((base * (b // len(base) + 1))[:b], jnp.int32)
    else:
        lens = jnp.full((b,), ctx, jnp.int32)
    pos = (lens - 1)[:, None]
    q = jax.random.normal(ks[3], (b, 1, nh, d), jnp.bfloat16)

    auto_impl = micro_read_impl(b)
    variants = []
    if not args.skip_pallas:
        variants.append(
            ("pallas", partial(paged_attention_pallas, block_size=block),
             (kp, vp), ())
        )
    if not args.skip_xla:
        variants.insert(
            0,
            ("xla", partial(paged_attention_xla, block_size=block),
             (kp, vp), ()),
        )
    if args.int8 and not args.skip_pallas:
        # int8 pools + per-(page, token) scales (VERDICT r3 #4): HBM sees
        # ~62% of the bf16 bytes per token; the kernel dequantizes in-page
        from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
            quantize_kv_pool,
        )

        kp8, kss = quantize_kv_pool(kp)
        vp8, vss = quantize_kv_pool(vp)
        variants.append((
            "pallas_int8",
            partial(paged_attention_pallas, block_size=block),
            (kp8, vp8), (kss, vss),
        ))

    results = {}
    for name, att, pools, scales in variants:
        # pools/scales/tables/lens are jit ARGUMENTS, never closure
        # captures: a captured device array is baked into the computation
        # as a literal, and through the remote-compile tunnel those
        # literals ride the compile request body — at batch 32 x ctx 4096
        # the two pools are ~540 MB and the tunnel rejects the upload with
        # HTTP 413 (the round-4 "wedge"; smaller shapes merely made
        # compile minutes-slow)
        @jax.jit
        def many(q, kpool, vpool, tables, pos, lens, scales, _a=att):
            kw = (
                {"k_scale": scales[0], "v_scale": scales[1]}
                if scales else {}
            )

            def body(i, o):
                return _a(q + (o * 1e-9).astype(q.dtype),
                          kpool, vpool, tables, pos, lens, **kw)
            return jax.lax.fori_loop(0, iters, body, q)

        dt = (timed(many, q, pools[0], pools[1], tables, pos, lens, scales)
              - rtt) / iters
        results[name] = dt * 1e6

    live = int(np.sum(np.asarray(lens)))
    out = {"metric": "paged_attention_decode_us"}
    if "pallas" in results:
        out["pallas_us"] = round(results["pallas"], 1)
    if "xla" in results:
        out["xla_us"] = round(results["xla"], 1)
        if "pallas" in results:
            out["speedup"] = round(results["xla"] / results["pallas"], 2)
    # crossover labelling (VERDICT r5 weak #6): which variant this
    # micro-bench's batch-size dispatch selects, what it measured, and —
    # separately — the FUSED path serving actually reads through (the
    # model-level resolve_impl on the same static shape facts)
    from distributed_gpu_inference_tpu.ops.attention import resolve_impl

    out["micro_auto_impl"] = auto_impl
    if auto_impl in results:
        out["micro_auto_us"] = round(results[auto_impl], 1)
    out["serving_impl"] = resolve_impl(
        q_seq=1, head_dim=d, padded_ctx=m * block,
    )
    out["serving_uses_fused_kernel"] = out["serving_impl"] != "xla"
    best = results.get(auto_impl, results.get("pallas", results.get("xla")))
    out.update(**{
        "live_kv_gb_s": round(
            (live * hkv * d * 2 * 2) / (best / 1e6) / 1e9, 1
        ),
        "config": {"batch": b, "ctx": ctx, "mixed": args.mixed,
                   "block_size": block, "backend": jax.default_backend()},
    })
    if "pallas_int8" in results:
        out["pallas_int8_us"] = round(results["pallas_int8"], 1)
        out["int8_vs_bf16"] = round(
            results["pallas"] / results["pallas_int8"], 2
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
