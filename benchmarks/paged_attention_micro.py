#!/usr/bin/env python
"""Micro-benchmark: Pallas paged-attention kernels vs the XLA gather
fallback, on-device (chained fori_loop + value readback — through a TPU
tunnel, ``block_until_ready`` alone does not wait for device completion and
single-call timing only measures the control RTT).

Measured on v5e (2026-07, ctx window of a llama3-8b-geometry decode batch):

==========================  =========  =========  ========
scenario (B=8, Hkv=8, 128d)  XLA        Pallas     speedup
==========================  =========  =========  ========
uniform ctx=8000             357 us     367 us     ~1x
mixed lens 50..8000          282 us      84 us     3.4x
uniform ctx=1000             9.8 us     15.8 us    0.6x
==========================  =========  =========  ========

The win comes from walking only live pages: the XLA path gathers the full
padded block table for every sequence, the kernel's fori_loop bound is the
sequence's actual page count (and the sliding-window start group). Mixed
lengths are the continuous-batching steady state, so the kernel is the
default on TPU for decode (ops/attention.py impl="auto").

Batch-size crossover (VERDICT r5 weak #6): this micro-bench's NON-FUSED
read kernel loses to XLA gather at large batch (measured on v5e, r5 wedge
table: 2050-2237 µs vs 482-1065 µs at batch 32) while winning 3.4x at
batch 8 mixed — the per-row page re-staging overhead scales with rows.
SERVING never sees this: the model's decode path calls the fused kernel
through ``ops/attention.py resolve_impl`` (label emitted as
``serving_impl`` below). Since round 6 the crossover itself lives in
``resolve_impl`` (``fused=False`` + ``rows``; ``MICRO_READ_XLA_MIN_BATCH``
is an env OVERRIDE only) — this bench calls it instead of duplicating the
threshold, and the emitted ``micro_auto_impl`` labels the auto-selected
variant whose timing feeds the derived ``live_kv_gb_s``.

``--impl ragged`` measures the round-6 ragged kernel — one invocation over
a flattened row batch whose rows carry their own query spans. With
``--q-span 1`` it is an apples-to-apples decode read against the other two
variants; wider spans measure the mixed prefill+decode round shape serving
actually dispatches (``--mixed-spans`` builds the decode-heavy + one-chunk
row mix of a ragged admission round).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=8)
    ap.add_argument("--q-heads", type=int, default=32)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--ctx", type=int, default=8000)
    ap.add_argument("--mixed", action="store_true",
                    help="heterogeneous lens 50..ctx (continuous batching)")
    ap.add_argument("--iters", type=int, default=500)
    ap.add_argument("--impl", choices=["all", "xla", "pallas", "ragged"],
                    default="all",
                    help="which read variant(s) to measure: the XLA "
                         "gather, the non-fused decode kernel, the ragged "
                         "prefill+decode kernel, or all of them")
    ap.add_argument("--q-span", type=int, default=1,
                    help="query span per row for the ragged variant "
                         "(1 = decode-shaped rows; >1 = uniform "
                         "verify/chunk rows)")
    ap.add_argument("--mixed-spans", action="store_true",
                    help="ragged variant only: decode rows (span 1) plus "
                         "ONE prefill chunk row of --q-span queries — the "
                         "row mix of a ragged admission round")
    ap.add_argument("--skip-xla", action="store_true",
                    help="skip the XLA-gather variant (its full-table "
                         "gather materializes [B, M*Bk, Hkv, D] context — "
                         "hundreds of MB at batch 32 x ctx 4k, which can "
                         "wedge/OOM the compile on the tunnel chip)")
    ap.add_argument("--skip-pallas", action="store_true",
                    help="skip the Pallas kernel variants (CPU smoke runs: "
                         "interpret-mode pallas inside the timing fori_loop "
                         "trips a JAX lowering-cache limitation)")
    ap.add_argument("--int8", action="store_true",
                    help="also measure the int8-KV (per-token scales) "
                         "kernel path")
    args = ap.parse_args()
    want = {
        "all": {"xla", "pallas", "ragged"},
        "xla": {"xla"}, "pallas": {"pallas"}, "ragged": {"ragged"},
    }[args.impl]
    if args.skip_xla:
        want.discard("xla")
    if args.skip_pallas:
        want -= {"pallas", "ragged"}
    if not want:
        ap.error("the --impl/--skip flags leave nothing to measure")
    if args.int8 and "pallas" not in want:
        ap.error("--int8 measures the Pallas int8 kernel; it needs the "
                 "pallas variant selected")
    if args.mixed_spans and "ragged" not in want:
        ap.error("--mixed-spans shapes the ragged variant's rows; it needs "
                 "the ragged variant selected")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_gpu_inference_tpu.ops.attention import (
        paged_attention_xla,
        resolve_impl,
    )
    from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
        paged_attention_pallas,
        ragged_paged_attention,
    )

    b, hkv, nh, d = args.batch, args.kv_heads, args.q_heads, args.head_dim
    block, ctx, iters = args.block_size, args.ctx, args.iters

    def timed(fn, *a):
        out = fn(*a)
        float(jnp.sum(out))  # compile + warm
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out = fn(*a)
            float(jnp.sum(out))  # readback forces device completion
            best = min(best, time.perf_counter() - t0)
        return best

    tiny = jnp.ones((8, 128), jnp.float32)
    rtt = min(timed(jax.jit(lambda x: x + 1), tiny) for _ in range(3))

    m = -(-ctx // block)
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 4)
    kp = jax.random.normal(ks[0], (1 + b * m, hkv, block, d), jnp.bfloat16)
    vp = jax.random.normal(ks[1], (1 + b * m, hkv, block, d), jnp.bfloat16)
    tables = jnp.asarray(
        np.arange(1, 1 + b * m, dtype=np.int32).reshape(b, m)
    )
    if args.mixed:
        base = [ctx, 100, ctx // 2, 50, ctx // 4, ctx, 500, 1000]
        lens = jnp.asarray((base * (b // len(base) + 1))[:b], jnp.int32)
    else:
        lens = jnp.full((b,), ctx, jnp.int32)
    pos = (lens - 1)[:, None]
    q = jax.random.normal(ks[3], (b, 1, nh, d), jnp.bfloat16)

    # ragged-variant operands: [B, S] spans. Default S = --q-span for every
    # row; --mixed-spans keeps decode rows at span 1 and gives ONE row the
    # full chunk (the ragged admission round's shape).
    s_rag = max(1, args.q_span)
    pos_rag = np.full((b, s_rag), -1, np.int32)
    lens_np = np.asarray(lens)
    for i in range(b):
        span = 1 if (args.mixed_spans and i != 0) else s_rag
        span = min(span, int(lens_np[i]))
        pos_rag[i, :span] = np.arange(
            lens_np[i] - span, lens_np[i], dtype=np.int32
        )
    q_rag = jax.random.normal(ks[2], (b, s_rag, nh, d), jnp.bfloat16)
    pos_rag = jnp.asarray(pos_rag)

    # the crossover label comes from the ONE dispatch authority (bare read:
    # fused=False + row count), not a bench-local constant
    auto_impl = resolve_impl(
        q_seq=1, head_dim=d, padded_ctx=m * block, rows=b, fused=False,
    )
    variants = []
    if "xla" in want:
        variants.append(
            ("xla", partial(paged_attention_xla, block_size=block),
             (kp, vp), (), (q, pos)),
        )
    if "pallas" in want:
        variants.append(
            ("pallas", partial(paged_attention_pallas, block_size=block),
             (kp, vp), (), (q, pos))
        )
    if "ragged" in want:
        variants.append(
            ("ragged", partial(ragged_paged_attention, block_size=block),
             (kp, vp), (), (q_rag, pos_rag))
        )
    if args.int8:
        # int8 pools + per-(page, token) scales (VERDICT r3 #4): HBM sees
        # ~62% of the bf16 bytes per token; the kernel dequantizes in-page
        from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
            quantize_kv_pool,
        )

        kp8, kss = quantize_kv_pool(kp)
        vp8, vss = quantize_kv_pool(vp)
        variants.append((
            "pallas_int8",
            partial(paged_attention_pallas, block_size=block),
            (kp8, vp8), (kss, vss), (q, pos),
        ))

    results = {}
    for name, att, pools, scales, qp in variants:
        # pools/scales/tables/lens are jit ARGUMENTS, never closure
        # captures: a captured device array is baked into the computation
        # as a literal, and through the remote-compile tunnel those
        # literals ride the compile request body — at batch 32 x ctx 4096
        # the two pools are ~540 MB and the tunnel rejects the upload with
        # HTTP 413 (the round-4 "wedge"; smaller shapes merely made
        # compile minutes-slow)
        @jax.jit
        def many(q, kpool, vpool, tables, pos, lens, scales, _a=att):
            kw = (
                {"k_scale": scales[0], "v_scale": scales[1]}
                if scales else {}
            )

            def body(i, o):
                return _a(q + (o * 1e-9).astype(q.dtype),
                          kpool, vpool, tables, pos, lens, **kw)
            return jax.lax.fori_loop(0, iters, body, q)

        dt = (timed(many, qp[0], pools[0], pools[1], tables, qp[1], lens,
                    scales) - rtt) / iters
        results[name] = dt * 1e6

    live = int(np.sum(np.asarray(lens)))
    out = {"metric": "paged_attention_decode_us"}
    for name in ("pallas", "xla", "ragged"):
        if name in results:
            out[f"{name}_us"] = round(results[name], 1)
    if "xla" in results and "pallas" in results:
        out["speedup"] = round(results["xla"] / results["pallas"], 2)
    if "xla" in results and "ragged" in results:
        out["ragged_speedup_vs_xla"] = round(
            results["xla"] / results["ragged"], 2
        )
    # crossover labelling (VERDICT r5 weak #6): which variant the bare-read
    # dispatch selects for this row count, what it measured, and —
    # separately — the FUSED path serving actually reads through (the
    # model-level resolve_impl on the same static shape facts)
    out["micro_auto_impl"] = auto_impl
    if auto_impl in results:
        out["micro_auto_us"] = round(results[auto_impl], 1)
    out["serving_impl"] = resolve_impl(
        q_seq=1, head_dim=d, padded_ctx=m * block,
    )
    out["serving_uses_fused_kernel"] = out["serving_impl"] != "xla"
    best = results.get(auto_impl,
                       results.get("pallas",
                                   results.get("ragged",
                                               results.get("xla"))))
    out.update(**{
        "live_kv_gb_s": round(
            (live * hkv * d * 2 * 2) / (best / 1e6) / 1e9, 1
        ),
        "config": {"batch": b, "ctx": ctx, "mixed": args.mixed,
                   "impl": args.impl, "q_span": s_rag,
                   "mixed_spans": args.mixed_spans,
                   "block_size": block, "backend": jax.default_backend()},
    })
    if "pallas_int8" in results:
        out["pallas_int8_us"] = round(results["pallas_int8"], 1)
        out["int8_vs_bf16"] = round(
            results["pallas"] / results["pallas_int8"], 2
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
