#!/usr/bin/env python
"""Speculative decoding benchmark: real draft/verify loop, measured speedup.

Parity with the reference's ``benchmarks/speculative.py`` metrics (accept
rate, tokens/step, speedup, draft overhead) — but the reference's harness is
an analytic accept-rate SIMULATOR (:123-272); this one runs the actual
on-device tree draft→verify→accept loop and an identical vanilla decode for
the speedup denominator.

Methodology: random-init weights have near-uniform logits no draft can
match, so the harness first TRAINS the target on a learnable synthetic task
(noisy Markov chain, ``benchmarks/common.train_toy_lm``) and then distills
the EAGLE draft head against it on-device
(``runtime.speculative.distill_draft_params``) — every number is real
compute on real (trained) weights, no simulated accept rates.

Usage:
    python -m benchmarks.speculative --model llama3-mini --requests 4 \
        --max-tokens 64
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from benchmarks.common import (
    Timer,
    add_platform_arg,
    emit,
    make_request,
    resolve_backend_model,
    train_toy_lm,
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--requests", type=int, default=4)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--widths", default="4,2,2",
                    help="tree widths per level, comma-separated")
    ap.add_argument("--train-steps", type=int, default=1500,
                    help="target-model training steps on the synthetic task")
    ap.add_argument("--distill-steps", type=int, default=800,
                    help="EAGLE draft-head distillation steps")
    add_platform_arg(ap)
    args = ap.parse_args()

    import jax

    backend, model = resolve_backend_model(args, tpu_default="llama3-tiny")
    widths = tuple(int(w) for w in args.widths.split(","))

    from distributed_gpu_inference_tpu.models.configs import get_model_config
    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )
    from distributed_gpu_inference_tpu.runtime.speculative import (
        SpeculativeConfig,
        SpeculativeDecoder,
        distill_draft_params,
    )

    cfg = get_model_config(model)
    # big models: adafactor fits f32 training in HBM; a bounded task vocab
    # keeps the synthetic chain learnable at Llama-3's 128k vocab
    big = cfg.num_params > 5e8
    with Timer() as t_train:
        params, sample_stream = train_toy_lm(
            cfg, jax.random.PRNGKey(0), steps=args.train_steps,
            optimizer="adafactor" if big else "adam",
            task_vocab=4096,
        )
    with Timer() as t_distill:
        draft_params = distill_draft_params(
            cfg, params, jax.random.PRNGKey(1), steps=args.distill_steps
        )

    max_seq = args.prompt_len + args.max_tokens + 64
    spec = SpeculativeDecoder(
        cfg,
        params=params,
        draft_params=draft_params,
        spec_cfg=SpeculativeConfig(widths=widths),
        max_batch_size=args.requests,
        max_seq_len=max_seq,
        prefill_buckets=(args.prompt_len,),
    )
    vanilla = TPUEngine(
        cfg,
        EngineConfig(
            max_batch_size=args.requests, max_seq_len=max_seq,
            prefill_buckets=(args.prompt_len,), enable_prefix_cache=False,
        ),
        params=spec.params,  # same weights: same tokens, fair timing
    )

    prompts = [
        [int(t) for t in row]
        for row in sample_stream(
            jax.random.PRNGKey(42), args.requests, args.prompt_len
        )
    ]

    def reqs():
        return [make_request(p, args.max_tokens) for p in prompts]

    # warmup both paths (compile), then reset counters: warmup drafting
    # must not contaminate the reported accept rate / tokens-per-step
    spec.generate(reqs())
    vanilla.generate(reqs())
    for k in spec.stats:
        spec.stats[k] = 0

    with Timer() as t_spec:
        spec_resps = spec.generate(reqs())
    with Timer() as t_van:
        van_resps = vanilla.generate(reqs())

    spec_tokens = sum(r.completion_tokens for r in spec_resps)
    van_tokens = sum(r.completion_tokens for r in van_resps)
    st = spec.get_stats()
    spec_tps = spec_tokens / t_spec.elapsed
    van_tps = van_tokens / t_van.elapsed

    emit({
        "benchmark": "speculative",
        "metric": "speculative_speedup",
        "value": round(spec_tps / van_tps, 3) if van_tps else None,
        "unit": "x vs vanilla decode",
        "model": model,
        "backend": backend,
        "configured_widths": list(widths),
        "widths_at_measurement": st.get("current_widths"),
        "accept_rate": round(
            st["accepted"] / st["drafted"] if st.get("drafted") else 0.0, 4
        ),
        "tokens_per_step": round(st.get("tokens_per_step", 0.0), 3),
        "spec_tokens_per_s": round(spec_tps, 2),
        "vanilla_tokens_per_s": round(van_tps, 2),
        "spec_elapsed_s": round(t_spec.elapsed, 3),
        "vanilla_elapsed_s": round(t_van.elapsed, 3),
        "target_train_s": round(t_train.elapsed, 1),
        "draft_distill_s": round(t_distill.elapsed, 1),
    })


if __name__ == "__main__":
    main()
