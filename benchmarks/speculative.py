#!/usr/bin/env python
"""Speculative decoding benchmark: real draft/verify loop, measured speedup.

Parity with the reference's ``benchmarks/speculative.py`` metrics (accept
rate, tokens/step, speedup, draft overhead) — but the reference's harness is
an analytic accept-rate SIMULATOR (:123-272); this one runs the actual
on-device tree draft→verify→accept loop and an identical vanilla decode for
the speedup denominator.

Methodology: random-init weights have near-uniform logits no draft can
match, so the harness first TRAINS the target on a learnable synthetic task
(noisy Markov chain, ``benchmarks/common.train_toy_lm``) and then distills
the EAGLE draft head against it on-device
(``runtime.speculative.distill_draft_params``) — every number is real
compute on real (trained) weights, no simulated accept rates.

Usage:
    python -m benchmarks.speculative --model llama3-mini --requests 4 \
        --max-tokens 64
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from benchmarks.common import (
    Timer,
    add_platform_arg,
    emit,
    make_request,
    percentiles,
    resolve_backend_model,
    train_toy_lm,
)


def _flatten_params(params, prefix=""):
    """Nested dict-of-arrays → ({'a.b.c': array}, {'a.b.c': dtype_name}).

    bfloat16 does not survive np.savez/np.load (comes back as raw void
    ``|V2``), so extended dtypes ride as uint16 bit patterns with their
    dtype name in a sidecar map."""
    import numpy as np

    out, dtypes = {}, {}
    for k, v in params.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            sub, subd = _flatten_params(v, key + ".")
            out.update(sub)
            dtypes.update(subd)
        else:
            arr = np.asarray(v)
            dtypes[key] = arr.dtype.name
            if arr.dtype.name == "bfloat16":
                arr = arr.view(np.uint16)
            out[key] = arr
    return out, dtypes


def _unflatten_params(data):
    """Inverse of _flatten_params over an npz (ignoring non 'p.' keys)."""
    import json as _json

    import ml_dtypes
    import numpy as np

    dtypes = _json.loads(str(data["dtypes"])) if "dtypes" in data.files else {}
    out = {}
    for key in data.files:
        if not key.startswith("p."):
            continue
        arr = data[key]
        name = dtypes.get(key[2:])
        if name == "bfloat16":
            arr = arr.view(ml_dtypes.bfloat16)
        parts = key[2:].split(".")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return out


def _run_micro(args, spec, vanilla, reqs, model, backend,
               t_train, t_distill, widths, fl) -> None:
    """The direct spec-vs-vanilla measurement (rounds 2-4 metric): both
    engines driven by their own generate() loops, no batcher. NOTE the
    vanilla side decodes per-token here (1 host round per token) — the
    serving comparison below is the one with the RTT-amortized baseline."""
    # warmup both paths (compile), then reset counters: warmup drafting
    # must not contaminate the reported accept rate / tokens-per-step
    spec.generate(reqs())
    vanilla.generate(reqs())
    for k in spec.stats:
        spec.stats[k] = 0

    with Timer() as t_spec:
        spec_resps = spec.generate(reqs())
    with Timer() as t_van:
        van_resps = vanilla.generate(reqs())

    spec_tokens = sum(r.completion_tokens for r in spec_resps)
    van_tokens = sum(r.completion_tokens for r in van_resps)
    st = spec.get_stats()
    spec_tps = spec_tokens / t_spec.elapsed
    van_tps = van_tokens / t_van.elapsed

    emit({
        "benchmark": "speculative",
        "metric": "speculative_speedup",
        "value": round(spec_tps / van_tps, 3) if van_tps else None,
        "unit": "x vs vanilla decode",
        "model": model,
        "backend": backend,
        "configured_widths": list(widths),
        "widths_at_measurement": st.get("current_widths"),
        "accept_rate": round(
            st["accepted"] / st["drafted"] if st.get("drafted") else 0.0, 4
        ),
        "tokens_per_step": round(st.get("tokens_per_step", 0.0), 3),
        "spec_tokens_per_s": round(spec_tps, 2),
        "vanilla_tokens_per_s": round(van_tps, 2),
        "spec_elapsed_s": round(t_spec.elapsed, 3),
        "vanilla_elapsed_s": round(t_van.elapsed, 3),
        "target_train_s": round(t_train.elapsed, 1),
        "draft_distill_s": round(t_distill.elapsed, 1),
        "target_trained": not (args.no_train or args.quantization),
        "quantization": args.quantization,
        "feature_layers": list(fl) if fl else None,
        "distill_data": args.distill_data,
    })


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--requests", type=int, default=4)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--widths", default="4,2,2",
                    help="tree widths per level, comma-separated")
    ap.add_argument("--train-steps", type=int, default=1500,
                    help="target-model training steps on the synthetic task")
    ap.add_argument("--distill-steps", type=int, default=800,
                    help="EAGLE draft-head distillation steps")
    ap.add_argument("--distill-seq-len", type=int, default=64,
                    help="distill stream length: must COVER the serving "
                         "positions (prompt + max-tokens) or acceptance "
                         "collapses out-of-distribution past it — the "
                         "round-5 finding that explained serving accept "
                         "at 256-token generations being ~0 while the "
                         "64-token micro measured 0.36")
    ap.add_argument("--task-vocab", type=int, default=4096,
                    help="Markov-chain state count for target training; "
                         "smaller = sharper target at a fixed step budget "
                         "(the tunnel chip kernel-faults under sustained "
                         "training, so steps cannot simply be raised)")
    ap.add_argument("--no-adaptive", action="store_true",
                    help="pin the tree widths (no adaptive depth changes): "
                         "mid-measurement depth changes compile fresh "
                         "step graphs (seconds of XLA time inside the timed "
                         "window) and make accept rates incomparable "
                         "across ablation cells")
    ap.add_argument("--feature-layers", default=None,
                    help="EAGLE-3 multi-layer draft features: comma layer "
                         "indices (e.g. 1,2,3) or 'auto' (low/mid/high). "
                         "Default: last layer only (EAGLE-1)")
    ap.add_argument("--distill-data", default="random",
                    choices=("random", "on-policy", "task"),
                    help="distill streams: uniform-random tokens (round-3 "
                         "behavior), the target's own sampled generations "
                         "(on-policy), or the trained task distribution")
    ap.add_argument("--quantization", default=None,
                    help="weight-only target quantization (int8 | fp8): the "
                         "flagship 8B target only fits the chip quantized; "
                         "implies --no-train (a quantized target cannot be "
                         "trained) — measures the real tree machinery cost "
                         "at flagship scale (VERDICT r3 #1a)")
    ap.add_argument("--no-train", action="store_true",
                    help="skip target training (random-init target): the "
                         "draft is still distilled against the real frozen "
                         "target, so accept rates are real but lower — for "
                         "environments where big-model f32 training is "
                         "unavailable (the tunnel chip kernel-faults on "
                         "1B-scale training; observed rounds 2-3)")
    ap.add_argument("--task-noise", type=float, default=0.05,
                    help="Markov-chain noise for target training: lower = "
                         "more deterministic continuations = the high-"
                         "acceptance regime real trained models live in "
                         "(reference claims 2-3x THERE, README.md:30)")
    ap.add_argument("--rounds-per-dispatch", type=int, default=8,
                    help="tree rounds fused per device dispatch "
                         "(SpeculativeConfig.rounds_per_dispatch): the "
                         "spec analogue of decode_multi's T — through a "
                         "~110 ms tunnel RTT the serving comparison is "
                         "only fair when BOTH paths amortize")
    # -- serving mode (VERDICT r4 #4): spec THROUGH the batcher ----------
    ap.add_argument("--serving-rate", default=None,
                    help="after the micro measurement, drive an open-loop "
                         "Poisson workload at this req/s THROUGH the "
                         "ContinuousBatcher twice — spec-on vs spec-off — "
                         "and emit a speculative_serving line per rate "
                         "(comma-separated rates sweep)")
    ap.add_argument("--serving-requests", type=int, default=24)
    ap.add_argument("--skip-micro", action="store_true",
                    help="skip the micro spec-vs-vanilla measurement and "
                         "go straight to the serving comparison (the "
                         "micro vanilla baseline decodes per-token, which "
                         "dominates wall-clock at long max-tokens)")
    ap.add_argument("--serving-target-step-ms", type=float, default=400.0,
                    help="batcher round-latency target for the serving "
                         "comparison; must exceed the host-device RTT "
                         "(~110 ms through the tunnel) or the paged "
                         "horizon collapses to 1 and BOTH sides crawl")
    ap.add_argument("--spec-max-batch", type=int, default=2,
                    help="batcher routing knob: spec fires only when the "
                         "entire waiting load is <= this many greedy "
                         "requests")
    ap.add_argument("--spec-max-active", type=int, default=2,
                    help="batcher routing knob: a wave may start while up "
                         "to this many paged slots are active (0 = require "
                         "an idle engine — sticky-paged at steady rates)")
    ap.add_argument("--train-out", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--measure-from", default=None, help=argparse.SUPPRESS)
    add_platform_arg(ap)
    args = ap.parse_args()

    from distributed_gpu_inference_tpu.models.configs import get_model_config

    widths = tuple(int(w) for w in args.widths.split(","))
    # big models train in a SUBPROCESS that must run BEFORE this process
    # opens its TPU client: the tunnel pins a client's memory view at
    # connect time, so a parent that initialized the backend first never
    # sees the trainer's ~12 GB again (observed: distill OOMs in the parent
    # while succeeding in any fresh process). Decide everything jax-free.
    big = bool(args.model) and \
        get_model_config(args.model).num_params > 5e8
    if big and not args.no_train and not args.quantization \
            and not args.train_out \
            and not args.measure_from and args.platform != "cpu":
        # ORCHESTRATE ONLY: the tunnel client connects at interpreter start
        # and pins its memory view, so a process that was alive while the
        # f32 trainer held the chip can never allocate afterwards. Phase 1
        # (train) and phase 2 (distill + measure) therefore each run in
        # their own fresh process; this one just shuttles the npz.
        import subprocess
        import sys as _sys
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            out = f"{td}/trained.npz"
            base = [_sys.executable, "-m", "benchmarks.speculative",
                    "--model", args.model,
                    "--train-steps", str(args.train_steps),
                    "--distill-steps", str(args.distill_steps),
                    "--requests", str(args.requests),
                    "--prompt-len", str(args.prompt_len),
                    "--max-tokens", str(args.max_tokens),
                    "--widths", args.widths,
                    "--task-vocab", str(args.task_vocab),
                    "--task-noise", str(args.task_noise),
                    "--distill-seq-len", str(args.distill_seq_len),
                    "--rounds-per-dispatch", str(args.rounds_per_dispatch),
                    "--spec-max-batch", str(args.spec_max_batch),
                    "--spec-max-active", str(args.spec_max_active),
                    "--serving-target-step-ms",
                    str(args.serving_target_step_ms),
                    "--serving-requests", str(args.serving_requests),
                    "--distill-data", args.distill_data]
            if args.serving_rate:
                base += ["--serving-rate", str(args.serving_rate)]
            if args.skip_micro:
                base += ["--skip-micro"]
            if args.feature_layers:
                base += ["--feature-layers", args.feature_layers]
            if args.no_adaptive:
                base += ["--no-adaptive"]
            import time as _time

            t0 = _time.perf_counter()
            subprocess.run(base + ["--train-out", out], check=True)
            t_train_s = _time.perf_counter() - t0
            import os as _os

            _os.environ["DGI_SPEC_TRAIN_S"] = f"{t_train_s:.1f}"
            # let the tunnel reclaim the trainer's memory before the
            # measure process connects — a client's memory view pins at
            # connect time, so connecting during lazy reclaim starves it
            _time.sleep(45.0)
            subprocess.run(base + ["--measure-from", out], check=True)
        return

    trained_blob = None
    if args.measure_from:
        import numpy as _np

        trained_blob = _np.load(args.measure_from, allow_pickle=False)

    import jax

    backend, model = resolve_backend_model(args, tpu_default="llama3-tiny")

    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )
    from distributed_gpu_inference_tpu.runtime.speculative import (
        SpeculativeConfig,
        SpeculativeDecoder,
        distill_draft_params,
    )

    cfg = get_model_config(model)
    big = cfg.num_params > 5e8

    def run_training():
        return train_toy_lm(
            cfg, jax.random.PRNGKey(0), steps=args.train_steps,
            optimizer="adafactor" if big else "adam",
            task_vocab=args.task_vocab,
            noise=args.task_noise,
            batch=8 if big else 16,
        )

    if args.train_out:
        # subprocess mode: train, dump bf16 params + chain spec, exit —
        # the process boundary is the only reliable way to return the
        # f32 training peak to the tunnel-side allocator
        import numpy as _np

        params, sample_stream = run_training()
        import json as _json

        flat, dtypes = _flatten_params(params)
        _np.savez(args.train_out, perm=_np.asarray(sample_stream.perm),
                  noise=sample_stream.noise, dtypes=_json.dumps(dtypes),
                  **{f"p.{k}": v for k, v in flat.items()})
        return

    if trained_blob is not None:
        import os as _os

        from benchmarks.common import make_chain_sampler

        class _T:  # orchestrator-measured training wall time
            elapsed = float(_os.environ.get("DGI_SPEC_TRAIN_S", "0"))

        t_train = _T()
        params = _unflatten_params(trained_blob)
        sample_stream = make_chain_sampler(
            trained_blob["perm"], float(trained_blob["noise"]))
    elif args.no_train or args.quantization:
        class _T0:
            elapsed = 0.0

        t_train = _T0()
        if args.quantization:
            # flagship-scale target (8B int8): build through the engine's
            # quantized loader so the content-keyed orbax cache applies —
            # a second run restores int8 from disk instead of re-initing
            from distributed_gpu_inference_tpu.runtime.engine import (
                EngineConfig as _EC,
                TPUEngine as _TE,
            )

            cache = str(Path(__file__).resolve().parent.parent / ".cache" /
                        "quant")
            loader = _TE(cfg, _EC(
                max_batch_size=1, max_seq_len=64, num_blocks=4,
                prefill_buckets=(32,), quantization=args.quantization,
                quant_cache_dir=cache,
            ))
            params = loader.params
            del loader
        else:
            from distributed_gpu_inference_tpu.models import llama

            params = llama.init_params(cfg, jax.random.PRNGKey(0))

        def sample_stream(key, n, length):
            return jax.random.randint(
                key, (n, length), 1, min(cfg.vocab_size, 4096), "int32"
            )
    else:
        with Timer() as t_train:
            params, sample_stream = run_training()
    # EAGLE-3 knobs: multi-layer features + distill-data distribution
    if args.feature_layers == "auto":
        L = cfg.num_layers
        fl = tuple(sorted({max(L // 4, 0), L // 2, L - 1}))
    elif args.feature_layers:
        fl = tuple(int(x) for x in args.feature_layers.split(","))
    else:
        fl = None
    distill_kw = dict(feature_layers=fl, seq_len=args.distill_seq_len)
    if args.distill_data == "on-policy":
        distill_kw["on_policy"] = True
    elif args.distill_data == "task":
        if args.no_train or args.quantization:
            raise SystemExit("--distill-data task needs a trained target")
        distill_kw["data_stream"] = sample_stream

    with Timer() as t_distill:
        # the tunnel frees an exited process's device memory asynchronously;
        # right after subprocess training the first allocation burst can
        # race that reclaim — retry with backoff instead of dying
        import time as _time

        for attempt in range(4):
            try:
                draft_params = distill_draft_params(
                    cfg, params, jax.random.PRNGKey(1),
                    steps=args.distill_steps, **distill_kw,
                )
                break
            except Exception as exc:  # noqa: BLE001
                if "RESOURCE_EXHAUSTED" not in str(exc) or attempt == 3:
                    raise
                jax.clear_caches()
                _time.sleep(10.0 * (attempt + 1))

    max_seq = args.prompt_len + args.max_tokens + 64
    spec = SpeculativeDecoder(
        cfg,
        params=params,
        draft_params=draft_params,
        spec_cfg=SpeculativeConfig(widths=widths, feature_layers=fl,
                                   adaptive=not args.no_adaptive,
                                   rounds_per_dispatch=args.rounds_per_dispatch),
        max_batch_size=args.requests,
        max_seq_len=max_seq,
        prefill_buckets=(args.prompt_len,),
    )
    vanilla = TPUEngine(
        cfg,
        EngineConfig(
            max_batch_size=args.requests, max_seq_len=max_seq,
            prefill_buckets=(args.prompt_len,), enable_prefix_cache=False,
        ),
        params=spec.params,  # same weights: same tokens, fair timing
    )

    prompts = [
        [int(t) for t in row]
        for row in sample_stream(
            jax.random.PRNGKey(42), args.requests, args.prompt_len
        )
    ]

    def reqs():
        return [make_request(p, args.max_tokens) for p in prompts]

    if not args.skip_micro:
        _run_micro(args, spec, vanilla, reqs, model, backend,
                   t_train, t_distill, widths, fl)

    # ---- serving mode (VERDICT r4 #4): the SAME open-loop workload through
    # the ContinuousBatcher, spec-on vs spec-off. The spec decoder only ever
    # engages through its routing gate (all-greedy waiting load <=
    # spec_max_batch, paged engine idle), so this measures the spec
    # integration as DEPLOYED, not the micro harness.
    if args.serving_rate:
        import asyncio

        from distributed_gpu_inference_tpu.runtime.batcher import (
            BatcherConfig,
            ContinuousBatcher,
        )

        # pin tree adaptation for the measurement: the scan cache is keyed
        # by (widths, rounds), so a mid-serving depth change would
        # cold-compile an unwarmed scan graph (~a minute through the
        # tunnel) inside someone's TTFT — the warmup ladder below covers
        # exactly the pinned widths
        spec.spec_cfg.adaptive = False
        n = args.serving_requests
        srv_prompts = [
            [int(t) for t in row]
            for row in sample_stream(jax.random.PRNGKey(77), n,
                                     args.prompt_len)
        ]
        # warmup prompts come from OUTSIDE the measured set (and the spec
        # pool's prefix cache is cleared below): warming with measured
        # prompts would hand the spec-on side cached prefills the paged
        # spec-off side (prefix cache disabled) never gets
        warm_prompts = [
            [int(t) for t in row]
            for row in sample_stream(jax.random.PRNGKey(555),
                                     max(args.spec_max_batch, 1),
                                     args.prompt_len)
        ]
        bcfg = BatcherConfig(
            default_timeout_s=600.0,
            spec_max_batch=args.spec_max_batch,
            spec_max_active=args.spec_max_active,
            target_step_latency_ms=args.serving_target_step_ms,
        )
        # warm every wave width the router can start (each is a distinct
        # scan-graph batch shape) — with the SERVING budget, so the same
        # power-of-two rounds bucket compiles now, not mid-wave (a fresh
        # scan compile through the tunnel is ~a minute inside a TTFT).
        # ALSO walk the whole rounds ladder per width: block pressure can
        # shrink a dispatch to any lower power of two at runtime
        # (advance_wave blocks_needed), and a generation's tail uses the
        # small buckets — every (width, rounds) pair must pre-compile.
        ladder = [args.max_tokens]
        r = 1
        while r < args.rounds_per_dispatch:
            ladder.append(r + 1)    # max_remaining = r+1-1 = r → bucket r
            r *= 2
        for wb in range(1, min(args.spec_max_batch,
                               spec.max_batch_size) + 1):
            for mt in ladder:
                spec.generate(
                    [make_request(p, mt) for p in warm_prompts[:wb]]
                )
        spec.manager.clear_cached()     # no warm prefixes into the measure
        for T in bcfg.horizon_levels:
            slot = vanilla.submit(make_request(srv_prompts[0], 2))
            while vanilla.slots[slot] is not None and \
                    vanilla.slots[slot].finish_reason is None:
                vanilla.decode_multi(T)
            vanilla.finish_slot(slot, cache=False)
        for k in spec.stats:
            spec.stats[k] = 0

        async def drive(spec_obj, rate):
            from benchmarks.common import open_loop_drive

            batcher = ContinuousBatcher(vanilla, bcfg, spec=spec_obj)
            batcher.start()
            res, elapsed, _ = await open_loop_drive(
                batcher, srv_prompts, args.max_tokens, rate
            )
            stats = batcher.get_stats()
            await batcher.stop()
            return res, elapsed, stats

        def side(spec_obj, rate):
            # each side starts with a cold spec prefix cache
            spec.manager.clear_cached()
            res, elapsed, stats = asyncio.run(drive(spec_obj, rate))
            okr = [r for r, _ in res if r.error is None]
            toks = sum(r.completion_tokens for r in okr)
            return {
                "ok": len(okr),
                "tokens_per_s": round(toks / elapsed, 2),
                "e2e_ms": percentiles([ms for _, ms in res]),
                "ttft_ms": percentiles(
                    [r.ttft_ms for r in okr if r.ttft_ms is not None]
                ),
                "spec_waves": stats.get("spec_waves", 0),
                "spec_completed": stats.get("spec_completed", 0),
            }

        for rate in [float(r) for r in str(args.serving_rate).split(",")]:
            off = side(None, rate)
            st0 = {k: v for k, v in spec.get_stats().items()}
            on = side(spec, rate)
            st1 = spec.get_stats()
            drafted = st1.get("drafted", 0) - st0.get("drafted", 0)
            accepted = st1.get("accepted", 0) - st0.get("accepted", 0)
            emit({
                "benchmark": "speculative_serving",
                "metric": "spec_on_vs_off_tokens_per_s",
                "value": round(
                    on["tokens_per_s"] / off["tokens_per_s"], 3
                ) if off["tokens_per_s"] else None,
                "unit": "x (open-loop through the batcher)",
                "model": model,
                "arrival_rate_rps": rate,
                "requests": n,
                "spec_max_batch": args.spec_max_batch,
                "spec_max_active": args.spec_max_active,
                "rounds_per_dispatch": args.rounds_per_dispatch,
                "serving_accept_rate": round(
                    accepted / drafted, 4) if drafted else 0.0,
                "spec_on": on,
                "spec_off": off,
            })


if __name__ == "__main__":
    main()
