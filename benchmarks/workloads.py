#!/usr/bin/env python
"""Seeded multi-tenant workload generator — the traces production serving
actually sees, for measuring cache-aware routing (and any future cluster
bench) honestly.

Every scenario produces a deterministic, seed-stable open-loop trace: the
same ``(scenario, seed, knobs)`` always generates byte-identical requests
and arrival times, so two benchmark legs (routing ON vs OFF, ragged vs
legacy, one replica vs four) replay the EXACT same offered load.

Scenarios:

- ``chat``     multi-turn conversations with growing shared prefixes: each
               tenant has a system prompt shared by all its conversations;
               each turn's prompt is the previous turn's prompt plus an
               assistant stub and a fresh user message — the prefix a
               radix cache (and a locality router) can reuse grows every
               turn. Turn k+1 depends on turn k (``depends_on`` + think
               time): an open-loop driver must not fire a turn before its
               predecessor's reply exists.
- ``rag``      single-shot requests with long, heterogeneous prompts: a
               document context drawn from a small shared corpus (the
               cacheable part) plus a unique query; prompt lengths are
               lognormal — the long tail is the point.
- ``bursty``   the chat mix, but tenant arrivals modulate through on/off
               bursts (a tenant's whole fleet goes quiet, then floods) —
               the schedule a locality router must not melt under.
- ``storm``    the ANTI-AFFINITY schedule (round 13): a handful of tenants
               with deep shared system prompts take turns flooding the
               fleet — a whole burst of one tenant's requests lands inside
               a fraction of a second, saturating whichever worker is warm
               for that prefix so load-based spillover scatters the tail
               across cold workers. Advisory routing (PR 7) collapses
               here by design; cluster-wide KV migration is measured
               against exactly this trace.
- ``priority`` the rag mix across NAMED tenant tiers (round 12): paid
               (priority 10) over free (priority 0) over batch
               (priority -10), assigned per tenant by index — the tier
               mix the overload-control ladder (server/admission.py)
               sheds and degrades against. Every request carries its
               tenant id and tier in the trace.
- ``longctx``  long-context traffic (round 17): book-length RAG contexts
               (a shared corpus of ~``long_len``-char documents, one per
               request plus a unique query — the 32k shape) interleaved
               with long AGENT TRACES (one conversation whose prompt is
               the full accumulated tool-call transcript, dependency-
               chained like chat turns). A background trickle of SHORT
               chat requests rides the same trace so one run measures
               both the giant prefills and the short-request tails they
               threaten — the mixed-traffic frontier the prefill budget
               exists for.

Any scenario can additionally be generated ``tiered=True``: tenants gain
paid/free/batch tiers (index-derived — NO extra rng draws, so arrival
schedules and prompts stay byte-identical to the untiered trace) and the
matching priorities. Untiered traces omit the ``tier`` field entirely,
keeping their JSONL byte-identical to pre-tier builds.

Usage (CLI emits JSONL for external drivers; ``generate()`` is the
library surface ``benchmarks/worker_serving.py --workers`` drives):

    python -m benchmarks.workloads --scenario chat --seed 0
    python -m benchmarks.workloads --scenario rag --seed 3 --requests 64
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

_LETTERS = "abcdefghijklmnopqrstuvwxyz"

# control-plane priority per named tier — mirrors
# server/admission.py TIER_PRIORITY_BOOST (benchmarks must not import
# server code; the pairing is asserted in tests/test_overload_chaos.py)
TIER_PRIORITY = {"paid": 10, "free": 0, "batch": -10}


def tier_for_tenant(index: int, tenants: int) -> str:
    """Deterministic index-derived tier split: the first quarter of
    tenants (at least one) is paid, the last quarter (when ≥3 tenants)
    is batch, the middle is free. No rng draws — tier assignment can be
    bolted onto an existing trace without moving a single arrival."""
    n_paid = max(1, tenants // 4)
    n_batch = max(1, tenants // 4) if tenants >= 3 else 0
    if index < n_paid:
        return "paid"
    if n_batch and index >= tenants - n_batch:
        return "batch"
    return "free"


def _text(rng: np.random.Generator, n: int) -> str:
    """Deterministic ASCII filler (ByteTokenizer: one token per char)."""
    return "".join(_LETTERS[i] for i in rng.integers(0, 26, int(n)))


@dataclass
class WorkloadRequest:
    """One trace entry. ``arrival_s`` is the open-loop offset from trace
    start; when ``depends_on`` is set the driver must additionally wait
    for that request's completion plus ``think_s`` (multi-turn chat —
    a turn cannot be typed before the previous reply renders)."""

    id: str
    arrival_s: float
    tenant: str
    prompt: str
    max_tokens: int
    priority: int = 0
    conversation: Optional[str] = None
    turn: int = 0
    depends_on: Optional[str] = None
    think_s: float = 0.0
    # named tenant tier (paid/free/batch — round 12 overload control).
    # Empty = untiered: the field is OMITTED from JSONL so pre-tier
    # traces stay byte-identical.
    tier: str = ""


@dataclass
class Workload:
    scenario: str
    seed: int
    requests: List[WorkloadRequest]
    meta: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return max((r.arrival_s for r in self.requests), default=0.0)

    def to_jsonl(self) -> str:
        # untiered requests drop the empty tier key: same-seed JSONL for
        # pre-tier scenarios is byte-identical to pre-tier builds
        out = []
        for r in self.requests:
            d = asdict(r)
            if not d.get("tier"):
                d.pop("tier", None)
            out.append(json.dumps(d))
        return "\n".join(out)


def _chat(rng: np.random.Generator, *, requests: int, tenants: int,
          turns: int, rate: float, system_len: int, turn_len: int,
          max_tokens: int, think_s: float,
          priority_for: Optional[Dict[str, int]] = None) -> List[WorkloadRequest]:
    n_convs = max(1, requests // max(1, turns))
    out: List[WorkloadRequest] = []
    sys_prompts = {
        f"t{t}": _text(rng, system_len) for t in range(tenants)
    }
    conv_starts = np.cumsum(rng.exponential(1.0 / rate, n_convs))
    for c in range(n_convs):
        tenant = f"t{int(rng.integers(0, tenants))}"
        conv = f"c{c}"
        history = sys_prompts[tenant]
        prev_id: Optional[str] = None
        # turns arrive dependency-chained; arrival_s spaces conversations
        at = float(conv_starts[c])
        for k in range(turns):
            if len(out) >= requests:
                return out
            user = _text(rng, turn_len)
            prompt = history + user
            rid = f"{conv}.{k}"
            out.append(WorkloadRequest(
                id=rid, arrival_s=round(at, 4), tenant=tenant,
                prompt=prompt, max_tokens=max_tokens,
                priority=(priority_for or {}).get(tenant, 0),
                conversation=conv, turn=k, depends_on=prev_id,
                think_s=round(float(rng.uniform(0.5, 1.5) * think_s), 4)
                if prev_id is not None else 0.0,
            ))
            # the assistant stub stands in for the reply the client would
            # echo back — deterministic, so the grown prefix is stable
            history = prompt + "|" + _text(rng, max_tokens // 2) + "|"
            prev_id = rid
    return out


def _rag(rng: np.random.Generator, *, requests: int, tenants: int,
         rate: float, corpus_docs: int, doc_len: int, query_len: int,
         max_tokens: int,
         priority_for: Optional[Dict[str, int]] = None) -> List[WorkloadRequest]:
    corpus = [_text(rng, max(32, int(rng.lognormal(np.log(doc_len), 0.5))))
              for _ in range(corpus_docs)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, requests))
    out: List[WorkloadRequest] = []
    for i in range(requests):
        tenant = f"t{int(rng.integers(0, tenants))}"
        # zipf-ish doc popularity: a few hot docs dominate — the shareable
        # prefix mass a locality router exists for
        doc = corpus[min(corpus_docs - 1,
                         int(rng.zipf(1.5)) - 1)]
        out.append(WorkloadRequest(
            id=f"r{i}", arrival_s=round(float(arrivals[i]), 4),
            tenant=tenant, prompt=doc + _text(rng, query_len),
            max_tokens=max_tokens,
            priority=(priority_for or {}).get(tenant, 0),
        ))
    return out


def _storm(rng: np.random.Generator, *, requests: int, tenants: int,
           rate: float, system_len: int, turn_len: int, max_tokens: int,
           burst: int,
           priority_for: Optional[Dict[str, int]] = None) -> List[WorkloadRequest]:
    """Anti-affinity tenant storms: each storm picks ONE tenant and fires
    ``burst`` requests sharing that tenant's deep system prompt within a
    ~quarter-second window — faster than any single worker can absorb, so
    a locality router must either queue on the warm worker or spill the
    tail cold. ``rate`` is storms/s."""
    sys_prompts = {f"t{t}": _text(rng, system_len) for t in range(tenants)}
    burst = max(1, burst)
    n_storms = max(1, -(-requests // burst))
    storm_starts = np.cumsum(rng.exponential(1.0 / rate, n_storms))
    # the burst window scales with the burst: requests land far faster
    # than one worker drains them (saturation) while still spanning a few
    # heartbeats — the router SEES the warm worker saturate mid-storm,
    # which is the moment advisory routing starts spilling cold
    span = 0.15 * burst
    out: List[WorkloadRequest] = []
    for s in range(n_storms):
        tenant = f"t{int(rng.integers(0, tenants))}"
        at = float(storm_starts[s])
        offs = np.sort(rng.uniform(0.0, span, burst))
        for j in range(burst):
            if len(out) >= requests:
                return out
            out.append(WorkloadRequest(
                id=f"s{s}.{j}", arrival_s=round(at + float(offs[j]), 4),
                tenant=tenant,
                prompt=sys_prompts[tenant] + _text(rng, turn_len),
                max_tokens=max_tokens,
                priority=(priority_for or {}).get(tenant, 0),
                conversation=f"s{s}",
            ))
    return out


def _longctx(rng: np.random.Generator, *, requests: int, tenants: int,
             rate: float, long_len: int, query_len: int, turn_len: int,
             max_tokens: int, corpus_docs: int, agent_turns: int,
             short_fraction: float,
             priority_for: Optional[Dict[str, int]] = None
             ) -> List[WorkloadRequest]:
    """Long-context mix: ~1/3 book-length RAG one-shots, ~1/3 one long
    agent trace (dependency-chained turns whose prompt accumulates the
    whole transcript toward ``long_len``), and ``short_fraction`` short
    chat requests woven between them. Length jitter is mild (±12%) so a
    trace generated for a 32k deployment actually exercises ~32k paths
    instead of averaging down to 16k."""
    corpus = [
        _text(rng, max(256, int(long_len * float(rng.uniform(0.88, 1.12)))))
        for _ in range(corpus_docs)
    ]
    n_short = int(requests * short_fraction)
    n_agent = min(agent_turns, max(0, (requests - n_short) // 3))
    n_rag = max(0, requests - n_short - n_agent)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, requests))
    out: List[WorkloadRequest] = []
    # book-length RAG one-shots: hot docs dominate (zipf), so prefix
    # caching and affinity routing have something to win at 32k depth
    for i in range(n_rag):
        tenant = f"t{int(rng.integers(0, tenants))}"
        doc = corpus[min(corpus_docs - 1, int(rng.zipf(1.5)) - 1)]
        out.append(WorkloadRequest(
            id=f"L{i}", arrival_s=round(float(arrivals[i]), 4),
            tenant=tenant, prompt=doc + _text(rng, query_len),
            max_tokens=max_tokens,
            priority=(priority_for or {}).get(tenant, 0),
        ))
    # one long agent trace: each turn's prompt is the full transcript so
    # far — the grown prefix marches toward long_len and each turn
    # depends on its predecessor (a tool call cannot fire before the
    # previous observation exists)
    if n_agent:
        tenant = f"t{int(rng.integers(0, tenants))}"
        step = max(turn_len, long_len // max(1, n_agent))
        history = _text(rng, step)
        prev_id: Optional[str] = None
        for k in range(n_agent):
            i = n_rag + k
            rid = f"A0.{k}"
            out.append(WorkloadRequest(
                id=rid, arrival_s=round(float(arrivals[i]), 4),
                tenant=tenant, prompt=history, max_tokens=max_tokens,
                priority=(priority_for or {}).get(tenant, 0),
                conversation="A0", turn=k, depends_on=prev_id,
                think_s=round(float(rng.uniform(0.05, 0.2)), 4)
                if prev_id is not None else 0.0,
            ))
            history = history + "|" + _text(rng, step) + "|"
            prev_id = rid
    # the short-request tail riding alongside: the latency victims the
    # prefill budget protects
    for j in range(requests - len(out)):
        i = len(out)
        tenant = f"t{int(rng.integers(0, tenants))}"
        out.append(WorkloadRequest(
            id=f"s{j}", arrival_s=round(float(arrivals[i]), 4),
            tenant=tenant, prompt=_text(rng, turn_len),
            max_tokens=max_tokens,
            priority=(priority_for or {}).get(tenant, 0),
        ))
    out.sort(key=lambda r: (r.arrival_s, r.id))
    return out


def generate(scenario: str, seed: int = 0, *, requests: int = 32,
             tenants: int = 4, turns: int = 4, rate: float = 2.0,
             system_len: int = 256, turn_len: int = 64,
             doc_len: int = 512, query_len: int = 64,
             corpus_docs: int = 6, max_tokens: int = 32,
             think_s: float = 0.2, tiered: bool = False,
             burst: int = 8, long_len: int = 32768,
             agent_turns: int = 6,
             short_fraction: float = 0.5) -> Workload:
    """Build one seed-stable trace. All randomness flows from ONE
    ``np.random.default_rng(seed)`` consumed in a fixed order — adding a
    scenario must never reorder draws inside an existing one.

    ``tiered=True`` stamps every tenant with a named paid/free/batch tier
    (index-derived, zero extra draws) and the matching priority —
    prompts/arrivals stay byte-identical to the untiered trace. The
    ``priority`` scenario is always tiered."""
    rng = np.random.default_rng(seed)
    kw: Dict[str, Any] = {}
    tier_map = {f"t{t}": tier_for_tenant(t, tenants)
                for t in range(tenants)}
    prio_map = {k: TIER_PRIORITY[v] for k, v in tier_map.items()}
    if scenario == "chat":
        reqs = _chat(rng, requests=requests, tenants=tenants, turns=turns,
                     rate=rate, system_len=system_len, turn_len=turn_len,
                     max_tokens=max_tokens, think_s=think_s,
                     priority_for=prio_map if tiered else None)
    elif scenario == "rag":
        reqs = _rag(rng, requests=requests, tenants=tenants, rate=rate,
                    corpus_docs=corpus_docs, doc_len=doc_len,
                    query_len=query_len, max_tokens=max_tokens,
                    priority_for=prio_map if tiered else None)
    elif scenario == "bursty":
        # chat arrivals pushed through per-tenant on/off bursts: each
        # conversation's start is delayed to its tenant's next ON window
        reqs = _chat(rng, requests=requests, tenants=tenants, turns=turns,
                     rate=rate * 2.0, system_len=system_len,
                     turn_len=turn_len, max_tokens=max_tokens,
                     think_s=think_s,
                     priority_for=prio_map if tiered else None)
        period = {f"t{t}": float(rng.uniform(2.0, 6.0))
                  for t in range(tenants)}
        duty = {f"t{t}": float(rng.uniform(0.3, 0.7))
                for t in range(tenants)}
        for r in reqs:
            p, d = period[r.tenant], duty[r.tenant]
            phase = r.arrival_s % p
            if phase > p * d:   # OFF window: shift to the next ON edge
                r.arrival_s = round(r.arrival_s + (p - phase), 4)
        kw["burst_period_s"] = period
    elif scenario == "storm":
        reqs = _storm(rng, requests=requests, tenants=tenants, rate=rate,
                      system_len=system_len, turn_len=turn_len,
                      max_tokens=max_tokens, burst=burst,
                      priority_for=prio_map if tiered else None)
        kw["burst"] = burst
    elif scenario == "priority":
        # named tenant tiers (round 12 — was a two-level 10/0 split):
        # paid over free over batch, per-tenant ids in every trace row
        tiered = True
        reqs = _rag(rng, requests=requests, tenants=tenants, rate=rate,
                    corpus_docs=corpus_docs, doc_len=doc_len,
                    query_len=query_len, max_tokens=max_tokens,
                    priority_for=prio_map)
        kw["priority_tiers"] = prio_map
    elif scenario == "longctx":
        reqs = _longctx(rng, requests=requests, tenants=tenants, rate=rate,
                        long_len=long_len, query_len=query_len,
                        turn_len=turn_len, max_tokens=max_tokens,
                        corpus_docs=max(2, min(corpus_docs, 4)),
                        agent_turns=agent_turns,
                        short_fraction=short_fraction,
                        priority_for=prio_map if tiered else None)
        kw["long_len"] = long_len
        kw["short_fraction"] = short_fraction
    else:
        raise ValueError(
            f"unknown scenario {scenario!r} "
            "(chat | rag | bursty | storm | priority | longctx)"
        )
    if tiered:
        for r in reqs:
            r.tier = tier_map[r.tenant]
        kw["tenant_tiers"] = tier_map
    return Workload(
        scenario=scenario, seed=seed, requests=reqs,
        meta={"requests": len(reqs), "tenants": tenants, "rate": rate,
              "max_tokens": max_tokens, **kw},
    )


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--scenario", default="chat",
                    choices=["chat", "rag", "bursty", "storm", "priority",
                             "longctx"])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--turns", type=int, default=4)
    ap.add_argument("--rate", type=float, default=2.0,
                    help="open-loop arrival rate (req/s or conv/s)")
    ap.add_argument("--system-len", type=int, default=256)
    ap.add_argument("--turn-len", type=int, default=64)
    ap.add_argument("--doc-len", type=int, default=512)
    ap.add_argument("--max-tokens", type=int, default=32)
    ap.add_argument("--burst", type=int, default=8,
                    help="requests per tenant storm (storm scenario)")
    ap.add_argument("--long-len", type=int, default=32768,
                    help="target long-prompt chars (longctx scenario; "
                    "ByteTokenizer: 1 char = 1 token)")
    ap.add_argument("--agent-turns", type=int, default=6,
                    help="turns in the longctx agent trace")
    ap.add_argument("--short-fraction", type=float, default=0.5,
                    help="fraction of longctx requests that are short "
                    "chat traffic (the tail-latency victims)")
    ap.add_argument("--tiered", action="store_true",
                    help="stamp paid/free/batch tenant tiers (+matching "
                    "priorities) onto the trace; arrivals/prompts stay "
                    "byte-identical to the untiered run")
    ap.add_argument("--summary", action="store_true",
                    help="print meta only, not the JSONL trace")
    args = ap.parse_args()
    wl = generate(args.scenario, args.seed, requests=args.requests,
                  tenants=args.tenants, turns=args.turns, rate=args.rate,
                  system_len=args.system_len, turn_len=args.turn_len,
                  doc_len=args.doc_len, max_tokens=args.max_tokens,
                  tiered=args.tiered, burst=args.burst,
                  long_len=args.long_len, agent_turns=args.agent_turns,
                  short_fraction=args.short_fraction)
    if args.summary:
        print(json.dumps({"scenario": wl.scenario, "seed": wl.seed,
                          "duration_s": round(wl.duration_s, 3),
                          **wl.meta}))
    else:
        print(wl.to_jsonl())


if __name__ == "__main__":
    main()
