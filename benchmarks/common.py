"""Shared benchmark helpers: percentile stats, request synthesis, JSON out.

Metric definitions mirror the reference's harnesses (SURVEY §6): tokens/s,
TTFT/E2E p50/p95/p99, prefix-cache hit rate, accept rate — so results are
comparable in kind; unlike the reference's distributed/PD/speculative
benchmarks (analytic simulators), every harness here drives REAL compute.
"""

from __future__ import annotations

import functools
import json
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def add_platform_arg(ap) -> None:
    """Shared --platform flag (all four harnesses)."""
    ap.add_argument(
        "--platform", default=None,
        help="force a jax platform (e.g. cpu) before backend init — a "
        "TPU-tunnel plugin may otherwise pin the default",
    )


def resolve_backend_model(args, tpu_default: str = "llama3-1b",
                          cpu_default: str = "llama3-mini"):
    """Apply --platform, return (backend, model). One implementation so the
    harnesses can't drift on platform/model selection."""
    import jax

    if getattr(args, "platform", None):
        jax.config.update("jax_platforms", args.platform)
    backend = jax.default_backend()
    model = args.model or (tpu_default if backend == "tpu" else cpu_default)
    return backend, model


def percentiles(values: Sequence[float],
                ps=(50, 95, 99)) -> Dict[str, Optional[float]]:
    if not values:
        return {f"p{p}": None for p in ps}
    arr = np.asarray(sorted(values))
    return {f"p{p}": round(float(np.percentile(arr, p)), 2) for p in ps}


def synth_prompts(n: int, prompt_len: int, vocab: int, seed: int = 0,
                  shared_prefix_len: int = 0) -> List[List[int]]:
    """Random prompts, optionally sharing a common prefix (prefix-cache and
    PD benchmarks need realistic system-prompt sharing)."""
    rng = np.random.default_rng(seed)
    shared_prefix_len = min(shared_prefix_len, prompt_len)
    prefix = rng.integers(1, vocab, shared_prefix_len).tolist() \
        if shared_prefix_len else []
    out = []
    for _ in range(n):
        rest = rng.integers(1, vocab, prompt_len - len(prefix)).tolist()
        out.append(prefix + rest)
    return out


def make_request(prompt_token_ids: Sequence[int], max_new_tokens: int):
    """One request shape for every harness (greedy, fixed budget) so the
    four benchmarks cannot drift on sampling config."""
    from distributed_gpu_inference_tpu.utils.data_structures import (
        InferenceRequest,
        SamplingParams,
    )

    return InferenceRequest(
        prompt_token_ids=list(prompt_token_ids),
        sampling=SamplingParams(max_new_tokens=max_new_tokens,
                                temperature=0.0),
    )


def make_chain_sampler(perm, noise: float = 0.05):
    """Reconstructable sampler for the noisy Markov chain a trained toy LM
    models — (perm, noise) fully determine the data distribution, so a
    subprocess-trained model's prompts can be drawn in the parent."""
    import jax
    import jax.numpy as jnp

    perm = jnp.asarray(perm)
    tv = int(perm.shape[0])

    def sample_stream(k, b, s):
        ks = jax.random.split(k, s)
        x0 = jax.random.randint(ks[0], (b,), 0, tv, jnp.int32)

        def step(x, kk):
            k_u, k_r = jax.random.split(kk)
            nxt = perm[x]
            u = jax.random.uniform(k_u, (b,))
            rnd = jax.random.randint(k_r, (b,), 0, tv, jnp.int32)
            x2 = jnp.where(u < noise, rnd, nxt).astype(jnp.int32)
            return x2, x2

        _, xs = jax.lax.scan(step, x0, ks[1:])
        return jnp.concatenate([x0[:, None], xs.T], axis=1)   # [B, S]

    sample_stream.perm = perm
    sample_stream.noise = noise
    return sample_stream


def train_toy_lm(cfg, key, steps: int = 600, batch: int = 16,
                 seq_len: int = 64, lr: float = 3e-3, noise: float = 0.05,
                 optimizer: str = "adam", task_vocab: int = 0):
    """Train a model on a learnable synthetic task so benchmarks that need a
    PREDICTABLE model (speculative decoding) measure real behavior.

    Random-init weights have near-uniform, chaotic logits — no draft can
    match them, so an accept-rate measurement on them says nothing (the
    reference dodges this by SIMULATING accept rates,
    ``benchmarks/speculative.py:123-272``). Here the target is trained on a
    noisy Markov chain (x_{t+1} = perm[x_t] w.p. 1-noise): a task a tiny
    transformer learns to near-ceiling in seconds, giving sharp logits an
    EAGLE head can genuinely be distilled against.

    Returns ``(params_in_model_dtype, sample_stream)`` where
    ``sample_stream(key, batch, seq_len)`` draws token streams from the
    chain (use it for prompts so decode continues in-distribution).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from distributed_gpu_inference_tpu.models import llama

    kp, kperm, kdata = jax.random.split(jax.random.PRNGKey(0) if key is None
                                        else key, 3)
    # large-vocab models (Llama-3's 128k) can't memorize a whole-vocab
    # permutation in a few hundred steps — restrict the chain's state space
    # so every transition is seen many times (the MODEL keeps its full
    # vocab; only the data visits a subset)
    tv = min(task_vocab, cfg.vocab_size) if task_vocab else cfg.vocab_size
    perm = jax.random.permutation(kperm, tv)
    sample_stream = make_chain_sampler(perm, noise)

    bs = 16
    m = -(-seq_len // bs)
    positions = jnp.tile(jnp.arange(seq_len, dtype=jnp.int32), (batch, 1))
    lens = jnp.full((batch,), seq_len, jnp.int32)
    tables = jnp.asarray(
        np.arange(1, 1 + batch * m, dtype=np.int32).reshape(batch, m)
    )
    params = llama.init_params(cfg, kp, jnp.float32)
    # adafactor keeps optimizer state ~free (factored second moments) so a
    # 1B-class model trains in f32 within 16 GB HBM — adam's m+v alone adds
    # 2x param bytes and OOMs there
    opt = optax.adam(lr) if optimizer == "adam" else optax.adafactor(lr)
    opt_state = opt.init(params)

    def loss_fn(params, toks):
        kv = llama.init_kv_pools(cfg, 1 + batch * m, bs, jnp.float32)
        out = llama.forward_chunk(
            cfg, params, toks, positions, kv, tables, lens,
            block_size=bs, last_only=False,
        )
        logp = jax.nn.log_softmax(out.logits[:, :-1].astype(jnp.float32), -1)
        tgt = toks[:, 1:, None]
        return -jnp.mean(jnp.take_along_axis(logp, tgt, axis=-1))

    # the WHOLE training loop is one lax.scan in one jitted call: through a
    # remote TPU tunnel, a host-driven step loop pays dispatch per step and
    # a compile per shape — this compiles once and runs device-side.
    # Donation lets XLA reuse the input param/opt buffers for the outputs:
    # at 1B-scale f32 that halves peak HBM.
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train(params, opt_state):
        def step_fn(carry, step):
            params, opt_state = carry
            toks = sample_stream(
                jax.random.fold_in(kdata, step), batch, seq_len
            )
            loss, grads = jax.value_and_grad(loss_fn)(params, toks)
            # pass params: adafactor's relative scaling requires them
            updates, opt_state = opt.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            step_fn, (params, opt_state), jnp.arange(steps)
        )
        return params, losses

    params, _losses = train(params, opt_state)
    dtype = jnp.dtype(cfg.dtype)
    return jax.tree.map(lambda a: a.astype(dtype), params), sample_stream


# hardware constants the projection artifacts (pipeline_70b, mixtral_ep)
# divide by — shared so the two projections can never model different chips
V5E_HBM_GB = 16.0
ICI_GBPS = 45.0          # v5e per-link ICI, one direction (public spec)


def measure_slice(eng, cfg, batch: int, prompt_len: int,
                  decode_tokens: int):
    """THE measured-input slice probe shared by the projection artifacts
    (pipeline_70b, mixtral_ep): warm the engine, then measure prefill wall
    time and the decode_calls-delta-amortized per-step decode time for one
    layer slice. Keeping it in one place keeps the two artifacts'
    numbers method-comparable. → (prefill_s, step_s)."""
    rng = np.random.default_rng(0)

    def reqs():
        return [
            make_request(
                rng.integers(1, cfg.vocab_size, prompt_len).tolist(),
                decode_tokens,
            )
            for _ in range(batch)
        ]

    warm = reqs()
    for r in warm:
        r.sampling.max_new_tokens = 8
    eng.generate(warm, use_multi_step=True)

    t0 = time.perf_counter()
    eng.submit_batch(reqs())
    t_prefill = time.perf_counter() - t0
    calls0 = eng.stats["decode_calls"]
    t1 = time.perf_counter()
    while any(s is not None and s.finish_reason is None for s in eng.slots):
        eng.decode_multi()
    t_decode = time.perf_counter() - t1
    steps = eng.stats["decode_calls"] - calls0
    for i, s in enumerate(list(eng.slots)):
        if s is not None:
            eng.finish_slot(i, cache=False)
    return t_prefill, t_decode / max(steps, 1)


async def open_loop_drive(batcher, prompts, max_tokens: int, rate: float,
                          seed: int = 11):
    """Drive an OPEN-loop Poisson workload through a started batcher:
    arrivals do not slow down when the server falls behind (the only
    regime where sustained-rate TTFT is a valid SLO statement), and each
    request is CONSTRUCTED at its arrival instant so the engine's TTFT
    clock (slot start_time = request.arrival_time) includes queue wait.

    → (results [(response, e2e_ms)], elapsed_s, last_arrival_s). The ONE
    arrival-process implementation for every serving harness
    (single_worker + speculative) so TTFT semantics cannot drift."""
    import asyncio

    gaps = np.random.default_rng(seed).exponential(1.0 / rate, len(prompts))
    arrivals = np.cumsum(gaps)

    async def one(p, at):
        await asyncio.sleep(float(at))
        t0 = time.perf_counter()
        resp = await batcher.submit(make_request(p, max_tokens))
        return resp, (time.perf_counter() - t0) * 1000.0

    t0 = time.perf_counter()
    results = await asyncio.gather(
        *(one(p, a) for p, a in zip(prompts, arrivals))
    )
    return results, time.perf_counter() - t0, float(arrivals[-1])


def emit(result: Dict[str, Any]) -> None:
    print(json.dumps(result))


class Timer:
    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self.t0
