"""Shared benchmark helpers: percentile stats, request synthesis, JSON out.

Metric definitions mirror the reference's harnesses (SURVEY §6): tokens/s,
TTFT/E2E p50/p95/p99, prefix-cache hit rate, accept rate — so results are
comparable in kind; unlike the reference's distributed/PD/speculative
benchmarks (analytic simulators), every harness here drives REAL compute.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def add_platform_arg(ap) -> None:
    """Shared --platform flag (all four harnesses)."""
    ap.add_argument(
        "--platform", default=None,
        help="force a jax platform (e.g. cpu) before backend init — a "
        "TPU-tunnel plugin may otherwise pin the default",
    )


def resolve_backend_model(args, tpu_default: str = "llama3-1b",
                          cpu_default: str = "llama3-mini"):
    """Apply --platform, return (backend, model). One implementation so the
    harnesses can't drift on platform/model selection."""
    import jax

    if getattr(args, "platform", None):
        jax.config.update("jax_platforms", args.platform)
    backend = jax.default_backend()
    model = args.model or (tpu_default if backend == "tpu" else cpu_default)
    return backend, model


def percentiles(values: Sequence[float],
                ps=(50, 95, 99)) -> Dict[str, Optional[float]]:
    if not values:
        return {f"p{p}": None for p in ps}
    arr = np.asarray(sorted(values))
    return {f"p{p}": round(float(np.percentile(arr, p)), 2) for p in ps}


def synth_prompts(n: int, prompt_len: int, vocab: int, seed: int = 0,
                  shared_prefix_len: int = 0) -> List[List[int]]:
    """Random prompts, optionally sharing a common prefix (prefix-cache and
    PD benchmarks need realistic system-prompt sharing)."""
    rng = np.random.default_rng(seed)
    shared_prefix_len = min(shared_prefix_len, prompt_len)
    prefix = rng.integers(1, vocab, shared_prefix_len).tolist() \
        if shared_prefix_len else []
    out = []
    for _ in range(n):
        rest = rng.integers(1, vocab, prompt_len - len(prefix)).tolist()
        out.append(prefix + rest)
    return out


def make_request(prompt_token_ids: Sequence[int], max_new_tokens: int):
    """One request shape for every harness (greedy, fixed budget) so the
    four benchmarks cannot drift on sampling config."""
    from distributed_gpu_inference_tpu.utils.data_structures import (
        InferenceRequest,
        SamplingParams,
    )

    return InferenceRequest(
        prompt_token_ids=list(prompt_token_ids),
        sampling=SamplingParams(max_new_tokens=max_new_tokens,
                                temperature=0.0),
    )


def emit(result: Dict[str, Any]) -> None:
    print(json.dumps(result))


class Timer:
    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self.t0
