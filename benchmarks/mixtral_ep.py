#!/usr/bin/env python
"""Mixtral-8x7B expert-parallel artifact: measured per-device cost → 8-chip
projection (VERDICT r4 #5).

Beyond-reference scope — SURVEY §2.2 lists MoE/EP as ABSENT even upstream —
so the bar is the same measured-grounded method as ``pipeline_70b.py``
(BASELINE config 4's artifact): every input to the projection is a real
measurement on the target silicon, nothing simulated.

The EP design (``parallel/sharding.py``, ``models/llama.py _moe_mlp``):
expert weights shard their E axis over ``model`` alongside the attention
heads; each device computes its LOCAL expert(s) for ALL tokens and XLA
all-reduces the top-k combine. On an 8-device mesh each chip therefore
holds exactly the "per-device width" of Mixtral-8x7B:

- 1 of 8 experts per layer (the dominant bytes: ~176 MB int8 each),
- 4 of 32 query heads and 1 of 8 KV heads (head_dim 128),
- the replicated router / norms / embeddings.

1. **Per-device per-layer cost, real chip**: build TWO engines at exactly
   that width (num_experts=1, top-1, heads 4/1, head_dim 128 — wq/wk/wo
   and the expert mats are byte-identical to one chip's shard) with
   different layer counts; the timing difference isolates per-layer cost
   from embed/head ends, as in pipeline_70b.
2. **HBM fit, arithmetic from the same config**: 32 layers x (expert +
   attention shard) int8 + replicated bf16 embeddings + KV pool shard.
3. **Projection**: decode step = 32 x per-device layer cost + the
   per-layer combine all-reduces bounded from activation bytes over ICI.
   The EP schedule itself executes for real on the 8-device virtual mesh
   (``__graft_entry__._dryrun_moe_expert_parallel``: mixtral-tiny
   expert-sharded serve step, bit-exact vs single-device) and at engine
   level in ``tests/test_model_moe.py``.

Usage:
    python -m benchmarks.mixtral_ep --layers 2,6 --batch 16
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


from benchmarks.common import (
    ICI_GBPS,
    V5E_HBM_GB,
    add_platform_arg,
    emit,
    measure_slice,
)

N_DEVICES = 8


def _per_device_cfg(base, n_layers: int):
    """Mixtral-8x7B's exact per-device shard width as a standalone config:
    the E/heads slices one chip of an 8-way ``model`` mesh owns."""
    return dataclasses.replace(
        base,
        name=f"mixtral-ep-slice{n_layers}",
        num_layers=n_layers,
        num_experts=1,
        num_experts_per_tok=1,
        num_heads=base.num_heads // N_DEVICES,        # 4
        num_kv_heads=base.num_kv_heads // N_DEVICES,  # 1
        head_dim=base.head_dim,                       # keep 128
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", default="2,6",
                    help="two slice depths; the difference isolates "
                         "per-layer cost")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=512)
    ap.add_argument("--decode-tokens", type=int, default=64)
    ap.add_argument("--quantization", default="int8")
    add_platform_arg(ap)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    backend = jax.default_backend()

    from distributed_gpu_inference_tpu.models.configs import get_model_config
    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )

    base = get_model_config("mixtral-8x7b")
    l_lo, l_hi = (int(x) for x in args.layers.split(","))
    max_seq = args.prompt_len + args.decode_tokens + 32

    measured = {}
    for n in (l_lo, l_hi):
        cfg = _per_device_cfg(base, n)
        eng = TPUEngine(
            cfg,
            EngineConfig(
                max_batch_size=args.batch, max_seq_len=max_seq,
                block_size=32, prefill_buckets=(args.prompt_len,),
                enable_prefix_cache=False,
                quantization=args.quantization,
            ),
        )
        t_prefill, t_step = measure_slice(
            eng, cfg, args.batch, args.prompt_len, args.decode_tokens
        )
        measured[n] = {"prefill_s": round(t_prefill, 3),
                       "decode_step_ms": round(t_step * 1e3, 2)}
        del eng
        import gc

        gc.collect()
        if n != l_hi and backend == "tpu":
            # lazy tunnel HBM reclaim between slice engines (same gap as
            # pipeline_70b.py / benchmarks/speculative.py)
            time.sleep(45.0)

    d_layers = l_hi - l_lo
    per_layer_decode_ms = (
        measured[l_hi]["decode_step_ms"] - measured[l_lo]["decode_step_ms"]
    ) / d_layers
    per_layer_prefill_s = (
        measured[l_hi]["prefill_s"] - measured[l_lo]["prefill_s"]
    ) / d_layers
    ends_decode_ms = (
        measured[l_lo]["decode_step_ms"] - l_lo * per_layer_decode_ms
    )

    # ---- per-device HBM fit (int8 weights) ----
    # expert mats: 3 x hidden x intermediate per expert, 1 expert/device
    expert_bytes = 3 * base.hidden_size * base.intermediate_size
    # attention shard: wq 4 heads + wk/wv 1 kv head + wo, all x128
    attn_bytes = base.hidden_size * base.head_dim * (
        base.num_heads // N_DEVICES * 2          # wq + wo
        + base.num_kv_heads // N_DEVICES * 2     # wk + wv
    )
    router_bytes = base.hidden_size * base.num_experts   # replicated, f32/4
    layer_dev_bytes = expert_bytes + attn_bytes + router_bytes
    embed_bytes = base.vocab_size * base.hidden_size * 2   # bf16, replicated
    head_bytes = embed_bytes                               # untied
    ctx = 4096
    kv_dev_bytes = (
        args.batch * ctx * (base.num_kv_heads // N_DEVICES) * base.head_dim
        * 2 * 2 * base.num_layers
    )
    dev_gb = (
        base.num_layers * layer_dev_bytes + embed_bytes + head_bytes
        + kv_dev_bytes
    ) / 1e9

    # ---- projection: 8-way EP decode ----
    # two all-reduces per layer ([T, H] combine + attention wo), bf16
    ar_bytes = 2 * args.batch * base.hidden_size * 2
    # ring all-reduce moves ~2x the payload over the slowest link
    ar_ms = (2 * ar_bytes) / (ICI_GBPS * 1e9) * 1e3
    step_ms = base.num_layers * (per_layer_decode_ms + ar_ms) \
        + ends_decode_ms
    proj_decode_tps = args.batch / (step_ms / 1e3)
    prefill_s = base.num_layers * per_layer_prefill_s

    emit({
        "benchmark": "mixtral_ep",
        "metric": "projected_mixtral8x7b_8chip_decode_tokens_per_s",
        "value": round(proj_decode_tps, 1),
        "unit": "tokens/s (measured-grounded projection)",
        "backend": backend,
        "quantization": args.quantization,
        "batch": args.batch,
        "prompt_len": args.prompt_len,
        "measured_slices": measured,
        "per_layer_decode_ms": round(per_layer_decode_ms, 3),
        "per_layer_prefill_s": round(per_layer_prefill_s, 4),
        "ends_decode_ms": round(ends_decode_ms, 2),
        "projection": {
            "devices": N_DEVICES,
            "experts_per_device": 1,
            "allreduce_ms_per_layer": round(ar_ms, 4),
            "decode_step_ms": round(step_ms, 2),
            "decode_tokens_per_s": round(proj_decode_tps, 1),
            "prefill_s_512_batch": round(prefill_s, 2),
        },
        "hbm_fit": {
            "expert_bytes_int8_mb": round(expert_bytes / 1e6, 1),
            "layer_dev_bytes_int8_mb": round(layer_dev_bytes / 1e6, 1),
            "per_device_gb": round(dev_gb, 2),
            "v5e_hbm_gb": V5E_HBM_GB,
            "fits": dev_gb < V5E_HBM_GB,
            "kv_note": f"KV pool shard: batch {args.batch} x {ctx} ctx "
                       "bf16, 1/8 of the KV heads",
        },
        "schedule_validation": "__graft_entry__ dryrun regime 7 "
                               "(mixtral-tiny EP serve step, bit-exact vs "
                               "single-device) + tests/test_model_moe.py",
    })


if __name__ == "__main__":
    main()
