#!/usr/bin/env python
"""70B pipeline artifact: measured per-layer cost → 8-chip projection.

BASELINE config 4 (Llama-3-70B layer-sharded across 8 chips) cannot be
MEASURED end-to-end on one tunneled chip, but it can be measured-grounded
(VERDICT r3 #5): every input to the projection is a real measurement.

1. **Per-layer cost, real chip**: build TWO int8 engines at true 70B layer
   width (hidden 8192, GQA 64/8, intermediate 28672) with different layer
   counts; the timing DIFFERENCE isolates pure per-layer decode/prefill
   cost from the embed/head ends — the same subtraction a pipeline's
   middle stages experience.
2. **HBM fit, arithmetic from the same config**: per-stage bytes at 80/8 =
   10 layers/stage int8 + bf16 embed (stage 0) / LM head (stage 7) + the
   KV pool a serving batch needs.
3. **Projection**: steady-state pipeline decode tokens/s = microbatch
   size / bottleneck-stage step time, with the ICI hop cost bounded from
   the activation bytes ([B, 8192] bf16 per hop). The ppermute schedule
   itself is validated for real on an 8-device virtual mesh at the same
   layer geometry (``benchmarks/distributed.py --mode spmd --model
   llama3-70b-micro``).

The reference's version of this benchmark simulates 10 ms/layer and a
synthetic 10 Gbps link (``/root/reference/benchmarks/distributed.py:
128-171``); here nothing is simulated — per-layer times are measured on
the target silicon at the target width.

Usage:
    python -m benchmarks.pipeline_70b --layers 4,8 --batch 8
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from benchmarks.common import (
    ICI_GBPS,
    V5E_HBM_GB,
    add_platform_arg,
    emit,
    measure_slice,
)

def _mk_slice_engine(cfg70, n_layers, args, quant):
    from distributed_gpu_inference_tpu.models.loader import (
        init_quantized_streamed,
    )
    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )

    cfg = dataclasses.replace(cfg70, name=f"llama3-70b-slice{n_layers}",
                              num_layers=n_layers)
    max_seq = args.prompt_len + args.decode_tokens + 32
    # ALWAYS stream-init quantized: a 4-layer 70B-width slice is ~11 GB
    # bf16 — the engine's full-precision-then-consume path nominally fits,
    # but the tunnel frees the consumed bf16 leaves lazily and the
    # follow-on prefill OOMs (observed this round). Streamed init peaks at
    # the int8 tree + one f32 layer slice.
    params = (
        init_quantized_streamed(cfg, quant, dtype="bfloat16", seed=0)
        if quant else None
    )
    # no quant_cache_dir: explicit params bypass the engine's orbax cache
    # entirely (it only applies to engine-built trees), and the streamed
    # init IS the fast path for random-init weights (~30 s incl. compiles)
    return TPUEngine(
        cfg,
        EngineConfig(
            max_batch_size=args.batch, max_seq_len=max_seq, block_size=32,
            prefill_buckets=(args.prompt_len,), enable_prefix_cache=False,
            quantization=quant,
        ),
        params=params,
    ), cfg

def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", default="4,8",
                    help="two slice depths; the difference isolates "
                         "per-layer cost")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=512)
    ap.add_argument("--decode-tokens", type=int, default=64)
    ap.add_argument("--stages", type=int, default=8)
    ap.add_argument("--quantization", default="int8")
    add_platform_arg(ap)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    backend = jax.default_backend()

    from distributed_gpu_inference_tpu.models.configs import get_model_config

    cfg70 = get_model_config("llama3-70b")
    l_lo, l_hi = (int(x) for x in args.layers.split(","))

    measured = {}
    for n in (l_lo, l_hi):
        eng, cfg = _mk_slice_engine(cfg70, n, args, args.quantization)
        t_prefill, t_step = measure_slice(
            eng, cfg, args.batch, args.prompt_len, args.decode_tokens
        )
        measured[n] = {"prefill_s": round(t_prefill, 3),
                       "decode_step_ms": round(t_step * 1e3, 2)}
        del eng
        import gc

        gc.collect()
        if n != l_hi:
            # the tunnel reclaims a freed engine's HBM lazily; give it time
            # before the NEXT slice allocates ~11 GB (same trap as the
            # benchmarks/speculative.py subprocess gap). Nothing follows
            # the last slice, so no sleep there.
            time.sleep(45.0)

    # per-layer cost from the slice DIFFERENCE (embed/head cancel)
    d_layers = l_hi - l_lo
    per_layer_decode_ms = (
        measured[l_hi]["decode_step_ms"] - measured[l_lo]["decode_step_ms"]
    ) / d_layers
    per_layer_prefill_s = (
        measured[l_hi]["prefill_s"] - measured[l_lo]["prefill_s"]
    ) / d_layers
    # what's left of the lo-slice after removing its layers ≈ embed+head+
    # dispatch overhead (the ends of the pipeline + per-call cost)
    ends_decode_ms = (
        measured[l_lo]["decode_step_ms"] - l_lo * per_layer_decode_ms
    )

    # ---- per-stage HBM fit (80 layers / stages), int8 weights ----
    layers_per_stage = cfg70.num_layers // args.stages
    layer_bytes_int8 = cfg70.layer_param_bytes(1)
    embed_bytes = cfg70.vocab_size * cfg70.hidden_size * 2      # bf16
    head_bytes = embed_bytes                                     # untied
    # serving KV pool per stage: batch x ctx 8k, GQA 8 heads x 128, bf16,
    # only this stage's layers
    ctx = 8192
    kv_stage_bytes = (
        args.batch * ctx * cfg70.num_kv_heads * cfg70.head_dim * 2 * 2
        * layers_per_stage
    )
    stage_mid_gb = (layers_per_stage * layer_bytes_int8 + kv_stage_bytes) / 1e9
    stage_end_gb = stage_mid_gb + max(embed_bytes, head_bytes) / 1e9

    # ---- projection: steady-state pipeline decode ----
    # bottleneck stage = 10 layers + the head end (stage 7); hop = [B, 8192]
    # bf16 per microbatch over ICI
    hop_ms = (args.batch * cfg70.hidden_size * 2) / (ICI_GBPS * 1e9) * 1e3
    stage_ms = layers_per_stage * per_layer_decode_ms + hop_ms
    stage_end_ms = stage_ms + ends_decode_ms        # head-bearing stage
    bottleneck_ms = max(stage_ms, stage_end_ms)
    # pipeline full (microbatches >= stages): one microbatch of B tokens
    # emerges per bottleneck step
    proj_decode_tps = args.batch / (bottleneck_ms / 1e3)
    # per-token latency = sum of stage times
    token_latency_ms = args.stages * stage_ms + ends_decode_ms

    emit({
        "benchmark": "pipeline_70b",
        "metric": "projected_70b_8chip_decode_tokens_per_s",
        "value": round(proj_decode_tps, 1),
        "unit": "tokens/s (measured-grounded projection)",
        "backend": backend,
        "quantization": args.quantization,
        "batch": args.batch,
        "prompt_len": args.prompt_len,
        "measured_slices": measured,
        "per_layer_decode_ms": round(per_layer_decode_ms, 3),
        "per_layer_prefill_s": round(per_layer_prefill_s, 4),
        "ends_decode_ms": round(ends_decode_ms, 2),
        "projection": {
            "stages": args.stages,
            "layers_per_stage": layers_per_stage,
            "hop_ms_per_microbatch": round(hop_ms, 4),
            "stage_ms_mid": round(stage_ms, 2),
            "stage_ms_head_end": round(stage_end_ms, 2),
            "decode_tokens_per_s": round(proj_decode_tps, 1),
            "token_latency_ms": round(token_latency_ms, 1),
            "prefill_s_512_batch": round(
                args.stages * layers_per_stage * per_layer_prefill_s, 2
            ),
        },
        "hbm_fit": {
            "layer_bytes_int8_gb": round(layer_bytes_int8 / 1e9, 3),
            "stage_mid_gb": round(stage_mid_gb, 2),
            "stage_end_gb": round(stage_end_gb, 2),
            "v5e_hbm_gb": V5E_HBM_GB,
            "fits": stage_end_gb < V5E_HBM_GB,
            "kv_note": f"KV pool: batch {args.batch} x {ctx} ctx bf16, "
                       f"per-stage layers only",
        },
        "schedule_validation": "benchmarks/distributed.py --mode spmd "
                               "--model llama3-70b-micro (8-dev virtual "
                               "mesh, real ppermute microbatch schedule at "
                               "70B layer width)",
    })

if __name__ == "__main__":
    main()
