#!/usr/bin/env python
"""Single-worker serving benchmark: real engine, real tokens.

Parity with ``benchmarks/single_worker.py`` in the reference (the only
reference harness that drives real engines): decode tokens/s, TTFT and E2E
p50/p95/p99, prefix-cache hit rate — measured over the continuous batcher
at a given concurrency (reference defaults: 100 requests, 8 concurrent,
256 max_tokens, :76-97).

Usage:
    python -m benchmarks.single_worker --model llama3-mini --requests 32 \
        --concurrency 8 --prompt-len 128 --max-tokens 64
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from benchmarks.common import (
    Timer,
    add_platform_arg,
    emit,
    make_request,
    percentiles,
    resolve_backend_model,
    synth_prompts,
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--shared-prefix", type=int, default=64,
                    help="tokens of shared system prefix (prefix-cache hits)")
    ap.add_argument("--no-prefix-cache", action="store_true")
    ap.add_argument("--target-step-ms", type=float, default=400.0,
                    help="batcher round-latency target; must exceed the "
                    "host↔device round-trip or the adaptive horizon "
                    "collapses to 1 step (≈110 ms through a TPU tunnel)")
    # -- open-loop SLO mode (VERDICT r4 #3: publish a TTFT-SLO frontier) --
    ap.add_argument("--arrival-rate", default=None,
                    help="OPEN-loop mode: Poisson arrivals at this req/s "
                    "(seeded), no concurrency gate — TTFT then includes "
                    "queue wait, which is what an SLO means. "
                    "--concurrency still sizes the engine's slot count. "
                    "Comma-separated rates sweep a frontier on ONE "
                    "engine (one line per rate; 8B engine init through "
                    "the tunnel costs minutes, the sweep pays it once)")
    ap.add_argument("--seed", type=int, default=7, help="arrival-process seed")
    ap.add_argument("--quantization", default=None,
                    help="weight quantization (e.g. int8 — the 8B flagship "
                    "needs it to fit a 16 GB chip)")
    ap.add_argument("--kv-dtype", default=None, help="kv_cache_dtype")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--subwave", type=int, default=0,
                    help="admission sub-wave width (engine admission_subwave)")
    ap.add_argument("--interleave", type=int, default=0,
                    help="decode steps interleaved between admission "
                    "sub-waves/chunks (engine admission_interleave_steps)")
    ap.add_argument("--max-horizon", type=int, default=64,
                    help="cap the adaptive decode horizon (batcher "
                    "max_multi_step): an SLO config bounds the longest "
                    "admission stall to max_horizon x step, trading "
                    "peak decode throughput for TTFT")
    add_platform_arg(ap)
    args = ap.parse_args()

    import jax

    backend, model = resolve_backend_model(args)

    from distributed_gpu_inference_tpu.runtime.batcher import (
        BatcherConfig,
        ContinuousBatcher,
    )
    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )
    max_seq = args.prompt_len + args.max_tokens + 16
    eng = TPUEngine(
        model,
        EngineConfig(
            max_batch_size=args.concurrency,
            max_seq_len=max_seq,
            block_size=args.block_size,
            prefill_buckets=(args.prompt_len,),
            enable_prefix_cache=not args.no_prefix_cache,
            quantization=args.quantization,
            kv_cache_dtype=args.kv_dtype,
            admission_subwave=args.subwave,
            admission_interleave_steps=args.interleave,
        ),
    )
    prompts = synth_prompts(
        args.requests, args.prompt_len, eng.model_cfg.vocab_size,
        shared_prefix_len=args.shared_prefix,
    )

    def req(p):
        return make_request(p, args.max_tokens)

    # warmup compile: prefill bucket + EVERY decode-horizon graph the
    # batcher may request (each distinct scan length T is its own XLA
    # compile — they must not land mid-measurement). Warm with a prompt
    # OUTSIDE the measured set (and cache=False) so the warmup neither
    # pre-warms the prefix cache for a measured prompt nor skews the
    # reported hit rate.
    bcfg = BatcherConfig(default_timeout_s=600.0,
                         target_step_latency_ms=args.target_step_ms,
                         max_multi_step=args.max_horizon)
    warm_prompt = synth_prompts(
        1, args.prompt_len, eng.model_cfg.vocab_size, seed=987,
        shared_prefix_len=0,
    )[0]
    eng.generate([make_request(warm_prompt, 2)])
    if args.subwave > 0:
        # each power-of-2 sub-wave width is its own narrow prefill graph:
        # _prefill_subwave buckets a chunk of k<=subwave requests to the
        # next power of 2 CLAMPED to the slot count — warm exactly that
        # set (e.g. subwave 6 can produce a width-8 graph; concurrency 6
        # clamps it to width 6)
        w = 1
        while True:
            width = min(w, args.concurrency)
            eng.generate(
                [make_request(warm_prompt, 2) for _ in range(width)]
            )
            if w >= args.subwave or width == args.concurrency:
                break
            w *= 2
    for T in bcfg.horizon_levels:
        # 2 tokens suffice: on-device budgets finish the slot inside the
        # T-step scan, and the T graph still compiles
        slot = eng.submit(make_request(warm_prompt, 2))
        while eng.slots[slot] is not None and \
                eng.slots[slot].finish_reason is None:
            eng.decode_multi(T)
        eng.finish_slot(slot, cache=False)
    # counters accumulated by warmup must not enter the report
    eng.manager.stats.prefix_queries = 0
    eng.manager.stats.prefix_hit_tokens = 0
    eng.manager.stats.prefix_total_tokens = 0

    async def run(rate):
        batcher = ContinuousBatcher(eng, bcfg)
        batcher.start()
        results = []

        if rate:
            # open loop via the shared driver (benchmarks/common.py
            # open_loop_drive — the one arrival-process implementation)
            from benchmarks.common import open_loop_drive

            results, elapsed, span = await open_loop_drive(
                batcher, prompts, args.max_tokens, rate, seed=args.seed
            )
            stats_snap = batcher.get_stats()
            await batcher.stop()
            return results, elapsed, span, stats_snap
        else:
            sem = asyncio.Semaphore(args.concurrency)

            async def one(p):
                async with sem:
                    t0 = time.perf_counter()
                    resp = await batcher.submit(req(p))
                    return resp, (time.perf_counter() - t0) * 1000.0

            with Timer() as t:
                results = await asyncio.gather(*(one(p) for p in prompts))
        await batcher.stop()
        return results, t.elapsed, 0.0, batcher.get_stats()

    rates = (
        [float(r) for r in str(args.arrival_rate).split(",")]
        if args.arrival_rate else [None]
    )
    for i, rate in enumerate(rates):
        if i > 0:
            # each rate must measure the same COLD state the first did:
            # drop blocks the previous rate's requests left in the prefix
            # cache (identical prompts would otherwise prefill as cache
            # hits from rate 2 on) and re-zero the per-rate counters
            eng.manager.clear_cached()
            eng.manager.stats.prefix_queries = 0
            eng.manager.stats.prefix_hit_tokens = 0
            eng.manager.stats.prefix_total_tokens = 0
        results, elapsed, arrival_span, last_batcher_stats = \
            asyncio.run(run(rate))
        resps = [r for r, _ in results]
        e2es = [ms for _, ms in results]
        ok = [r for r in resps if r.error is None]
        decoded = sum(r.completion_tokens for r in ok)
        ttfts = [r.ttft_ms for r in ok if r.ttft_ms is not None]
        stats = eng.get_stats()

        out = {
            "benchmark": "single_worker",
            "metric": "decode_tokens_per_s",
            "value": round(decoded / elapsed, 2),
            "unit": "tokens/s",
            "model": model,
            "backend": backend,
            "requests": args.requests,
            "ok": len(ok),
            "concurrency": args.concurrency,
            "prompt_len": args.prompt_len,
            "max_tokens": args.max_tokens,
            "elapsed_s": round(elapsed, 3),
            "requests_per_s": round(len(ok) / elapsed, 3),
            "ttft_ms": percentiles(ttfts),
            "e2e_ms": percentiles(e2es),
            "prefix_hit_rate": round(
                stats["kv_cache"].get("prefix_hit_rate", 0.0), 4
            ),
        }
        if rate:
            tpots = [
                (ms - r.ttft_ms) / (r.completion_tokens - 1)
                for r, ms in results
                if r.error is None and r.ttft_ms is not None
                and r.completion_tokens > 1
            ]
            b = last_batcher_stats
            out.update({
                "mode": "open_loop",
                "arrival_rate_rps": rate,
                "batcher": {
                    "decode_rounds": b.get("decode_rounds"),
                    "avg_occupancy": round(b.get("avg_occupancy", 0.0), 2),
                    "horizon": b.get("horizon"),
                    "step_latency_ema_ms": round(
                        b.get("step_latency_ema_ms", 0.0), 1
                    ),
                    "chunked_admissions": b.get("chunked_admissions"),
                    "batched_waves": b.get("batched_waves"),
                },
                # sustained = the server kept up with the offered load:
                # the run finishes within ~one service time of the last
                # arrival, i.e. the queue was not growing without bound
                "offered_span_s": round(float(arrival_span), 3),
                "drain_s": round(elapsed - float(arrival_span), 3),
                "tpot_ms": percentiles(tpots),
                "quantization": args.quantization,
                "kv_cache_dtype": args.kv_dtype,
                "interleave": args.interleave,
                "subwave": args.subwave,
                "target_step_ms": args.target_step_ms,
            })
        emit(out)


if __name__ == "__main__":
    main()
