#!/usr/bin/env python
"""Single-worker serving benchmark: real engine, real tokens.

Parity with ``benchmarks/single_worker.py`` in the reference (the only
reference harness that drives real engines): decode tokens/s, TTFT and E2E
p50/p95/p99, prefix-cache hit rate — measured over the continuous batcher
at a given concurrency (reference defaults: 100 requests, 8 concurrent,
256 max_tokens, :76-97).

Usage:
    python -m benchmarks.single_worker --model llama3-mini --requests 32 \
        --concurrency 8 --prompt-len 128 --max-tokens 64
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from benchmarks.common import (
    Timer,
    add_platform_arg,
    emit,
    make_request,
    percentiles,
    resolve_backend_model,
    synth_prompts,
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--shared-prefix", type=int, default=64,
                    help="tokens of shared system prefix (prefix-cache hits)")
    ap.add_argument("--no-prefix-cache", action="store_true")
    ap.add_argument("--target-step-ms", type=float, default=400.0,
                    help="batcher round-latency target; must exceed the "
                    "host↔device round-trip or the adaptive horizon "
                    "collapses to 1 step (≈110 ms through a TPU tunnel)")
    add_platform_arg(ap)
    args = ap.parse_args()

    import jax

    backend, model = resolve_backend_model(args)

    from distributed_gpu_inference_tpu.runtime.batcher import (
        BatcherConfig,
        ContinuousBatcher,
    )
    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )
    max_seq = args.prompt_len + args.max_tokens + 16
    eng = TPUEngine(
        model,
        EngineConfig(
            max_batch_size=args.concurrency,
            max_seq_len=max_seq,
            prefill_buckets=(args.prompt_len,),
            enable_prefix_cache=not args.no_prefix_cache,
        ),
    )
    prompts = synth_prompts(
        args.requests, args.prompt_len, eng.model_cfg.vocab_size,
        shared_prefix_len=args.shared_prefix,
    )

    def req(p):
        return make_request(p, args.max_tokens)

    # warmup compile: prefill bucket + EVERY decode-horizon graph the
    # batcher may request (each distinct scan length T is its own XLA
    # compile — they must not land mid-measurement). Warm with a prompt
    # OUTSIDE the measured set (and cache=False) so the warmup neither
    # pre-warms the prefix cache for a measured prompt nor skews the
    # reported hit rate.
    bcfg = BatcherConfig(default_timeout_s=600.0,
                         target_step_latency_ms=args.target_step_ms)
    warm_prompt = synth_prompts(
        1, args.prompt_len, eng.model_cfg.vocab_size, seed=987,
        shared_prefix_len=0,
    )[0]
    eng.generate([make_request(warm_prompt, 2)])
    for T in bcfg.horizon_levels:
        # 2 tokens suffice: on-device budgets finish the slot inside the
        # T-step scan, and the T graph still compiles
        slot = eng.submit(make_request(warm_prompt, 2))
        while eng.slots[slot] is not None and \
                eng.slots[slot].finish_reason is None:
            eng.decode_multi(T)
        eng.finish_slot(slot, cache=False)
    # counters accumulated by warmup must not enter the report
    eng.manager.stats.prefix_queries = 0
    eng.manager.stats.prefix_hit_tokens = 0
    eng.manager.stats.prefix_total_tokens = 0

    async def run():
        batcher = ContinuousBatcher(eng, bcfg)
        batcher.start()
        sem = asyncio.Semaphore(args.concurrency)
        results = []

        async def one(p):
            async with sem:
                t0 = time.perf_counter()
                resp = await batcher.submit(req(p))
                return resp, (time.perf_counter() - t0) * 1000.0

        with Timer() as t:
            results = await asyncio.gather(*(one(p) for p in prompts))
        await batcher.stop()
        return results, t.elapsed

    results, elapsed = asyncio.run(run())
    resps = [r for r, _ in results]
    e2es = [ms for _, ms in results]
    ok = [r for r in resps if r.error is None]
    decoded = sum(r.completion_tokens for r in ok)
    ttfts = [r.ttft_ms for r in ok if r.ttft_ms is not None]
    stats = eng.get_stats()

    emit({
        "benchmark": "single_worker",
        "metric": "decode_tokens_per_s",
        "value": round(decoded / elapsed, 2),
        "unit": "tokens/s",
        "model": model,
        "backend": backend,
        "requests": args.requests,
        "ok": len(ok),
        "concurrency": args.concurrency,
        "prompt_len": args.prompt_len,
        "max_tokens": args.max_tokens,
        "elapsed_s": round(elapsed, 3),
        "requests_per_s": round(len(ok) / elapsed, 3),
        "ttft_ms": percentiles(ttfts),
        "e2e_ms": percentiles(e2es),
        "prefix_hit_rate": round(
            stats["kv_cache"].get("prefix_hit_rate", 0.0), 4
        ),
    })


if __name__ == "__main__":
    main()
