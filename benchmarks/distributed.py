#!/usr/bin/env python
"""Distributed pipeline benchmark: layer-sharded serving, real compute.

Parity with the reference's ``benchmarks/distributed.py`` metrics (pipeline
tokens/s, per-hop latency) — the reference SIMULATES the pipeline (10 ms per
layer, synthetic 10 Gbps transfers, :128-160); here both modes run the real
thing:

- ``--mode http``: N real stage workers over loopback HTTP with binary
  framing (the cross-host path, ``comm/``), greedy decode of one stream.
- ``--mode spmd``: the in-mesh SPMD pipeline (``parallel/pipeline.py``) over
  a device mesh — hops are ICI ppermutes inside one jitted graph. Needs
  multiple devices (run under XLA_FLAGS=--xla_force_host_platform_device_count=N
  JAX_PLATFORMS=cpu for a virtual mesh).

Usage:
    python -m benchmarks.distributed --mode http --stages 2 --max-tokens 32
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import (
    Timer,
    add_platform_arg,
    emit,
    percentiles,
    resolve_backend_model,
    synth_prompts,
)


def run_http(args) -> None:
    import jax

    from distributed_gpu_inference_tpu.comm.data_plane import DataPlaneServer
    from distributed_gpu_inference_tpu.comm.session import (
        DistributedInferenceSession,
        WorkerSession,
    )
    from distributed_gpu_inference_tpu.comm.stage_worker import (
        PipelineStageWorker,
    )
    from distributed_gpu_inference_tpu.models import llama
    from distributed_gpu_inference_tpu.models.configs import get_model_config
    from distributed_gpu_inference_tpu.parallel.pipeline import uniform_stages
    from distributed_gpu_inference_tpu.utils.data_structures import (
        BlockRange,
        SessionConfig,
    )

    backend, model = resolve_backend_model(args)
    cfg = get_model_config(model)
    full = llama.init_params(cfg, jax.random.PRNGKey(0), "float32")
    ranges = uniform_stages(cfg.num_layers, args.stages)
    max_len = args.prompt_len + args.max_tokens + 16

    servers = []
    for rng in ranges:
        st = PipelineStageWorker(
            model, rng, full_params=full,
            num_blocks=4 * (max_len // 16 + 2),
            max_blocks_per_seq=max_len // 16 + 2, dtype="float32",
        )
        srv = DataPlaneServer(st, host="127.0.0.1", port=0)
        srv.start()
        servers.append(srv)
    route = [
        WorkerSession(f"http://127.0.0.1:{s.bound_port}", BlockRange(*r),
                      timeout_s=300.0)
        for s, r in zip(servers, ranges)
    ]
    sess = DistributedInferenceSession(
        route, SessionConfig(max_length=max_len)
    )
    sess.setup()
    prompt = synth_prompts(1, args.prompt_len, cfg.vocab_size)[0]

    # warmup: compile prefill + decode shapes on every stage
    sess.step(np.asarray(prompt, np.int32)[None, :])
    sess.step(np.asarray([[1]], np.int32))

    sess2 = DistributedInferenceSession(
        route, SessionConfig(max_length=max_len)
    )
    sess2.setup()
    hop_ms = []
    with Timer() as t:
        t0 = time.perf_counter()
        logits = sess2.step(np.asarray(prompt, np.int32)[None, :])
        ttft_ms = (time.perf_counter() - t0) * 1000.0
        tok = int(np.argmax(logits[0, -1]))
        decoded = 0
        for _ in range(args.max_tokens - 1):
            h0 = time.perf_counter()
            logits = sess2.step(np.asarray([[tok]], np.int32))
            hop_ms.append((time.perf_counter() - h0) * 1000.0)
            tok = int(np.argmax(logits[0, -1]))
            decoded += 1
    sess2.close()
    sess.close()
    for s in servers:
        s.stop()

    emit({
        "benchmark": "distributed_pipeline",
        "mode": "http",
        "metric": "pipeline_decode_tokens_per_s",
        "value": round(decoded / sum(hop_ms) * 1000.0, 2) if hop_ms else None,
        "unit": "tokens/s",
        "model": model,
        "backend": backend,
        "stages": args.stages,
        "prompt_len": args.prompt_len,
        "ttft_ms": round(ttft_ms, 1),
        "step_ms": percentiles(hop_ms),
        "elapsed_s": round(t.elapsed, 3),
    })


def run_spmd(args) -> None:
    import jax
    import jax.numpy as jnp

    from distributed_gpu_inference_tpu.models import llama
    from distributed_gpu_inference_tpu.models.configs import get_model_config
    from distributed_gpu_inference_tpu.parallel.mesh import AXIS_STAGE
    from distributed_gpu_inference_tpu.parallel import pipeline as pp

    from jax.sharding import Mesh

    # resolve --platform BEFORE the first jax.devices() call — touching the
    # backend first would initialize the plugin-pinned platform and make the
    # flag a no-op. spmd defaults to the CPU-scale model (virtual mesh).
    _, model = resolve_backend_model(args, tpu_default="llama3-mini")
    cfg = get_model_config(model)
    devices = jax.devices()
    if len(devices) < args.stages:
        raise SystemExit(
            f"spmd mode needs >= {args.stages} devices (have {len(devices)}); "
            "set XLA_FLAGS=--xla_force_host_platform_device_count=N"
        )
    mesh = Mesh(
        np.asarray(devices[: args.stages]).reshape(args.stages), (AXIS_STAGE,)
    )
    params = pp.shard_params_stages(
        llama.init_params(cfg, jax.random.PRNGKey(0), "float32"), mesh
    )
    n_micro, mb, s = args.microbatches, args.microbatch_size, args.prompt_len
    max_blocks = -(-(s + 4) // 16)
    num_blocks = 1 + n_micro * mb * max_blocks
    kv = pp.shard_kv_stages(
        llama.init_kv_pools(cfg, num_blocks, 16, jnp.float32), mesh
    )
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, cfg.vocab_size, (n_micro, mb, s)).astype(np.int32)
    positions = np.tile(np.arange(s, dtype=np.int32), (n_micro, mb, 1))
    tables = np.zeros((n_micro, mb, max_blocks), np.int32)
    nb = 1
    for i in range(n_micro):
        for j in range(mb):
            tables[i, j] = np.arange(nb, nb + max_blocks)
            nb += max_blocks
    kv_lens = np.full((n_micro, mb), s, np.int32)

    def step():
        logits, new_kv = pp.pipelined_forward(
            cfg, params, jnp.asarray(tokens), jnp.asarray(positions), kv,
            jnp.asarray(tables), jnp.asarray(kv_lens), mesh,
        )
        jax.block_until_ready(logits)
        return new_kv

    step()  # warmup compile
    with Timer() as t:
        for _ in range(args.iters):
            step()
    total_tokens = args.iters * n_micro * mb * s
    emit({
        "benchmark": "distributed_pipeline",
        "mode": "spmd",
        "metric": "pipeline_prefill_tokens_per_s",
        "value": round(total_tokens / t.elapsed, 2),
        "unit": "tokens/s",
        "model": model,
        "stages": args.stages,
        "microbatches": n_micro,
        "microbatch_size": mb,
        "seq_len": s,
        "iters": args.iters,
        "elapsed_s": round(t.elapsed, 3),
    })


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("http", "spmd"), default="http")
    ap.add_argument("--model", default=None)
    ap.add_argument("--stages", type=int, default=2)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--max-tokens", type=int, default=32)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--microbatch-size", type=int, default=2)
    ap.add_argument("--iters", type=int, default=4)
    add_platform_arg(ap)
    args = ap.parse_args()
    if args.mode == "http":
        run_http(args)
    else:
        run_spmd(args)


if __name__ == "__main__":
    main()
