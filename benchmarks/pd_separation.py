#!/usr/bin/env python
"""Prefill/decode disaggregation benchmark: hybrid vs separated, for real.

Parity with the reference's ``benchmarks/pd_separation.py`` metrics (TTFT and
TPOT, hybrid vs separated) — but the reference computes both from an analytic
roofline model (:182-225); here both configurations RUN:

- **hybrid**: one engine interleaves new prefills with ongoing decodes (the
  classic interference regime — a long prefill stalls every decode step).
- **separated**: a prefill engine and a decode engine; each finished prefill
  migrates its KV to the decode engine over the real export→wire→adopt path
  (``runtime/kv_handoff.py``), decodes run without prefill interference.

Usage:
    python -m benchmarks.pd_separation --requests 8 --prompt-len 128 \
        --max-tokens 32
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import (
    Timer,
    add_platform_arg,
    emit,
    make_request,
    percentiles,
    resolve_backend_model,
    synth_prompts,
)


def _mk_engine(model, batch, max_seq, params=None, prefill_buckets=(128,)):
    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )

    return TPUEngine(
        model,
        EngineConfig(
            max_batch_size=batch, max_seq_len=max_seq,
            prefill_buckets=prefill_buckets, enable_prefix_cache=False,
        ),
        params=params,
    )


def _req(p, max_tokens):
    return make_request(p, max_tokens)


_DECODE_T = 4  # decode scan length per round (amortizes host round-trips)


def _warm(eng, prompt):
    """Compile every graph the measured loops touch: the batched-wave
    prefill (generate), the single-request [1, bucket] prefill (submit),
    and the T-step decode scan — mid-measurement XLA compiles would
    otherwise dominate the percentiles."""
    eng.generate([_req(prompt, 2)])
    slot = eng.submit(_req(prompt, 3))
    while eng.slots[slot] is not None and \
            eng.slots[slot].finish_reason is None:
        eng.decode_multi(_DECODE_T)
    eng.finish_slot(slot, cache=False)


def _decode_round(eng, tpots):
    d0 = time.perf_counter()
    out = eng.decode_multi(_DECODE_T)
    if out:
        # normalize by the steps the round actually advanced (a slot can
        # finish mid-scan) — dividing by the fixed T would understate
        # per-token latency in tail rounds
        steps_run = max(len(v) for v in out.values())
        if steps_run:
            per_tok = (time.perf_counter() - d0) * 1000.0 / steps_run
            tpots.extend([per_tok] * steps_run)
    return out


def run_hybrid(model, prompts, args, params):
    """One engine, staggered arrivals: prefills interleave with decodes."""
    eng = _mk_engine(model, args.requests, args.max_seq, params,
                     (args.prompt_len,))
    _warm(eng, prompts[0])

    ttfts, tpots = [], []
    with Timer() as t:
        for p in prompts:
            # a new request arrives: prefill NOW (stalls ongoing decodes)
            t0 = time.perf_counter()
            eng.submit(_req(p, args.max_tokens))
            ttfts.append((time.perf_counter() - t0) * 1000.0)
            # run a few decode rounds for everyone between arrivals
            for _ in range(args.decode_per_arrival):
                _decode_round(eng, tpots)
        # drain
        while eng.num_active:
            _decode_round(eng, tpots)
            for i, s in enumerate(list(eng.slots)):
                if s is not None and s.finish_reason is not None:
                    eng.finish_slot(i)
    return ttfts, tpots, t.elapsed


def run_separated(model, prompts, args, params, migration="host"):
    """Prefill engine + decode engine + real KV migration between them.

    ``migration="host"``: export → serialize → deserialize → adopt (the
    DCN/cross-host wire path; on the tunneled bench chip this pays the
    tunnel's ~4 MB/s D2H rate).
    ``migration="device"``: ``migrate_kv_device`` — pages move pool→pool in
    one jitted gather-scatter, zero host bytes (the intra-slice PD path:
    prefill and decode pools of one process/slice, BASELINE config 5).
    """
    from distributed_gpu_inference_tpu.runtime.kv_handoff import (
        adopt_kv,
        deserialize_handoff,
        export_slot_kv,
        migrate_kv_device,
        serialize_handoff,
    )

    pre = _mk_engine(model, 2, args.max_seq, params, (args.prompt_len,))
    dec = _mk_engine(model, args.requests, args.max_seq, pre.params,
                     (args.prompt_len,))
    _warm(pre, prompts[0])
    _warm(dec, prompts[0])
    # warm the migration path (export/copy + adopt graphs)
    wslot = pre.submit(_req(prompts[0], 3))
    if migration == "device":
        aslot = migrate_kv_device(pre, dec, wslot)
        pre.finish_slot(wslot, cache=False)
    else:
        wire = serialize_handoff(export_slot_kv(pre, wslot))
        pre.finish_slot(wslot, cache=False)
        aslot = adopt_kv(dec, deserialize_handoff(wire))
    dec.finish_slot(aslot, cache=False)

    ttfts, tpots, migrate_ms = [], [], []
    migrate_bytes = 0
    with Timer() as t:
        pending = list(prompts)
        active = 0
        while pending or active:
            if pending:
                p = pending.pop(0)
                t0 = time.perf_counter()
                slot = pre.submit(_req(p, args.max_tokens))
                ttfts.append((time.perf_counter() - t0) * 1000.0)
                m0 = time.perf_counter()
                if migration == "device":
                    dslot = migrate_kv_device(pre, dec, slot)
                    # sync so migrate_ms covers the device copy, not just
                    # its dispatch (tunnel RTT) — same basis as host mode
                    np.asarray(dec.kv["k"][0, :1, 0, 0, 0])
                    migrate_bytes += 0
                    pre.finish_slot(slot, cache=False)
                else:
                    wire = serialize_handoff(export_slot_kv(pre, slot))
                    migrate_bytes += len(wire)
                    pre.finish_slot(slot, cache=False)
                    adopt_kv(dec, deserialize_handoff(wire))
                migrate_ms.append((time.perf_counter() - m0) * 1000.0)
                active += 1
            # decode pool advances independently of prefill arrivals
            for _ in range(args.decode_per_arrival):
                _decode_round(dec, tpots)
            for i, s in enumerate(list(dec.slots)):
                if s is not None and s.finish_reason is not None:
                    dec.finish_slot(i)
                    active -= 1
            if not pending and not dec.num_active:
                break
    return ttfts, tpots, migrate_ms, migrate_bytes, t.elapsed


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--max-tokens", type=int, default=32)
    ap.add_argument("--decode-per-arrival", type=int, default=4)
    ap.add_argument("--migration", default="device",
                    choices=("host", "device", "both"),
                    help="separated-pool KV migration path: host = "
                         "serialize/wire (DCN shape), device = pool→pool "
                         "jitted copy (intra-slice shape)")
    add_platform_arg(ap)
    args = ap.parse_args()

    import jax

    backend, model = resolve_backend_model(args)
    args.max_seq = args.prompt_len + args.max_tokens + 16

    from distributed_gpu_inference_tpu.models import llama
    from distributed_gpu_inference_tpu.models.configs import get_model_config

    cfg = get_model_config(model)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompts = synth_prompts(args.requests, args.prompt_len, cfg.vocab_size)

    hy_ttft, hy_tpot, hy_s = run_hybrid(model, prompts, args, params)
    modes = ["host", "device"] if args.migration == "both" \
        else [args.migration]
    sep_out = {}
    for mode in modes:
        sep_ttft, sep_tpot, mig_ms, mig_bytes, sep_s = run_separated(
            model, prompts, args, params, migration=mode
        )
        sep_out[mode] = {
            "ttft_ms": percentiles(sep_ttft),
            "tpot_ms": percentiles(sep_tpot),
            "migration_ms": percentiles(mig_ms),
            "migration_mb": round(mig_bytes / 1e6, 2),
            "migration_mb_s": round(
                (mig_bytes / 1e6) / (sum(mig_ms) / 1e3), 2
            ) if mig_ms and sum(mig_ms) and mig_bytes else None,
            "elapsed_s": round(sep_s, 3),
        }

    hy = percentiles(hy_tpot)
    best = sep_out.get("device") or sep_out[modes[0]]
    sep = best["tpot_ms"]
    emit({
        "benchmark": "pd_separation",
        "metric": "decode_tpot_p95_improvement",
        "value": round(hy["p95"] / sep["p95"], 3)
        if hy["p95"] and sep["p95"] else None,
        "unit": "x (hybrid p95 TPOT / separated p95 TPOT)",
        "model": model,
        "backend": backend,
        "requests": args.requests,
        "prompt_len": args.prompt_len,
        "max_tokens": args.max_tokens,
        "hybrid": {
            "ttft_ms": percentiles(hy_ttft),
            "tpot_ms": hy,
            "elapsed_s": round(hy_s, 3),
        },
        **{f"separated_{m}": v for m, v in sep_out.items()},
        # both pools share ONE chip here, so device work serializes and the
        # TPOT comparison cannot show disaggregation's full benefit — on a
        # real deployment the pools run on disjoint slice partitions
        # (BASELINE.json config 5: v5e-64); what this measures for real is
        # the migration path cost (device copy vs export → wire → adopt)
        "single_chip_note": "pools share one device; see migration_*",
    })


if __name__ == "__main__":
    main()
