#!/usr/bin/env python
"""Focused PD KV-handoff benchmark: blocking vs streamed vs device migration.

Measures the DECODE-READY DELAY — first token sampled on the prefill engine
→ sequence adopted and resumable on the decode engine — for the three
migration paths (VERDICT r3 #3):

- **blocking**: the round-3 one-shot path — export every page, pull to host,
  serialize, one POST over the real data plane, adopt. The whole cost lands
  after prefill.
- **streamed**: ``StreamedExport`` begin/piece/commit over the same data
  plane — pages cross the wire while later prefill chunks compute (the
  donor uses a small prefill bucket so a 512-token prompt spans chunks);
  only the tail piece + commit remain after the first token samples. Runs
  the PRODUCT path (``TPULLMEngine.pd_prefill`` with its sender thread).
- **device**: ``migrate_kv_device`` — pool→pool jitted gather-scatter for
  same-chip/same-slice pools; zero host bytes (the intra-slice shape,
  BASELINE config 5).

Reference contrast: its migration body is a 50 ms sleep
(``server/app/services/pd_scheduler.py:462-472``); the per-layer transfer
proto (:121-127) is never wired.

Usage:
    python -m benchmarks.pd_handoff --prompt-len 512 --reps 3
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import (
    add_platform_arg,
    emit,
    make_request,
    percentiles,
    resolve_backend_model,
)


class _StubStage:
    def health(self):
        return {"status": "ok", "role": "pd-handoff-bench"}


def _mk_engine(model, batch, max_seq, buckets, quant=None, params=None,
               cache_dir=None, kv_dtype=None):
    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )

    return TPUEngine(
        model,
        EngineConfig(
            max_batch_size=batch, max_seq_len=max_seq,
            prefill_buckets=buckets, enable_prefix_cache=False,
            quantization=quant, quant_cache_dir=cache_dir,
            kv_cache_dtype=kv_dtype,
            # every byte-width KV dtype needs 32-token pages on TPU
            block_size=32 if kv_dtype in
            ("int8", "fp8", "float8_e4m3fn") else 16,
        ),
        params=params,
    )


def _wrap(engine):
    """A TPULLMEngine with an injected engine (shared weights between the
    donor and receiver wrappers — two independent loads would not fit two
    8B trees on one chip)."""
    from distributed_gpu_inference_tpu.worker.engines.llm import (
        ByteTokenizer,
        TPULLMEngine,
    )

    w = TPULLMEngine({})
    w.engine = engine
    w.tokenizer = ByteTokenizer()
    w.loaded = True
    return w


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--prompt-len", type=int, default=512)
    ap.add_argument("--max-tokens", type=int, default=4)
    ap.add_argument("--prefill-bucket", type=int, default=128,
                    help="donor prefill bucket (chunks per prompt = "
                         "prompt_len / bucket — what streaming overlaps)")
    ap.add_argument("--piece-blocks", type=int, default=4)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--kv-dtype", default=None,
                    help="int8: both pools quantized — handoffs move ~40%% "
                         "fewer bytes (int8 pages + bf16 scale pages vs "
                         "bf16 pages), which directly shrinks the host "
                         "path's D2H + wire time")
    add_platform_arg(ap)
    args = ap.parse_args()

    import jax

    backend, model = resolve_backend_model(
        args, tpu_default="llama3-8b", cpu_default="llama3-tiny"
    )
    quant = "int8" if model == "llama3-8b" else None
    cache_dir = str(Path(__file__).resolve().parent.parent / ".cache" /
                    "quant") if quant else None
    max_seq = args.prompt_len + args.max_tokens + 32

    from distributed_gpu_inference_tpu.runtime.kv_handoff import (
        export_slot_kv,
        migrate_kv_device,
        serialize_handoff,
    )
    from distributed_gpu_inference_tpu.comm.data_plane import DataPlaneServer
    from distributed_gpu_inference_tpu.models.configs import get_model_config

    cfg = get_model_config(model)
    donor = _mk_engine(model, 2, max_seq, (args.prefill_bucket,),
                       quant, cache_dir=cache_dir, kv_dtype=args.kv_dtype)
    recv = _mk_engine(model, 2, max_seq, (args.prefill_bucket,),
                      None, params=donor.params, kv_dtype=args.kv_dtype)
    donor_w, recv_w = _wrap(donor), _wrap(recv)

    plane = DataPlaneServer(_StubStage(), host="127.0.0.1", port=0,
                            kv_receiver=recv_w.kv_receiver)
    plane.start()
    url = f"http://127.0.0.1:{plane.bound_port}"

    rng = np.random.default_rng(0)

    def prompt():
        return rng.integers(1, cfg.vocab_size, args.prompt_len).tolist()

    import httpx

    def run_blocking():
        req = make_request(prompt(), args.max_tokens)
        req.session_id = f"blk-{req.request_id}"
        slot = donor.submit(req)
        t0 = time.perf_counter()
        raw = serialize_handoff(export_slot_kv(donor, slot))
        donor.finish_slot(slot, cache=False)
        r = httpx.post(url + "/kv/transfer", content=raw, timeout=300.0)
        r.raise_for_status()
        ms = (time.perf_counter() - t0) * 1000.0
        _drain(r.json()["slot"])
        return ms, len(raw), 0

    def run_streamed():
        req_ids = prompt()
        out = donor_w.pd_prefill({
            "prompt_token_ids": req_ids,
            "max_new_tokens": args.max_tokens,
            "kv_cache_key": f"st-{time.monotonic_ns()}",
            "decode_url": url,
            "decode_worker": "w2", "target_worker": "w1",
            "pd_stream": True,
            "pd_stream_piece_blocks": args.piece_blocks,
        })
        assert out.get("pd_streamed"), "streamed path did not engage"
        _drain(out["decode_slot"])
        return (out["migration_ms"], out["migration_bytes"],
                out["bytes_before_first_token"])

    def run_device():
        req = make_request(prompt(), args.max_tokens)
        slot = donor.submit(req)
        t0 = time.perf_counter()
        dslot = migrate_kv_device(donor, recv, slot)
        # sync: the copy must have EXECUTED, not just dispatched
        np.asarray(recv.kv["k"][0, :1, 0, 0, 0])
        ms = (time.perf_counter() - t0) * 1000.0
        donor.finish_slot(slot, cache=False)
        _drain(dslot)
        return ms, 0, 0

    def _drain(slot):
        while recv.slots[slot] is not None and \
                recv.slots[slot].finish_reason is None:
            recv.decode_multi(4)
        recv.finish_slot(slot, cache=False)

    # warm every graph + wire path once
    for fn in (run_blocking, run_streamed, run_device):
        fn()

    results = {}
    for name, fn in (("blocking", run_blocking), ("streamed", run_streamed),
                     ("device", run_device)):
        ms, mb, early = [], 0, 0
        for _ in range(args.reps):
            m, b, e = fn()
            ms.append(m)
            mb = b
            early = e
        results[name] = {
            "migration_ms": percentiles(ms),
            "wire_mb": round(mb / 1e6, 2),
            "bytes_before_first_token_mb": round(early / 1e6, 2),
        }
    plane.stop()

    blk = results["blocking"]["migration_ms"]["p50"]
    emit({
        "benchmark": "pd_handoff",
        "metric": "migration_p50_cut_vs_blocking",
        "value": {
            "streamed": round(
                100 * (1 - results["streamed"]["migration_ms"]["p50"] / blk),
                1),
            "device": round(
                100 * (1 - results["device"]["migration_ms"]["p50"] / blk),
                1),
        },
        "unit": "% decode-ready delay cut (p50)",
        "model": model,
        "backend": backend,
        "quantization": quant,
        "prompt_len": args.prompt_len,
        "prefill_bucket": args.prefill_bucket,
        "piece_blocks": args.piece_blocks,
        "kv_cache_dtype": args.kv_dtype,
        **results,
    })


if __name__ == "__main__":
    main()
